"""Opportunistic TPU evidence capture.

The remote-TPU tunnel in this environment flaps: it can be down at the one
moment the driver runs ``bench.py`` and up during an ordinary test or CLI
run minutes earlier.  The reference never had this problem (local GPU,
reference MapReduce/src/main.cu:393) — its published numbers were captured
interactively.  Ours must be captured *whenever the hardware happens to be
reachable*, from ANY entrypoint.

``record(kind, payload)`` appends one JSON line to
``artifacts/tpu_runs.jsonl`` (repo-root relative, overridable via
``$LOCUST_ARTIFACTS_DIR``) **iff this process is actually on a TPU
backend**.  On CPU it is a no-op, so call sites sprinkle it freely:

  * ``bench.py`` — stage timings + MB/s of every TPU bench run,
  * ``locust_tpu/cli.py`` — stage report of every TPU CLI run,
  * ``scripts/tpu_checks.py`` / ``scripts/bench_sort_variants.py`` —
    kernel A/B and sort-variant numbers,
  * the TPU-gated pytest checks.

Each row self-describes: timestamp, jax version, device kind, plus the
caller's payload.  Append-only JSONL with a same-filesystem atomic write
per line (O_APPEND) — concurrent writers (bench retry loop + a test run)
interleave whole lines, never torn ones.
"""

from __future__ import annotations

import json
import os
import time

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "artifacts",
)


def artifacts_dir() -> str:
    return os.environ.get("LOCUST_ARTIFACTS_DIR", _DEFAULT_DIR)


# Ledger kinds whose rows DRIVE bench.py's evidence-tuned configuration
# (bench._evidence_tuned_tpu_defaults reads exactly these).  Shared here
# (jax-free) so the farm loop's bench-staleness check and bench's tuning
# can never drift: a kind added to one but not the other either leaves
# the committed headline stale or burns windows re-running an unchanged
# config.  emits_per_line_ab / key_width_ab are deliberately absent —
# they are verification phases; bench auto-sizes caps from the corpus.
CONFIG_AB_KINDS = (
    "engine_sort_mode_ab",
    "block_lines_ab",
    "engine_table_ab",
    "engine_pallas_ab",
)

# Bench sub-dict -> evidence-ledger row kind for the guarded non-headline
# benches (two-sided, same discipline as CONFIG_AB_KINDS): bench.py's
# sub-dict producer table must match these KEYS exactly (checked with a
# loud identity error at bench time), and every recorder of one of these
# KINDS imports the string from here instead of re-spelling it — a
# sub-dict added without a ledger kind, or a kind recorded that no bench
# sub-dict reports, fails loudly instead of silently drifting.  The
# "stream" sub-dict is deliberately absent: its evidence lands in
# dedicated per-round files (artifacts/stream_*.jsonl), not ledger rows.
BENCH_SUBDICT_KINDS = {
    "dataplane": "dataplane_bench",
    "serve": "serve_bench",
    "recovery": "recovery_bench",
    "plan": "plan_bench",
}


def ledger_rows(path: str | None = None) -> list[dict]:
    """Parsed rows of the evidence ledger (malformed lines skipped).

    The single ledger reader: the farm loop's harvest schedule, the
    sweep's phase skips, and bench's evidence tuning all decide off this
    file, and it is appended by concurrent processes and merged across
    machines via git — every consumer must treat it as untrusted,
    per-line.  One shared copy so a hardening fix can't miss a caller.

    ``path`` pins an explicit ledger file; default is the live
    ``artifacts_dir()`` ledger.  Callers whose WRITES are pinned (the
    farm loop git-commits the repo ledger) must pin their reads to the
    same file or the two silently diverge under $LOCUST_ARTIFACTS_DIR.
    """
    rows: list[dict] = []
    try:
        # errors="replace": a torn binary write or merge artifact must
        # cost ONE line (json.loads rejects the U+FFFD), not the whole
        # scan — UnicodeDecodeError from line iteration would otherwise
        # escape the per-line guard and kill the farm supervisor.
        with open(
            path or os.path.join(artifacts_dir(), "tpu_runs.jsonl"),
            encoding="utf-8",
            errors="replace",
        ) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if isinstance(r, dict):
                    rows.append(r)
    except OSError:
        pass
    return rows


def latest_row_ts(
    kind: str, backend: str = "tpu", where=None, path: str | None = None
) -> float:
    """Newest ``ts`` among ledger rows of ``kind``/``backend`` that also
    satisfy the optional ``where`` predicate.  Rows with missing or
    malformed ``ts`` (ledger is multi-writer, git-merged) are skipped,
    never raised on — one bad line must not cost a tunnel window."""
    ts = 0.0
    for r in ledger_rows(path):
        if r.get("kind") != kind or r.get("backend") != backend:
            continue
        if where is not None:
            try:
                if not where(r):
                    continue
            except Exception:  # locust: noqa[R017] malformed multi-writer ledger rows are skipped by contract (docstring above); per-row logging would spam every sweep over a git-merged ledger
                continue
        try:
            ts = max(ts, float(r.get("ts") or 0))
        except (TypeError, ValueError):
            continue
    return ts


_CODE_FP: str | None = None


def code_fingerprint() -> str:
    """Hash of the measurement-relevant package code: core/ops/parallel/
    io trees plus engine/config/backend.  Evidence rows are stamped with
    it so session-resume logic can tell "same code, reusable
    measurement" from "the compute path changed mid-session, re-measure"
    — a wall-clock floor alone cannot (a carried stale side would steer
    bench's evidence tuning with numbers from two code versions).
    Measurement IMPLEMENTATIONS outside the package are in the hash too:
    the variant kernels (scripts/bench_sort_variants.py), the check
    battery (scripts/tpu_checks.py), and bench.py's corpus/config policy
    — editing a measured kernel must invalidate its rows.  utils/ and
    the orchestration scripts (farm loop, sweep drivers) stay OUTSIDE:
    ledger/scheduling changes do not alter what a measurement means, and
    including them would invalidate same-code evidence on every
    instrumentation commit.  Paths hashed relative to the repo so the
    fingerprint is machine-portable."""
    global _CODE_FP
    if _CODE_FP is None:
        import hashlib

        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        repo = os.path.dirname(pkg)
        files: list[str] = []
        for d in ("core", "ops", "parallel", "io"):
            for root, _, names in os.walk(os.path.join(pkg, d)):
                files.extend(
                    os.path.join(root, n) for n in names if n.endswith(".py")
                )
        files.extend(
            os.path.join(pkg, n)
            for n in ("engine.py", "config.py", "backend.py")
        )
        files.extend(
            os.path.join(repo, p)
            for p in ("bench.py",
                      os.path.join("scripts", "bench_sort_variants.py"),
                      os.path.join("scripts", "tpu_checks.py"),
                      # opp_resume holds the engine-A/B timing methodology
                      # (rep counts, warm/compile boundary) — editing it
                      # changes what a row's numbers MEAN, so it must
                      # invalidate them, even though it also carries
                      # orchestration whose edits are harmless.
                      os.path.join("scripts", "opp_resume.py"))
        )
        h = hashlib.sha1()
        for p in sorted(files):
            try:
                with open(p, "rb") as f:
                    h.update(os.path.relpath(p, repo).encode())
                    h.update(b"\0")
                    h.update(f.read())
                    h.update(b"\0")
            except OSError:
                continue
        _CODE_FP = h.hexdigest()[:12]
    return _CODE_FP


def on_tpu() -> bool:
    """True iff jax is initialized on a non-CPU backend.

    Never *triggers* backend init: probing here could hang on a wedged
    tunnel, which is exactly what locust_tpu.backend exists to prevent.
    """
    try:
        import jax
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return False
        return jax.default_backend() not in ("cpu", "interpreter")
    except Exception:  # locust: noqa[R017] any failure to introspect jax state means "not on TPU" — False IS the answer here, not an error to surface
        return False


def record(kind: str, payload: dict, force: bool = False) -> bool:
    """Append one evidence row if on TPU (or ``force``).  Returns written?"""
    if not force and not on_tpu():
        return False
    try:
        import jax

        row = {
            "ts": round(time.time(), 1),
            "kind": kind,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0].device_kind)
            if jax.devices()
            else "unknown",
            "jax": jax.__version__,
            "code": code_fingerprint(),
            **payload,
        }
    except Exception as e:  # pragma: no cover - evidence must never break a run
        row = {"ts": round(time.time(), 1), "kind": kind, "error": str(e), **payload}
    try:
        d = artifacts_dir()
        os.makedirs(d, exist_ok=True)
        line = json.dumps(row, default=str) + "\n"
        fd = os.open(
            os.path.join(d, "tpu_runs.jsonl"),
            os.O_WRONLY | os.O_CREAT | os.O_APPEND,
            0o644,
        )
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
        return True
    except OSError:  # pragma: no cover - best-effort by design
        return False
