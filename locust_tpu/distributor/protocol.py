"""Wire protocol for the distributor control plane.

The reference's protocol is: send a whitespace-separated string, the slave
executes ``cmd[1:]`` as an arbitrary local command and replies "ACK"
(reference Distributor/slave.py:16-32) — unauthenticated remote code
execution (SURVEY.md Q8).  Replaced with:

  * length-prefixed JSON frames (no recv(1024) truncation — slave.py:16
    silently cuts long commands),
  * HMAC-SHA256 request authentication over a shared secret,
  * a closed command whitelist (no shell),
  * structured replies carrying the subprocess exit status (the reference
    ACKs unconditionally and discards the return code — slave.py:19-20,32).

Two frame types share the 4-byte length prefix (docs/DATAPLANE.md):

  * JSON frames — the control plane: every request and every small reply.
    Self-describing, debuggable, and what pre-binary peers speak.
  * BINARY frames (v1) — the data plane: bulk fetch replies as
    header + raw-digest MAC + small JSON meta + RAW payload bytes.  No
    base64 (the JSON path inflates payloads 4/3 on the wire), optional
    per-chunk zlib.  A receiver tells them apart by the first body byte:
    binary frames start with NUL, which no JSON document can.

Negotiated per-connection: a requester that wants binary data replies
says so in its (JSON) request; a peer that doesn't understand simply
ignores the unknown keys and answers JSON — old masters and old workers
interoperate with new ones in both directions.
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import json
import os
import socket
import struct
import time
import zlib

from locust_tpu import obs
from locust_tpu.utils import faultplan

MAX_FRAME = 64 * 1024 * 1024  # hard frame bound; fetch stays far below it

# Cross-node trace correlation (docs/OBSERVABILITY.md): requests carry an
# optional {"id": trace_id, "shard": n} dict under this key, workers run
# the command under a request-scoped tracer with that id and ship their
# span list back in the reply ("spans" + "clock"); binary fetch replies
# echo the id in the frame meta as "trace_id".  Peers that predate the
# key simply ignore it — same negotiation stance as the binary plane.
TRACE_KEY = "trace"


def trace_stamp(shard: int | None = None) -> dict | None:
    """The correlation stamp for an outgoing request: the active
    tracer's trace_id (+ the shard id for map requests), or None when
    telemetry is disabled (the request then carries no trace key at
    all — zero wire cost on the default path)."""
    t = obs.current()
    if t is None:
        return None
    stamp = {"id": t.trace_id}
    if shard is not None:
        stamp["shard"] = shard
    return stamp

# fetch window sizing: intermediates larger than one frame stream in
# offset-addressed chunks (VERDICT r2 missing #6).  Raw bytes per chunk;
# base64 expands 4/3, so even the max chunk is well under MAX_FRAME.
FETCH_CHUNK = 8 * 1024 * 1024
FETCH_CHUNK_MAX = 32 * 1024 * 1024

# serve_batch/serve_stats are the serve tier's scale-out dispatch
# surface (serve/pool.py -> worker.py): a worker started WITHOUT
# --serve answers them with a structured error, and pre-serve workers
# fall off the same "unknown command" path — both read as a failed
# placement the daemon's local engine absorbs.  plan_stage is the
# distributed-plan stage surface (plan/distribute.py, docs/PLAN.md
# "Distributed execution"): one map split fold or one shuffle-partition
# reduce per RPC, epoch-fenced like serve_batch.
COMMANDS = ("ping", "map", "fetch", "serve_batch", "serve_stats",
            "plan_stage", "shutdown")

# High-availability control plane (serve/replicate.py, docs/SERVING.md
# "High availability"): the primary serve daemon ships its fsync'd WAL
# records to a hot standby over this same authenticated frame protocol.
# ship       = a sequence-numbered batch of journal records (+ heartbeat
#              when empty); ship_catchup = a full live-journal snapshot
#              for a standby that connected late or fell behind;
#              ship_spill = one content-addressed corpus spill, pulled
#              on demand by sha reference.
SHIP_COMMANDS = ("ship", "ship_catchup", "ship_spill")

# Fencing epoch: every shipped record and every pool-worker RPC carries
# the sender's promotion epoch under this key.  Receivers track the
# highest epoch seen (EpochGuard) and reject lower ones with a
# structured ``stale_epoch`` — a partitioned old primary can never have
# its dispatches or ships honored after a standby promotes past it.
EPOCH_KEY = "_epoch"


class EpochGuard:
    """Monotone fencing-epoch tracker (one per receiving process).

    Thread-safe: serve_batch handlers and ship appliers run on
    concurrent connection threads, so the high-water mark mutates
    under a lock.
    """

    def __init__(self):
        import threading

        self._highest = 0
        self._lock = threading.Lock()

    def observe(self, epoch) -> int | None:
        """Record ``epoch``; returns None when it is current (>= the
        highest seen, which it then becomes), else the higher epoch
        already observed — the caller answers a structured
        ``stale_epoch`` naming it, never silently obeys a fenced-out
        sender."""
        e = int(epoch)
        with self._lock:
            if e < self._highest:
                return self._highest
            self._highest = e
            return None

    def highest(self) -> int:
        with self._lock:
            return self._highest

# Replay window: frames older than this are rejected; nonces are remembered
# for at least this long (worker side).
REPLAY_WINDOW_SECS = 120.0

# ---------------------------------------------------------- binary framing
# Body layout (after the shared 4-byte length prefix):
#   0   3  BIN_MAGIC  b"\x00LB"  (NUL first: cannot begin a JSON document)
#   3   1  version    (known: 1; anything else -> ProtocolError)
#   4   1  flags      (bit 0: payload is zlib-compressed)
#   5   1  reserved   (0)
#   6   2  meta_len   (!H)
#   8  32  mac        raw HMAC-SHA256 over version..reserved + meta + payload
#  40   m  meta       JSON dict (status/offset/total/eof/sha256/...)
#  40+m    payload    raw bytes (zlib stream if FLAG_ZLIB)
BIN_MAGIC = b"\x00LB"
BIN_VERSION = 1
FLAG_ZLIB = 0x01
_BIN_HEADER = struct.Struct("!3sBBBH32s")


class ProtocolError(ValueError):
    """Malformed/unsupported frame content (not an auth failure)."""


class FrameTooLarge(ProtocolError):
    """A frame body exceeding MAX_FRAME.  Structured: carries the exact
    size and limit so callers can chunk instead of parsing a message."""

    def __init__(self, size: int, limit: int = 0):
        self.size = int(size)
        self.limit = int(limit or MAX_FRAME)
        super().__init__(
            f"frame body of {self.size} bytes exceeds MAX_FRAME="
            f"{self.limit} by {self.size - self.limit}; chunk the transfer"
        )


def _mac(secret: bytes, payload: bytes) -> str:
    return hmac.new(secret, payload, hashlib.sha256).hexdigest()


def _mac_raw(secret: bytes, payload: bytes) -> bytes:
    return hmac.new(secret, payload, hashlib.sha256).digest()


def send_frame(
    sock: socket.socket, obj: dict, secret: bytes, sign_fresh: bool = True
) -> None:
    """Send one authenticated frame.

    ``sign_fresh`` stamps a timestamp + random nonce under the MAC so a
    recorded frame cannot be replayed later (requests); replies ride the
    same connection and skip the stamp.
    """
    if sign_fresh:
        obj = dict(obj, _ts=time.time(), _nonce=os.urandom(12).hex())
    payload = json.dumps(obj, sort_keys=True).encode()
    frame = json.dumps({"mac": _mac(secret, payload)}).encode() + b"\n" + payload
    if len(frame) > MAX_FRAME:
        raise FrameTooLarge(len(frame))
    wire = struct.pack("!I", len(frame)) + frame
    # Chaos: wire corruption/truncation (no-op without an active plan).
    # The 4-byte length header is preserved — a corrupted frame BODY is
    # caught by the HMAC (rejected, connection dropped) and a truncated
    # one by the receiver's bounded read timeout; both are the failure
    # modes the retry path must absorb (tests/test_faults.py).
    wire = faultplan.mangle(
        "rpc.frame", wire, keep_prefix=4, cmd=obj.get("cmd")
    )
    sock.sendall(wire)


def send_bin_frame(
    sock: socket.socket,
    meta: dict,
    payload: bytes,
    secret: bytes,
    compress: bool = False,
) -> int:
    """Send one authenticated BINARY frame (data plane).

    ``payload`` goes on the wire raw — no base64 — optionally through one
    per-frame zlib stream (``compress``; skipped when it doesn't shrink,
    which the receiver sees via the flags bit, not a meta field).  Binary
    frames are replies riding an already-authenticated request's
    connection, so like JSON replies they carry no freshness stamp; the
    MAC still covers header+meta+payload.  Returns bytes on the wire
    (length prefix included) so callers can account traffic exactly.
    """
    flags = 0
    body = payload
    if compress and payload:
        packed = zlib.compress(payload, 1)
        if len(packed) < len(payload):
            body, flags = packed, FLAG_ZLIB
    return send_bin_frame_encoded(sock, meta, body, secret, flags)


def send_bin_frame_encoded(
    sock: socket.socket,
    meta: dict,
    body: bytes,
    secret: bytes,
    flags: int = 0,
) -> int:
    """Low-level binary send: ``body`` goes on the wire as-is, ``flags``
    declares its encoding.  Split out so the worker can compress (and the
    chaos harness can mangle the ENCODED payload, io.chunk) before the
    frame is MAC'd — the MAC always covers the wire bytes."""
    meta_b = json.dumps(meta, sort_keys=True).encode()
    if len(meta_b) > 0xFFFF:
        raise ProtocolError(f"binary frame meta of {len(meta_b)} bytes > 64KiB")
    signed = bytes((BIN_VERSION, flags, 0)) + meta_b + body
    mac = _mac_raw(secret, signed)
    frame = (
        _BIN_HEADER.pack(BIN_MAGIC, BIN_VERSION, flags, 0, len(meta_b), mac)
        + meta_b
        + body
    )
    if len(frame) > MAX_FRAME:
        raise FrameTooLarge(len(frame))
    wire = struct.pack("!I", len(frame)) + frame
    wire = faultplan.mangle(
        "rpc.frame", wire, keep_prefix=4, cmd=meta.get("cmd", "fetch-data")
    )
    sock.sendall(wire)
    return len(wire)


@dataclasses.dataclass
class FrameIn:
    """One received frame, either kind, plus wire accounting.

    ``obj`` is the JSON document (JSON frame) or the meta dict (binary
    frame); ``payload`` is the decompressed raw payload (binary frames
    only, None for JSON); ``wire_bytes`` counts the length prefix too.
    """

    obj: dict
    payload: bytes | None
    wire_bytes: int
    binary: bool
    compressed: bool


def recv_frame_ex(sock: socket.socket, secret: bytes) -> FrameIn:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack("!I", header)
    if length > MAX_FRAME:
        raise FrameTooLarge(length)
    frame = _recv_exact(sock, length)
    if frame[:1] == b"\x00":
        return _parse_bin_frame(frame, secret, wire_bytes=length + 4)
    mac_line, _, payload = frame.partition(b"\n")
    try:
        mac = json.loads(mac_line)["mac"]
    except (ValueError, TypeError, KeyError):
        raise PermissionError("malformed auth header — rejecting frame")
    if not isinstance(mac, str) or not hmac.compare_digest(
        mac, _mac(secret, payload)
    ):
        raise PermissionError("bad HMAC — rejecting frame")
    return FrameIn(
        obj=json.loads(payload),
        payload=None,
        wire_bytes=length + 4,
        binary=False,
        compressed=False,
    )


def recv_frame(sock: socket.socket, secret: bytes) -> dict:
    """JSON-view receive (control plane): the frame's dict, either kind."""
    return recv_frame_ex(sock, secret).obj


def _parse_bin_frame(frame: bytes, secret: bytes, wire_bytes: int) -> FrameIn:
    if len(frame) < _BIN_HEADER.size:
        raise ProtocolError(
            f"binary frame of {len(frame)} bytes shorter than the "
            f"{_BIN_HEADER.size}-byte header"
        )
    magic, version, flags, reserved, meta_len, mac = _BIN_HEADER.unpack(
        frame[: _BIN_HEADER.size]
    )
    if magic != BIN_MAGIC:
        raise ProtocolError(f"bad binary frame magic {magic!r}")
    if version != BIN_VERSION:
        # Version skew is a STRUCTURED error, never a misparse: a v2
        # sender against this v1 receiver must fail loudly here.
        raise ProtocolError(
            f"unsupported binary frame version {version} (speak {BIN_VERSION})"
        )
    rest = frame[_BIN_HEADER.size :]
    if meta_len > len(rest):
        raise ProtocolError(
            f"binary frame meta_len {meta_len} exceeds body ({len(rest)}B)"
        )
    meta_b, body = rest[:meta_len], rest[meta_len:]
    signed = bytes((version, flags, reserved)) + meta_b + body
    if not hmac.compare_digest(mac, _mac_raw(secret, signed)):
        raise PermissionError("bad HMAC — rejecting binary frame")
    compressed = bool(flags & FLAG_ZLIB)
    if compressed:
        try:
            # Bounded decompression: MAX_FRAME is a RESOURCE bound, and a
            # <64MiB body of compressed zeros could otherwise expand to
            # tens of GiB (zlib ~1000:1) before anyone checks anything.
            # Valid payloads fit a frame uncompressed, so cap the output.
            d = zlib.decompressobj()
            out = d.decompress(body, MAX_FRAME + 1)
            if len(out) > MAX_FRAME or d.unconsumed_tail:
                raise ProtocolError(
                    "zlib payload decompresses beyond MAX_FRAME "
                    f"({MAX_FRAME}B) — rejecting frame"
                )
            if not d.eof:
                raise ProtocolError(
                    "corrupt zlib payload in binary frame: truncated stream"
                )
            body = out
        except zlib.error as e:
            # MAC passed, so the sender compressed garbage (e.g. a fault
            # injected before framing): structured, attributable error.
            raise ProtocolError(f"corrupt zlib payload in binary frame: {e}")
    try:
        meta = json.loads(meta_b)
    except ValueError:
        raise ProtocolError("binary frame meta is not valid JSON")
    if not isinstance(meta, dict):
        raise ProtocolError("binary frame meta must be a JSON object")
    return FrameIn(
        obj=meta,
        payload=body,
        wire_bytes=wire_bytes,
        binary=True,
        compressed=compressed,
    )


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()  # linear-time accumulation (frames can be ~64MB TSVs)
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


class ReplayGuard:
    """Worker-side freshness check: bounded-age timestamps + one-shot nonces.

    Thread-safe: the worker serves connections concurrently, so the nonce
    set is mutated under a lock.
    """

    def __init__(self, window: float = REPLAY_WINDOW_SECS):
        import threading

        self.window = window
        self._seen: dict[str, float] = {}
        self._lock = threading.Lock()

    def check(self, req: dict) -> None:
        now = time.time()
        ts = req.get("_ts")
        nonce = req.get("_nonce")
        if not isinstance(ts, (int, float)) or not isinstance(nonce, str):
            raise PermissionError("missing freshness stamp — rejecting frame")
        if abs(now - ts) > self.window:
            raise PermissionError("stale frame — rejecting (possible replay)")
        with self._lock:
            # Prune expired nonces, then enforce one-shot use.
            for n, t in list(self._seen.items()):
                if now - t > self.window:
                    del self._seen[n]
            if nonce in self._seen:
                raise PermissionError("nonce reuse — rejecting replayed frame")
            self._seen[nonce] = now


def parse_cluster_file(path: str) -> list[tuple[str, int]]:
    """Parse the reference's documented ``ip_address port`` cluster file
    (reference README.md:18-22) — the parser it never shipped (C12)."""
    nodes = []
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"bad cluster line (want 'ip port'): {raw!r}")
            nodes.append((parts[0], int(parts[1])))
    if not nodes:
        raise ValueError(f"cluster file {path!r} has no nodes")
    return nodes
