"""Wire protocol for the distributor control plane.

The reference's protocol is: send a whitespace-separated string, the slave
executes ``cmd[1:]`` as an arbitrary local command and replies "ACK"
(reference Distributor/slave.py:16-32) — unauthenticated remote code
execution (SURVEY.md Q8).  Replaced with:

  * length-prefixed JSON frames (no recv(1024) truncation — slave.py:16
    silently cuts long commands),
  * HMAC-SHA256 request authentication over a shared secret,
  * a closed command whitelist (no shell),
  * structured replies carrying the subprocess exit status (the reference
    ACKs unconditionally and discards the return code — slave.py:19-20,32).

This is the CONTROL plane only.  In the TPU framework the data plane is the
mesh all-to-all (parallel/shuffle.py); the distributor exists for CLI-stage
parity — fan out staged map runs, collect intermediate TSVs, reduce — i.e.
the role of the master script the reference documents but never shipped
(reference README.md:24, SURVEY.md C12).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import socket
import struct
import time

from locust_tpu.utils import faultplan

MAX_FRAME = 64 * 1024 * 1024  # hard frame bound; fetch stays far below it

# fetch window sizing: intermediates larger than one frame stream in
# offset-addressed chunks (VERDICT r2 missing #6).  Raw bytes per chunk;
# base64 expands 4/3, so even the max chunk is well under MAX_FRAME.
FETCH_CHUNK = 8 * 1024 * 1024
FETCH_CHUNK_MAX = 32 * 1024 * 1024

COMMANDS = ("ping", "map", "fetch", "shutdown")

# Replay window: frames older than this are rejected; nonces are remembered
# for at least this long (worker side).
REPLAY_WINDOW_SECS = 120.0


def _mac(secret: bytes, payload: bytes) -> str:
    return hmac.new(secret, payload, hashlib.sha256).hexdigest()


def send_frame(
    sock: socket.socket, obj: dict, secret: bytes, sign_fresh: bool = True
) -> None:
    """Send one authenticated frame.

    ``sign_fresh`` stamps a timestamp + random nonce under the MAC so a
    recorded frame cannot be replayed later (requests); replies ride the
    same connection and skip the stamp.
    """
    if sign_fresh:
        obj = dict(obj, _ts=time.time(), _nonce=os.urandom(12).hex())
    payload = json.dumps(obj, sort_keys=True).encode()
    frame = json.dumps({"mac": _mac(secret, payload)}).encode() + b"\n" + payload
    if len(frame) + 4 > MAX_FRAME:
        raise ValueError(
            f"frame of {len(frame)} bytes exceeds MAX_FRAME={MAX_FRAME}; "
            "chunk the transfer"
        )
    wire = struct.pack("!I", len(frame)) + frame
    # Chaos: wire corruption/truncation (no-op without an active plan).
    # The 4-byte length header is preserved — a corrupted frame BODY is
    # caught by the HMAC (rejected, connection dropped) and a truncated
    # one by the receiver's bounded read timeout; both are the failure
    # modes the retry path must absorb (tests/test_faults.py).
    wire = faultplan.mangle(
        "rpc.frame", wire, keep_prefix=4, cmd=obj.get("cmd")
    )
    sock.sendall(wire)


def recv_frame(sock: socket.socket, secret: bytes) -> dict:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack("!I", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    frame = _recv_exact(sock, length)
    mac_line, _, payload = frame.partition(b"\n")
    try:
        mac = json.loads(mac_line)["mac"]
    except (ValueError, TypeError, KeyError):
        raise PermissionError("malformed auth header — rejecting frame")
    if not isinstance(mac, str) or not hmac.compare_digest(
        mac, _mac(secret, payload)
    ):
        raise PermissionError("bad HMAC — rejecting frame")
    return json.loads(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()  # linear-time accumulation (frames can be ~64MB TSVs)
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


class ReplayGuard:
    """Worker-side freshness check: bounded-age timestamps + one-shot nonces.

    Thread-safe: the worker serves connections concurrently, so the nonce
    set is mutated under a lock.
    """

    def __init__(self, window: float = REPLAY_WINDOW_SECS):
        import threading

        self.window = window
        self._seen: dict[str, float] = {}
        self._lock = threading.Lock()

    def check(self, req: dict) -> None:
        now = time.time()
        ts = req.get("_ts")
        nonce = req.get("_nonce")
        if not isinstance(ts, (int, float)) or not isinstance(nonce, str):
            raise PermissionError("missing freshness stamp — rejecting frame")
        if abs(now - ts) > self.window:
            raise PermissionError("stale frame — rejecting (possible replay)")
        with self._lock:
            # Prune expired nonces, then enforce one-shot use.
            for n, t in list(self._seen.items()):
                if now - t > self.window:
                    del self._seen[n]
            if nonce in self._seen:
                raise PermissionError("nonce reuse — rejecting replayed frame")
            self._seen[nonce] = now


def parse_cluster_file(path: str) -> list[tuple[str, int]]:
    """Parse the reference's documented ``ip_address port`` cluster file
    (reference README.md:18-22) — the parser it never shipped (C12)."""
    nodes = []
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"bad cluster line (want 'ip port'): {raw!r}")
            nodes.append((parts[0], int(parts[1])))
    if not nodes:
        raise ValueError(f"cluster file {path!r} has no nodes")
    return nodes
