"""Loopback data-plane microbench: JSON/base64 vs binary framing.

Measures the distributor's fetch path variants against ONE in-process
worker over 127.0.0.1 (docs/DATAPLANE.md):

  * ``json_w1``   — the pre-binary path: one connection + one base64 JSON
                    chunk per request (PR 1's data plane, the baseline),
  * ``bin_w1``    — binary frames, raw payload, one chunk in flight,
  * ``bin_wK``    — binary frames, raw payload, K chunks pipelined,
  * ``bin_wK_z``  — binary frames, zlib payload, K chunks pipelined
                    (the default data plane).

The staged file is shaped like a real post-combine intermediate — packed
binary KV of sorted word keys with Zipf-ish counts (io/serde.py) — so the
compression ratio means something.  Pure host/socket work: no jax import,
safe under a wedged TPU tunnel, cheap enough for ``bench.py`` to embed a
row in its one-line JSON (the ``dataplane`` sub-dict).

``scripts/bench_dataplane.py`` is the CLI face; tests pin the result
schema (tests/test_dataplane.py).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile

from locust_tpu.distributor import master
from locust_tpu.distributor.worker import Worker
from locust_tpu.io import serde

VARIANTS = ("json_w1", "bin_w1", "bin_wK", "bin_wK_z")

# Per-variant fetch_file keyword overlays (window filled in at run time).
_VARIANT_KW = {
    "json_w1": dict(use_binary=False, use_zlib=False),
    "bin_w1": dict(use_binary=True, use_zlib=False),
    "bin_wK": dict(use_binary=True, use_zlib=False),
    "bin_wK_z": dict(use_binary=True, use_zlib=True),
}


def synth_intermediate(path: str, target_bytes: int) -> int:
    """Write a post-combine-shaped packed-KV file of ~``target_bytes``:
    sorted distinct word keys, Zipf-flavored int32 counts."""
    pairs = []
    approx = 0
    i = 0
    while approx < target_bytes:
        key = b"token%08d" % i
        pairs.append((key, 1 + (1_000_000 // (i + 1)) % 100_000))
        approx += len(key) + 6  # lens + value columns amortized
        i += 1
    serde.write_kvbin(pairs, path)
    return os.path.getsize(path)


def run_microbench(
    target_bytes: int = 4 << 20,
    # 64KiB chunks: small enough that the JSON path's per-request costs
    # (fresh TCP connection + HMAC + base64 round-trip) are visible, the
    # regime the pipelined path exists to kill (measured 2026-08-03:
    # ~3.1x at 64KiB vs ~1.9x at 32KiB on the CI host).
    chunk_bytes: int = 64 * 1024,
    window: int = 4,
    repeats: int = 3,
    secret: bytes = b"dataplane-microbench",
) -> dict:
    """Measure every variant; returns the schema-pinned result dict.

    Throughput is the best of ``repeats`` (steady-state; the first run
    warms the page cache), wire bytes are exact and repeat-invariant.
    """
    tmp = tempfile.mkdtemp(prefix="locust_dataplane_")
    try:
        remote = os.path.join(tmp, "inter.kvb")
        size = synth_intermediate(remote, target_bytes)
        expect_sha = hashlib.sha256(open(remote, "rb").read()).hexdigest()
        w = Worker(secret=secret, workdir=tmp)
        w.serve_in_thread()
        try:
            variants: dict[str, dict] = {}
            for name in VARIANTS:
                kw = dict(_VARIANT_KW[name])
                kw["window"] = window if name.endswith(("wK", "wK_z")) else 1
                best = None
                for r in range(max(1, repeats)):
                    local = os.path.join(tmp, f"got_{name}_{r}")
                    st = master.fetch_file(
                        w.addr, remote, local, secret,
                        expect_sha=expect_sha,
                        chunk_bytes=chunk_bytes,
                        **kw,
                    )
                    os.unlink(local)
                    if best is None or (st["mb_s"] or 0) > (best["mb_s"] or 0):
                        best = st
                best.pop("node", None)
                variants[name] = best
        finally:
            w._shutdown.set()

        def mbs(name: str) -> float:
            return float(variants[name]["mb_s"] or 0.0)

        json_wire = variants["json_w1"]["wire_bytes"]
        z_wire = variants["bin_wK_z"]["wire_bytes"]
        return {
            "corpus_bytes": size,
            "chunk_bytes": chunk_bytes,
            "window": window,
            "repeats": repeats,
            "variants": variants,
            "summary": {
                "fetch_mb_s_json": mbs("json_w1"),
                "fetch_mb_s_bin": max(mbs("bin_wK"), mbs("bin_wK_z")),
                "pipeline_speedup": round(
                    max(mbs("bin_wK"), mbs("bin_wK_z"))
                    / max(mbs("json_w1"), 1e-9),
                    3,
                ),
                "wire_bytes_json": json_wire,
                "wire_bytes_bin_zlib": z_wire,
                "wire_reduction": round(json_wire / max(z_wire, 1), 3),
                "compression_ratio": round(
                    variants["bin_wK_z"]["bytes"] / max(z_wire, 1), 3
                ),
            },
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
