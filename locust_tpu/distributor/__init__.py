"""Distributor package: master/worker data plane + the wire protocol.

Submodules resolve lazily (PEP 562): ``master`` and ``worker`` pull jax
in at import, but the serve tier's thin client only needs ``protocol``
(the jax-free wire layer) — an eager import here would make every
control-plane command (``python -m locust_tpu.serve stats`` against a
remote daemon) pay a jax init, which can HANG on a wedged axon tunnel
(CLAUDE.md).  ``from locust_tpu.distributor import master`` still works
exactly as before; it just imports when asked.
"""

import importlib

_SUBMODULES = ("master", "protocol", "worker")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"locust_tpu.distributor.{name}")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
