from locust_tpu.distributor import master, protocol, worker  # noqa: F401
