"""Distributor worker: the hardened descendant of Distributor/slave.py.

Same topology as the reference slave — a TCP daemon on each node that runs
the staged MapReduce binary on command (reference Distributor/slave.py:1-38)
— with the Q8 fixes: HMAC-authenticated frames, a closed command set
(``ping``/``map``/``fetch``/``shutdown``) instead of arbitrary
``subprocess.call(cmd[1:])`` (slave.py:30-32), structured status replies
instead of the unconditional "ACK" (slave.py:19-20), and the subprocess
exit code actually propagated (the reference discards it, slave.py:32).

``fetch`` is the piece of the data plane the reference left out entirely:
it returns the node's intermediate file so the master can stage it to the
reduce node (SURVEY.md §3.2 "unspecified transport, missing from repo").
Connections are persistent (docs/DATAPLANE.md): the master pipelines
windowed fetch requests down one connection and this daemon answers them
in order — binary frames with raw (optionally zlib) payloads when the
request negotiates them, base64 JSON for pre-binary masters — keeping
ONE open file handle per transfer instead of re-open+seek per chunk.
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import os
import socket
import subprocess
import sys
import threading
import time
import zlib

from locust_tpu import obs
from locust_tpu.distributor import protocol
from locust_tpu.utils import faultplan


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def default_map_runner(req: dict) -> dict:
    """Run the staged map via the CLI in a subprocess (one JAX process/node)."""
    out = req.get("intermediate", "/tmp/out.txt")
    cmd = [
        sys.executable,
        "-m",
        "locust_tpu",
        req["file"],
        str(req.get("line_start", -1)),
        str(req.get("line_end", -1)),
        str(req.get("node_num", 0)),
        "1",
        "-i",
        out,
    ]
    if req.get("inter_format"):  # packed-KV data plane (docs/DATAPLANE.md)
        cmd += ["--inter-format", str(req["inter_format"])]
    cmd += [str(a) for a in req.get("extra_args", [])]
    proc = subprocess.run(cmd, capture_output=True, timeout=req.get("timeout", 1800))
    return {
        "status": "ok" if proc.returncode == 0 else "error",
        "returncode": proc.returncode,
        "log": proc.stderr.decode(errors="replace")[-4000:],
        "intermediate": out,
    }


class Worker:
    """One worker daemon.  ``map_runner`` is injectable for loopback tests."""

    # Per-connection open-handle cap: a fetch transfer needs one handle;
    # a peer cycling paths on one connection must not leak descriptors.
    MAX_CACHED_FILES = 8

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: bytes = b"",
        map_runner=default_map_runner,
        workdir: str = "/tmp",
        conn_timeout: float = 30.0,
        max_connections: int = 32,
        support_binary: bool = True,
        serve: bool = False,
        serve_max_engines: int = 4,
    ):
        if not secret:
            raise ValueError("worker requires a shared secret (Q8: no open RCE)")
        self.secret = secret
        self.map_runner = map_runner
        # Scale-out serve dispatch (docs/SERVING.md): with serve=True the
        # worker answers ``serve_batch`` — an in-process engine fold over
        # a coalesced job batch, behind its OWN warm-executable cache
        # (serve/cache.py), which is what makes pool cache-affinity a
        # real scheduling input.  Lazy: the cache (and jax, at first
        # dispatch) only enters a worker that opted in.
        self._serve_cache = None
        if serve:
            from locust_tpu.serve.cache import ExecutableCache

            self._serve_cache = ExecutableCache(
                max_engines=serve_max_engines
            )
            # Tiny verified-corpus cache (sha -> split lines): a sharded
            # job sends several requests referencing ONE spill, and a
            # retried batch re-references its sha — without this every
            # request re-reads, re-hashes and re-splits the full corpus
            # on the dispatch critical path.  Content-addressed keys
            # can never go stale; 2 entries bound the memory.
            self._serve_corpus: dict[str, list] = {}
            self._serve_corpus_lock = threading.Lock()
            # Iterate-stage loop invariants (parsed edges, shard-
            # filtered columns, prep vectors) keyed by (sha, n, shard
            # layout): an N-epoch sweep sends N stage RPCs referencing
            # ONE graph — without this every epoch re-parses and
            # re-preps.  Content-addressed keys never go stale.
            self._iterate_graphs: dict[tuple, tuple] = {}
            self._iterate_lock = threading.Lock()
        # support_binary=False emulates a pre-binary (JSON-only) peer:
        # negotiation requests are ignored and every reply is a JSON
        # frame — the version-skew interop tests pin that an old worker
        # and a new master still complete jobs together.
        self.support_binary = support_binary
        # Fetch containment boundary is WORKER-side configuration; a request
        # must not be able to choose its own boundary.
        self.workdir = os.path.realpath(workdir)
        self.conn_timeout = conn_timeout
        self._replay_guard = protocol.ReplayGuard()
        # Fencing epoch high-water mark (docs/SERVING.md "High
        # availability"): serve daemons stamp dispatches with their
        # promotion epoch; once a newer primary has dispatched here, a
        # fenced-out zombie's RPCs are rejected structured stale_epoch.
        self._epoch_guard = protocol.EpochGuard()
        self._map_lock = threading.Lock()
        # Bounded concurrency: without a cap, an unauthenticated peer
        # opening idle connections would spawn unbounded threads (each
        # alive up to conn_timeout in recv) — a resource-exhaustion DoS.
        # When the cap is reached the accept loop stalls, pushing further
        # peers into the (small) listen backlog instead of into memory.
        self._conn_slots = threading.BoundedSemaphore(max_connections)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(5)
        self.addr = self._sock.getsockname()
        self._shutdown = threading.Event()

    def serve_forever(self) -> None:
        """Accept loop: one thread per connection.

        A node's map runs for minutes; with a serial loop that would block
        the master's pings and chunked fetches (and a reassigned shard's
        RPC) for the whole duration.  Connections are served concurrently;
        ``map`` commands still serialize under ``self._map_lock`` — the
        node has ONE accelerator and concurrent maps would contend for it.
        """
        while not self._shutdown.is_set():
            try:
                self._sock.settimeout(0.5)
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conn_slots.acquire()
            try:
                t = threading.Thread(
                    target=self._serve_one, args=(conn,), daemon=True
                )
                t.start()
            except Exception as e:  # noqa: BLE001 - spawn can fail under
                # thread/fd pressure; the ACCEPT LOOP must survive it (a
                # dead accept loop is a dead worker the master sees only
                # as timeouts), and it must not leak the slot or conn.
                self._conn_slots.release()
                try:
                    conn.close()
                except OSError:
                    pass
                print(
                    f"[worker] connection thread spawn failed "
                    f"({type(e).__name__}: {e}); dropped conn from "
                    f"{peer}, still accepting",
                    file=sys.stderr, flush=True,
                )
        self._sock.close()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            self._serve_conn(conn)
        finally:
            self._conn_slots.release()

    def _serve_conn(self, conn: socket.socket) -> None:
        """Serve REQUESTS on this connection until the peer closes or goes
        silent — the persistent-connection contract the master's pipelined
        fetch rides (it keeps several chunk requests in flight; we answer
        strictly in order, so responses need no sequence numbers).

        ``files`` caches one open handle per fetched path for the
        connection's lifetime: a windowed transfer of a multi-GB
        intermediate costs one open(), not one per chunk.
        """
        files: dict[str, tuple] = {}
        try:
            with conn:
                while not self._shutdown.is_set():
                    try:
                        # A silent peer must not hang the daemon: bound the
                        # read.  A clean peer close lands here too (recv of
                        # 0 bytes -> ConnectionError) — the loop exit.
                        conn.settimeout(self.conn_timeout)
                        req = protocol.recv_frame(conn, self.secret)
                    except PermissionError:
                        return  # unauthenticated/replayed peer: drop silently
                    except (ConnectionError, socket.timeout, OSError):
                        return  # peer closed / idled out
                    except Exception as e:
                        # Malformed frame: the stream cannot be resynced,
                        # but the daemon must survive (no remote DoS) —
                        # structured reply, then drop the connection.
                        self._try_reply(
                            conn, {"status": "error", "error": str(e)}
                        )
                        return
                    try:
                        self._replay_guard.check(req)
                        conn.settimeout(None)  # map subprocesses may run long
                        resp = self._handle(req, files)
                    except PermissionError:
                        return  # replayed frame: drop silently
                    except faultplan.FaultCrash:
                        return  # injected 'process crash': drop, no reply
                    except Exception as e:
                        resp = {"status": "error", "error": str(e)}
                    if not self._try_reply(conn, resp):
                        return
        finally:
            for fh, _ in files.values():
                try:
                    fh.close()
                except OSError:
                    pass

    def _try_reply(self, conn: socket.socket, resp) -> bool:
        """Send one reply frame — JSON, or binary when the handler returned
        a ``(meta, encoded_body, flags)`` triple.  False on a dead peer."""
        try:
            if isinstance(resp, tuple):
                meta, body, flags = resp
                protocol.send_bin_frame_encoded(
                    conn, meta, body, self.secret, flags=flags
                )
            else:
                protocol.send_frame(conn, resp, self.secret, sign_fresh=False)
            return True
        except OSError:
            return False

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def _handle(self, req: dict, files: dict | None = None):
        cmd = req.get("cmd")
        if cmd not in protocol.COMMANDS:
            return {"status": "error", "error": f"unknown command {cmd!r}"}
        # Chaos: straggler model — the worker stalls before handling
        # (tests/test_faults.py; no-op without an active plan).
        faultplan.delay(
            "rpc.delay",
            cmd=cmd, shard=req.get("node_num"), port=self.addr[1],
        )
        if cmd == "ping":
            return {"status": "ok", "pong": True}
        if cmd == "shutdown":
            self._shutdown.set()
            return {"status": "ok", "bye": True}
        if cmd == "map":
            return self._traced_map(req)
        if cmd == "serve_batch":
            return self._serve_batch(req)
        if cmd == "serve_stats":
            return self._serve_stats()
        if cmd == "plan_stage":
            return self._plan_stage(req)
        # fetch: stream back an intermediate file this worker produced, one
        # bounded window per request so arbitrarily large intermediates fit
        # the frame limit (the master pipelines ``offset`` windows until
        # ``eof``).  Containment boundary = self.workdir (server config,
        # NOT the request).
        path = req.get("path", "")
        real = os.path.realpath(path)
        if not real.startswith(self.workdir + os.sep):
            return {"status": "error", "error": "path outside workdir"}
        try:
            offset = int(req.get("offset", 0))
            max_bytes = int(req.get("max_bytes", protocol.FETCH_CHUNK))
        except (TypeError, ValueError):
            return {"status": "error", "error": "bad offset/max_bytes"}
        if offset < 0:
            return {"status": "error", "error": "negative offset"}
        max_bytes = max(1, min(max_bytes, protocol.FETCH_CHUNK_MAX))
        try:
            data, size = self._read_window(real, offset, max_bytes, files)
        except OSError as e:
            return {"status": "error", "error": str(e)}
        # eof/total reflect the REAL read (pre-fault): an injected disk-rot
        # corruption/truncation must look like a worker that believes it
        # delivered the bytes — the master's sha256 verification is what
        # catches it, not the fault being polite about itself.
        eof = offset + len(data) >= size
        data = faultplan.mangle(
            "io.intermediate", data,
            path=real, offset=offset, port=self.addr[1],
        )
        # Per-chunk digest over the RAW window: covers the wire encoding
        # round-trip (base64 or zlib) and anything between this read and
        # the master's disk write.
        meta = {
            "status": "ok",
            "sha256": hashlib.sha256(data).hexdigest(),
            "offset": offset,
            "total": size,
            "eof": eof,
        }
        tctx = req.get(protocol.TRACE_KEY)
        if isinstance(tctx, dict) and tctx.get("id"):
            # Correlation echo in the reply (binary frame) meta: every
            # fetched chunk is attributable to the job's trace_id.
            meta["trace_id"] = str(tctx["id"])
        if not (req.get("bin") and self.support_binary):
            # Pre-binary master (or a worker pinned JSON-only): the
            # original base64 JSON reply, byte for byte.
            return dict(meta, data_b64=base64.b64encode(data).decode())
        # Binary data plane: raw payload, zlib'd when the master accepts
        # it and it actually shrinks the chunk.
        flags, body, enc = 0, data, "raw"
        if req.get("accept_zlib") and data:
            packed = zlib.compress(data, 1)
            if len(packed) < len(data):
                flags, body, enc = protocol.FLAG_ZLIB, packed, "zlib"
        # Chaos: the ENCODED payload about to be framed (docs/DATAPLANE.md).
        # The frame MAC is computed AFTER this, so an injected corruption
        # reaches the master as a zlib error or chunk-sha mismatch — the
        # data-plane failure mode, distinct from rpc.frame's MAC reject.
        rule = faultplan.fire(
            "io.chunk", path=real, offset=offset, port=self.addr[1], enc=enc
        )
        if rule is not None:
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            else:
                body = faultplan.active().mutate(rule, body)
        return dict(meta, enc=enc, clen=len(body)), body, flags

    def _traced_map(self, req: dict) -> dict:
        """Run one map command under a REQUEST-scoped tracer when the
        master stamped a trace context into the request.

        The tracer is per-request (not the process tracer): a loopback
        cluster shares one process with its master, and the worker's
        spans must travel the same path as a remote worker's — serialized
        in the reply ("spans") with the worker's wall clock ("clock") for
        the master's offset estimate — never leak directly into a tracer
        enabled in this process (``obs.scoped`` masks it either way).
        An error reply ships its spans too (a failed attempt is exactly
        the part of a chaos timeline worth reading); an injected CRASH
        drops the connection before any reply, so those spans are lost
        with the "process" — faithful to what a real SIGKILL leaves.
        """
        tctx = req.get(protocol.TRACE_KEY)
        tracer = None
        if isinstance(tctx, dict) and tctx.get("id"):
            tracer = obs.Tracer(
                trace_id=str(tctx["id"]),
                process=f"worker:{self.addr[1]}",
            )
        with obs.scoped(tracer):
            with obs.span(
                "worker.map",
                shard=req.get("node_num"),
                port=self.addr[1],
            ):
                resp = self._run_map(req)
        if tracer is not None and isinstance(resp, dict):
            resp["spans"] = tracer.serialize()
            resp["clock"] = time.time()
        return resp

    def _run_map(self, req: dict) -> dict:
        rule = faultplan.fire(
            "worker.map", shard=req.get("node_num"), port=self.addr[1]
        )
        if rule is not None:
            if rule.action == "crash":
                raise faultplan.FaultCrash("injected crash mid-map")
            if rule.action == "error":
                return {"status": "error", "returncode": -9,
                        "log": "[faultplan] injected map failure",
                        "error": "injected map failure"}
            if rule.action == "delay":
                time.sleep(rule.delay_s)
        try:
            with self._map_lock:  # one accelerator: maps serialize
                resp = self.map_runner(req)
        except Exception as e:  # propagate failure, don't fake-ACK
            return {"status": "error", "error": repr(e)}
        if resp.get("status") == "ok" and "sha256" not in resp:
            # End-to-end integrity anchor: hash the intermediate at
            # map time so the master can verify the assembled fetch
            # against what the map actually wrote (Dean & Ghemawat's
            # checksummed intermediates).  A runner that wrote no
            # file (injected test runners) just ships no digest —
            # the master skips the end-to-end check then, and a
            # truly missing intermediate still fails at fetch time.
            inter = resp.get("intermediate") or req.get("intermediate")
            try:
                resp["sha256"] = _file_sha256(inter)
            except (OSError, TypeError):
                pass
        return resp

    # ------------------------------------------------- serve-batch surface

    def _serve_stats(self) -> dict:
        """The pool's warm-cache RPC (serve/pool.py seed_affinity): which
        shapes this worker already holds compiled.  A daemon restarting
        against a warm fleet re-learns affinity homes from this instead
        of cold-spraying its first batches."""
        if self._serve_cache is None:
            return {"status": "error",
                    "error": "serve dispatch not enabled (start with --serve)"}
        return {
            "status": "ok",
            "exec_cache": self._serve_cache.stats(),
            "warm_shapes": self._serve_cache.warm_shapes(),
        }

    def _serve_batch(self, req: dict) -> dict:
        """Fold one coalesced serve batch on this worker's engine.

        The daemon's pool (serve/pool.py) sends the batch meta plus
        content-addressed corpus REFERENCES — ``spill_dir/<sha>.bin``
        files the journal/pool already wrote once — and this handler
        verifies every sha before folding, so a stale, torn, or
        misdirected spill is a structured error, never a silent wrong
        answer.  Shard entries carry ``line_start``/``line_end`` (the
        same half-open line-range contract as the map command) and fold
        just that slice.  Dispatches serialize under ``_map_lock`` (one
        accelerator per node, same stance as map)."""
        if self._serve_cache is None:
            return {"status": "error",
                    "error": "serve dispatch not enabled (start with --serve)"}
        if protocol.EPOCH_KEY in req:
            try:
                stale = self._epoch_guard.observe(req[protocol.EPOCH_KEY])
            except (TypeError, ValueError):
                return {"status": "error",
                        "error": f"bad fencing epoch "
                                 f"{req[protocol.EPOCH_KEY]!r}"}
            if stale is not None:
                # The zombie-primary fence: this worker has already
                # served a newer primary — obeying the old one would be
                # the split-brain double-answer HA forbids.  The ONE
                # fencing-reply shape (serve/replicate.py): the reply
                # carries the high-water epoch so the fenced daemon
                # adopts the REAL fence instead of guessing.
                from locust_tpu.serve.replicate import stale_reply

                return stale_reply(stale, None)
        from locust_tpu.config import EngineConfig
        from locust_tpu.serve import batch as batching
        from locust_tpu.serve.jobs import (
            SPEC_CONFIG_KEYS,
            WORKLOADS,
            Job,
            JobSpec,
        )

        workload = req.get("workload")
        if workload not in WORKLOADS:
            return {"status": "error",
                    "error": f"unknown workload {workload!r}"}
        overrides = req.get("config") or {}
        if not isinstance(overrides, dict) or (
            set(overrides) - set(SPEC_CONFIG_KEYS)
        ):
            return {"status": "error",
                    "error": f"bad config overrides {overrides!r}"}
        try:
            cfg = EngineConfig(**overrides)
            bucket = int(req["bucket"])
            spill_dir = str(req["spill_dir"])
            jobs_meta = list(req["jobs"])
        except (KeyError, TypeError, ValueError) as e:
            return {"status": "error", "error": f"bad serve_batch: {e}"}
        if not jobs_meta:
            return {"status": "error", "error": "serve_batch with no jobs"}
        spec = JobSpec(tenant="pool", workload=workload, cfg=cfg)
        corpora: dict[str, list] = {}
        jobs: list[Job] = []
        for jm in jobs_meta:
            try:
                sha = str(jm["sha"])
                job_id = str(jm["job_id"])
                a = jm.get("line_start")
                b = jm.get("line_end")
            except (KeyError, TypeError):
                return {"status": "error", "error": f"bad job entry {jm!r}"}
            try:
                lines = self._serve_corpus_lines(sha, spill_dir)
            except ValueError as e:
                return {"status": "error", "error": str(e)}
            if a is not None or b is not None:
                lines = lines[int(a or 0):
                              int(b) if b is not None else len(lines)]
            # Each (sha, slice) is its own staging key: two shards of one
            # corpus must not alias each other's lines.
            ckey = f"{sha}:{a}:{b}"
            n_lines = len(lines)
            n_blocks, jbucket = batching.job_shape(n_lines, cfg)
            if jbucket > bucket:
                return {"status": "error",
                        "error": f"job {job_id}: {n_lines} lines need "
                                 f"bucket {jbucket} > batch bucket {bucket}"}
            corpora[ckey] = lines
            jobs.append(Job(
                job_id=job_id, spec=spec, corpus_digest=ckey,
                n_lines=n_lines, n_blocks=n_blocks, bucket=bucket,
            ))
        njobs_padded = batching.bucket_blocks(len(jobs))
        try:
            with self._map_lock:  # one accelerator: folds serialize
                engine, hit = self._serve_cache.lookup(
                    spec, njobs_padded, bucket
                )
                results = batching.dispatch_batch(engine, jobs, corpora)
                self._serve_cache.mark_compiled(spec, njobs_padded, bucket)
                out = []
                for job, res in zip(jobs, results):
                    pairs = res.to_host_pairs()
                    out.append({
                        "job_id": job.job_id,
                        "pairs": [
                            [base64.b64encode(k).decode(), int(v)]
                            for k, v in pairs
                        ],
                        "distinct": int(res.num_segments),
                        "truncated": bool(res.truncated),
                        "overflow_tokens": int(res.overflow_tokens),
                    })
        except Exception as e:  # noqa: BLE001 - structured, worker survives
            return {"status": "error",
                    "error": f"serve dispatch failed: "
                             f"{type(e).__name__}: {e}"}
        return {"status": "ok", "warm": bool(hit), "results": out}

    # ------------------------------------------------ plan-stage surface

    def _plan_stage(self, req: dict) -> dict:
        """One distributed-plan stage on this worker (docs/PLAN.md
        "Distributed execution"): phase "map" folds one source split and
        publishes its shuffle partitions atomically into the spill dir;
        phase "reduce" pulls one partition's inputs from their map
        workers over the binary data plane and returns the combined
        table.  Epoch-fenced like serve_batch: a fenced-out zombie
        primary can never get a stale partition published."""
        if self._serve_cache is None:
            return {"status": "error",
                    "error": "serve dispatch not enabled (start with --serve)"}
        if protocol.EPOCH_KEY in req:
            try:
                stale = self._epoch_guard.observe(req[protocol.EPOCH_KEY])
            except (TypeError, ValueError):
                return {"status": "error",
                        "error": f"bad fencing epoch "
                                 f"{req[protocol.EPOCH_KEY]!r}"}
            if stale is not None:
                from locust_tpu.serve.replicate import stale_reply

                return stale_reply(stale, None)
        phase = req.get("phase")
        # Chaos: the stage RPC boundary (docs/FAULTS.md).  "crash" models
        # the worker SIGKILL'd mid-stage (connection dropped, no reply —
        # the coordinator recomputes the stage on a survivor); "error" a
        # structured stage failure; "delay" a straggler the coordinator's
        # speculative backup races.
        rule = faultplan.fire(
            "plan.stage", phase=phase, split=req.get("split"),
            part=req.get("part"), port=self.addr[1],
        )
        if rule is not None:
            if rule.action == "crash":
                raise faultplan.FaultCrash("injected crash mid-plan-stage")
            if rule.action == "error":
                return {"status": "error",
                        "error": "[faultplan] injected plan stage failure"}
            if rule.action == "delay":
                time.sleep(rule.delay_s)
        try:
            with obs.span(
                "plan.stage", phase=phase, split=req.get("split"),
                part=req.get("part"), port=self.addr[1],
            ):
                if phase == "map":
                    return self._plan_map_stage(req)
                if phase == "reduce":
                    return self._plan_reduce_stage(req)
                if phase == "join":
                    return self._plan_join_stage(req)
                if phase == "iterate":
                    return self._plan_iterate_stage(req)
                return {"status": "error",
                        "error": f"unknown plan stage phase {phase!r}"}
        except Exception as e:  # noqa: BLE001 - structured, worker survives
            return {"status": "error",
                    "error": f"plan stage failed: {type(e).__name__}: {e}"}

    def _plan_map_stage(self, req: dict) -> dict:
        """Fold one source split and publish its shuffle partitions.

        The split's lines come from the content-addressed corpus spill
        (sha-verified, like serve_batch); doc ids are GLOBAL
        (``(line_start + i) // lines_per_doc``) so the per-split fold is
        exactly a restriction of the solo fold.  Partition files publish
        atomically under (plan fp, split, partition, attempt) — a
        recompute or speculative backup can never clobber a live file.
        """
        import numpy as np

        from locust_tpu.config import EngineConfig
        from locust_tpu.plan import distribute
        from locust_tpu.serve import batch as batching
        from locust_tpu.serve.jobs import SPEC_CONFIG_KEYS, Job, JobSpec

        overrides = req.get("config") or {}
        if not isinstance(overrides, dict) or (
            set(overrides) - set(SPEC_CONFIG_KEYS)
        ):
            return {"status": "error",
                    "error": f"bad config overrides {overrides!r}"}
        try:
            cfg = EngineConfig(**overrides)
            fold = str(req["fold"])
            sha = str(req["sha"])
            spill_dir = str(req["spill_dir"])
            plan_fp = str(req["plan_fp"])
            split = int(req["split"])
            attempt = int(req["attempt"])
            n_parts = int(req["n_parts"])
            a = int(req["line_start"])
            b = int(req["line_end"])
            lines_per_doc = int(req.get("lines_per_doc", 1))
        except (KeyError, TypeError, ValueError) as e:
            return {"status": "error", "error": f"bad plan_stage: {e}"}
        try:
            lines = self._serve_corpus_lines(sha, spill_dir)
        except ValueError as e:
            return {"status": "error", "error": str(e)}
        sl = lines[a:b]
        truncated, overflow = False, 0
        warm = False
        if fold == "wordcount":
            spec = JobSpec(tenant="pool", workload="wordcount", cfg=cfg)
            n_blocks, bucket = batching.job_shape(len(sl), cfg)
            ckey = f"{sha}:{a}:{b}"
            node_fp = str(req.get("node_fp") or "")
            job = Job(
                job_id=f"plan-{plan_fp}-s{split}", spec=spec,
                corpus_digest=ckey, n_lines=len(sl), n_blocks=n_blocks,
                bucket=bucket,
            )
            with self._map_lock:  # one accelerator: folds serialize
                if node_fp:
                    # Warm by the fold node's CLOSURE fingerprint
                    # (cache.fold_node_key): a repeat distributed plan
                    # — alpha-renamed included — lands every map split
                    # on this worker's already-compiled executable, so
                    # ``compiles`` stays flat on resubmit (the warm
                    # economics PR 11 proved for whole serve jobs).
                    engine, warm = self._serve_cache.lookup_fold_node(
                        node_fp, cfg, 1, bucket
                    )
                else:
                    engine, warm = self._serve_cache.lookup(
                        spec, 1, bucket
                    )
                res = batching.dispatch_batch(
                    engine, [job], {ckey: sl}
                )[0]
                if node_fp:
                    self._serve_cache.mark_compiled_fold_node(
                        node_fp, cfg.fingerprint(), 1, bucket
                    )
                else:
                    self._serve_cache.mark_compiled(spec, 1, bucket)
                pairs = res.to_host_pairs()
                truncated = bool(res.truncated)
                overflow = int(res.overflow_tokens)
            enc = pairs
        elif fold in ("tf", "index"):
            from locust_tpu.apps.tfidf import term_doc_counts

            ids = ((a + np.arange(len(sl))) // lines_per_doc).astype(
                np.int32
            )
            with self._map_lock:
                # The index fold tolerates per-line emit overflow the
                # way build_inverted_index does (warn, drop) — the solo
                # path's exact semantics; tf raises, also solo-exact.
                tf = term_doc_counts(
                    sl, ids, cfg, allow_overflow=(fold == "index")
                )
            enc = [
                (distribute.encode_key(fold, k), v)
                for k, v in tf.items()
            ]
        else:
            return {"status": "error", "error": f"unknown fold {fold!r}"}
        parts = distribute.publish_split(
            spill_dir, plan_fp, split, attempt, enc, n_parts
        )
        return {
            "status": "ok",
            "split": split,
            "attempt": attempt,
            "worker": f"{self.addr[0]}:{self.addr[1]}",
            "parts": parts,
            "truncated": truncated,
            "overflow_tokens": overflow,
            "warm": bool(warm),
        }

    def _plan_reduce_stage(self, req: dict) -> dict:
        """Combine one shuffle partition from its per-split input files.

        Inputs published by OTHER workers move worker-to-worker over the
        distributor's binary HMAC'd data plane (master.fetch_file:
        pipelined windows, sha-verified end to end) — the daemon never
        relays partition bytes.  ANY lost/damaged input answers a
        structured error naming ``lost_split`` so the coordinator
        recomputes exactly that map split from its durable corpus split,
        not the whole plan."""
        try:
            part = int(req["part"])
            key_width = int(req["key_width"])
            inputs = list(req["inputs"])
        except (KeyError, TypeError, ValueError) as e:
            return {"status": "error", "error": f"bad plan_stage: {e}"}
        me = f"{self.addr[0]}:{self.addr[1]}"
        acc, err = self._merge_partition_inputs(inputs, key_width, part)
        if err is not None:
            return err
        return {
            "status": "ok",
            "part": part,
            "worker": me,
            "pairs": [
                [base64.b64encode(k).decode(), int(v)]
                for k, v in sorted(acc.items())
            ],
        }

    def _merge_partition_inputs(
        self, inputs: list, key_width: int, part: int
    ) -> tuple[dict | None, dict | None]:
        """The reduce/join stages' shared input gather: read (local) or
        pull (remote) every per-split partition file for one bin and
        sum-merge.  Returns (table, None) or (None, structured error
        reply naming ``lost_split``)."""
        from locust_tpu.plan import distribute

        me = f"{self.addr[0]}:{self.addr[1]}"
        acc: dict = {}
        for ref in inputs:
            try:
                path = str(ref["path"])
                sha = str(ref["sha256"])
                owner = str(ref["worker"])
                split = int(ref["split"])
            except (KeyError, TypeError, ValueError):
                return None, {"status": "error",
                              "error": f"bad partition ref {ref!r}"}
            if int(ref.get("pairs", 1)) == 0:
                continue  # published empty: nothing to move or merge
            try:
                if owner == me:
                    pairs = distribute.read_partition(path, sha, key_width)
                else:
                    pairs = self._pull_partition(
                        owner, path, sha, key_width, part
                    )
            except Exception as e:  # noqa: BLE001 - structured loss report
                return None, {
                    "status": "error",
                    "lost_split": split,
                    "error": f"partition input lost (split {split}, "
                             f"part {part}, {owner}): "
                             f"{type(e).__name__}: {e}",
                }
            distribute.merge_pairs(acc, pairs)
        return acc, None

    def _plan_join_stage(self, req: dict) -> dict:
        """Evaluate one co-partitioned hash-join bin, tree-deep.

        The bin's wordcount table merges from its per-split partition
        inputs exactly like a reduce stage; then the WHOLE join tree
        evaluates over it locally (``distribute.eval_tree_doc`` — host
        Python ints, the solo ``_eval_join`` semantics) — however deep
        the tree, the bin never returns to the master between joins
        (docs/PLAN.md "Distributed execution").  ``distinct`` reports
        the bin's pre-join table size so the coordinator can prove the
        solo fold would not have truncated (its capacity gate)."""
        from locust_tpu.plan import distribute

        try:
            part = int(req["part"])
            key_width = int(req["key_width"])
            inputs = list(req["inputs"])
            tree = list(req["tree"])
        except (KeyError, TypeError, ValueError) as e:
            return {"status": "error", "error": f"bad plan_stage: {e}"}
        me = f"{self.addr[0]}:{self.addr[1]}"
        acc, err = self._merge_partition_inputs(inputs, key_width, part)
        if err is not None:
            return err
        try:
            joined = distribute.eval_tree_doc(tree, acc)
        except (KeyError, IndexError, TypeError, ValueError) as e:
            return {"status": "error",
                    "error": f"bad join tree {tree!r}: {e}"}
        return {
            "status": "ok",
            "part": part,
            "worker": me,
            "distinct": len(acc),
            "pairs": [
                [base64.b64encode(k).decode(), int(v)]
                for k, v in sorted(joined.items())
            ],
        }

    def _plan_iterate_stage(self, req: dict) -> dict:
        """One pagerank epoch on one rank shard (docs/PLAN.md
        "Distributed execution").

        The worker holds the loop-invariant graph state (edge arrays,
        inv_deg, dangling mask — cached per corpus sha, shard-filtered
        to ``dst in [lo, hi)``), reconstructs the previous epoch's full
        rank vector from ALL shards' published partitions (shard order
        is node order), runs ONE bit-exact ``pagerank_step`` and
        publishes its own slice for the next epoch.  Epoch 1 starts
        from the solo path's exact ``ranks0``.  A lost input partition
        answers structured ``(lost_epoch, lost_split)`` so the
        coordinator recomputes exactly that (epoch, shard) stage."""
        import numpy as np

        from locust_tpu.plan import distribute

        try:
            sha = str(req["sha"])
            spill_dir = str(req["spill_dir"])
            plan_fp = str(req["plan_fp"])
            epoch = int(req["epoch"])       # 1-based sweep number
            shard = int(req["shard"])
            n_shards = int(req["n_shards"])
            num_nodes = int(req["num_nodes"])
            damping = float(req["damping"])
            attempt = int(req["attempt"])
            inputs = req.get("inputs")      # None on epoch 1
        except (KeyError, TypeError, ValueError) as e:
            return {"status": "error", "error": f"bad plan_stage: {e}"}
        try:
            src_sub, dst_sub, inv_deg, dangling = self._iterate_graph(
                sha, spill_dir, num_nodes, shard, n_shards
            )
        except ValueError as e:
            return {"status": "error", "error": str(e)}
        me = f"{self.addr[0]}:{self.addr[1]}"
        if inputs is None:
            # The solo scan's exact ranks0: 1/n rounded double->f32.
            ranks = np.full(
                (num_nodes,), 1.0 / num_nodes, dtype=np.float32
            )
        else:
            slices = []
            for ref in sorted(inputs, key=lambda r: int(r["part"])):
                try:
                    path = str(ref["path"])
                    rsha = str(ref["sha256"])
                    owner = str(ref["worker"])
                    part = int(ref["part"])
                except (KeyError, TypeError, ValueError):
                    return {"status": "error",
                            "error": f"bad partition ref {ref!r}"}
                try:
                    if owner == me or os.path.exists(path):
                        pairs = distribute.read_partition(
                            path, rsha, distribute.RANK_KEY_WIDTH
                        )
                    else:
                        pairs = self._pull_partition(
                            owner, path, rsha,
                            distribute.RANK_KEY_WIDTH, part,
                        )
                except Exception as e:  # noqa: BLE001 - structured loss
                    return {
                        "status": "error",
                        "lost_split": part,
                        "lost_epoch": epoch - 1,
                        "error": f"rank partition lost (epoch "
                                 f"{epoch - 1}, shard {part}, {owner}): "
                                 f"{type(e).__name__}: {e}",
                    }
                slices.append(distribute.decode_rank_values(pairs))
            ranks = np.concatenate(slices) if slices else np.zeros(
                0, np.float32
            )
            if len(ranks) != num_nodes:
                return {"status": "error",
                        "error": f"rank vector reassembled {len(ranks)} "
                                 f"of {num_nodes} nodes"}
        from locust_tpu.apps.pagerank import pagerank_step

        lo, hi = distribute.shard_ranges(num_nodes, n_shards)[shard]
        with self._map_lock:  # one accelerator: device steps serialize
            new = np.asarray(pagerank_step(
                src_sub, dst_sub, ranks, inv_deg, dangling,
                damping, num_nodes,
            ))
        ref = distribute.publish_partition(
            distribute.partition_path(
                spill_dir, plan_fp, epoch, shard, attempt
            ),
            distribute.encode_rank_pairs(lo, new[lo:hi]),
        )
        ref["part"] = shard
        return {
            "status": "ok",
            "epoch": epoch,
            "shard": shard,
            "attempt": attempt,
            "worker": me,
            "ref": ref,
        }

    def _iterate_graph(
        self, sha: str, spill_dir: str, num_nodes: int, shard: int,
        n_shards: int,
    ) -> tuple:
        """The iterate stages' loop-invariant state, cached per (corpus
        sha, num_nodes, shard layout): parsed edge arrays restricted to
        this shard's dst range plus the FULL inv_deg/dangling vectors
        (``pagerank_prep``, bit-exact vs the solo kernel's prologue).
        Raises ``ValueError`` on a missing/damaged spill or a corpus
        that does not parse as an edge list."""
        import numpy as np

        from locust_tpu.plan import distribute

        key = (sha, int(num_nodes), int(n_shards), int(shard))
        with self._iterate_lock:
            ent = self._iterate_graphs.pop(key, None)
            if ent is not None:
                self._iterate_graphs[key] = ent  # LRU touch
                return ent
        path = os.path.join(spill_dir, f"{sha}.bin")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise ValueError(f"corpus spill unreadable: {e}")
        if hashlib.sha256(data).hexdigest() != sha:
            raise ValueError(f"corpus spill {sha} fails its content hash")
        from locust_tpu.apps.pagerank import pagerank_prep
        from locust_tpu.plan.compile import PlanError, edges_from_bytes

        try:
            src, dst = edges_from_bytes(data)
        except PlanError as e:
            raise ValueError(f"corpus is not an edge list: {e}")
        src = np.asarray(src, np.int32)
        dst = np.asarray(dst, np.int32)
        with self._map_lock:
            inv_deg, dangling = pagerank_prep(src, num_nodes)
            inv_deg = np.asarray(inv_deg)
            dangling = np.asarray(dangling)
        lo, hi = distribute.shard_ranges(num_nodes, n_shards)[shard]
        mask = (dst >= lo) & (dst < hi)
        ent = (src[mask], dst[mask], inv_deg, dangling)
        with self._iterate_lock:
            self._iterate_graphs[key] = ent
            while len(self._iterate_graphs) > 4:
                self._iterate_graphs.pop(next(iter(self._iterate_graphs)))
        return ent

    def _pull_partition(
        self, owner: str, path: str, sha: str, key_width: int, part: int
    ) -> list:
        """Fetch one remote partition over the binary data plane and
        decode it.  The transfer verifies the file sha end-to-end
        (fetch_file's expect_sha) and the local decode re-verifies —
        a mangled wire or disk byte is a loss, never a wrong answer."""
        from locust_tpu.distributor import master
        from locust_tpu.plan import distribute

        host, _, port = owner.rpartition(":")
        local = os.path.join(
            self.workdir,
            f"pull_{os.path.basename(path)}.{os.getpid()}."
            f"{threading.get_ident()}",
        )
        with obs.span("plan.shuffle", part=part, src=owner):
            try:
                master.fetch_file(
                    (host, int(port)), path, local, self.secret,
                    expect_sha=sha, rpc_timeout=120.0,
                )
                return distribute.read_partition(local, sha, key_width)
            finally:
                try:
                    os.unlink(local)
                except OSError:
                    pass

    def _serve_corpus_lines(self, sha: str, spill_dir: str) -> list:
        """One spilled corpus read+verified+split, through the tiny LRU
        cache.  Raises ``ValueError`` with the structured message on a
        missing/damaged spill — a stale or torn spill must never fold."""
        with self._serve_corpus_lock:
            ent = self._serve_corpus.pop(sha, None)
            if ent is not None:
                self._serve_corpus[sha] = ent  # LRU touch
                return ent
        path = os.path.join(spill_dir, f"{sha}.bin")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise ValueError(f"corpus spill unreadable: {e}")
        if hashlib.sha256(data).hexdigest() != sha:
            raise ValueError(f"corpus spill {sha} fails its content hash")
        lines = data.splitlines()
        with self._serve_corpus_lock:
            self._serve_corpus[sha] = lines
            while len(self._serve_corpus) > 2:
                self._serve_corpus.pop(next(iter(self._serve_corpus)))
        return lines

    def _read_window(
        self, real: str, offset: int, max_bytes: int, files: dict | None
    ) -> tuple[bytes, int]:
        """One bounded window, through the per-connection handle cache."""
        if files is None:  # direct _handle call (unit tests): no cache
            with open(real, "rb") as f:
                size = os.fstat(f.fileno()).st_size
                f.seek(offset)
                return f.read(max_bytes), size
        ent = files.get(real)
        if ent is None:
            while len(files) >= self.MAX_CACHED_FILES:
                _, (old, _) = files.popitem()
                try:
                    old.close()
                except OSError:
                    pass
            fh = open(real, "rb")
            ent = files[real] = (fh, os.fstat(fh.fileno()).st_size)
        fh, size = ent
        fh.seek(offset)
        return fh.read(max_bytes), size


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="locust-worker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1337)  # reference port, slave.py:7
    p.add_argument("--secret-env", default="LOCUST_SECRET",
                   help="env var holding the shared secret")
    p.add_argument("--fault-plan", default=None,
                   help="chaos-test fault plan: JSON text or a path "
                        f"(also ${faultplan.ENV_VAR}); see docs/FAULTS.md")
    p.add_argument("--workdir", default="/tmp",
                   help="fetch containment boundary (server-side config)")
    p.add_argument("--serve", action="store_true",
                   help="answer serve_batch dispatches from a serve "
                        "daemon's worker pool (docs/SERVING.md "
                        "scale-out dispatch); holds warm engines")
    p.add_argument("--serve-max-engines", type=int, default=4,
                   help="warm engines kept by the serve cache (LRU)")
    args = p.parse_args(argv)
    faultplan.install(args.fault_plan)
    secret = os.environ.get(args.secret_env, "").encode()
    if not secret:
        print(f"error: set ${args.secret_env} (refusing unauthenticated mode)",
              file=sys.stderr)
        return 2
    w = Worker(args.host, args.port, secret, workdir=args.workdir,
               serve=args.serve, serve_max_engines=args.serve_max_engines)
    print(f"[worker] listening on {w.addr[0]}:{w.addr[1]}", file=sys.stderr)
    w.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
