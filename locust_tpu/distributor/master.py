"""Distributor master: the launcher the reference documents but never shipped.

The reference README promises "the provided bash script will launch the
MapReduce program for all nodes" over a cluster file of ``ip port`` lines
(reference README.md:18-24) — no such script exists in the repo
(SURVEY.md C12).  This module implements that role:

  1. parse the cluster file (protocol.parse_cluster_file),
  2. shard the input by line ranges — the reference's per-node
     ``[line_start, line_end)`` CLI contract (main.cu:369-374),
  3. fan the staged map out to all workers in parallel,
  4. collect each node's intermediate (packed binary KV by default,
     docs/DATAPLANE.md; TSV for reference parity) over the authenticated
     channel — pipelined offset-addressed chunks over one connection per
     node, binary frames with optional zlib when the worker speaks them,
     sha256-verified per raw chunk AND end-to-end against the digest the
     worker recorded at map time, so intermediates larger than one
     protocol frame round-trip fine and a corrupted chunk can never
     silently reach the reduce,
  5. run the reduce stage locally over all collected intermediates —
     which re-sorts, fixing the reference's unsorted-reduce-input bug (Q6).

Fault tolerance (VERDICT r2 missing #6 — the reference has none, its slave
ACKs unconditionally, slave.py:19-20), per Dean & Ghemawat's OSDI'04
robustness recipe (re-execution + backup tasks + checksummed data):

  * a shard whose worker fails (dead connection, timeout, non-zero map
    exit, integrity mismatch) is REASSIGNED to the next live worker,
    bounded by ``max_retries`` failed attempts per shard;
  * a failed worker is QUARANTINED with exponential backoff + jitter
    (``WorkerHealth``) instead of for the rest of the job: a heartbeat
    loop pings quarantined workers once their backoff expires and
    un-quarantines them on recovery, so a transient flap doesn't burn a
    node for good;
  * a shard still running past ``speculate_after`` seconds gets a
    SPECULATIVE backup attempt on a different worker (the classic
    MapReduce straggler mitigation) — first finisher wins, the loser is
    abandoned (line-range shards are deterministic and idempotent, and
    every attempt writes an attempt-unique intermediate path, so the
    loser can never clobber the winner);
  * per-shard attempt timings land in the returned ``JobResult.shards``.

Chaos coverage: every failure path above is exercised under injected
faults by tests/test_faults.py (locust_tpu/utils/faultplan.py).
"""

from __future__ import annotations

import argparse
import base64
import concurrent.futures
import hashlib
import logging
import os
import queue
import socket
import sys
import tempfile
import threading
import time
import uuid

from locust_tpu import obs
from locust_tpu.distributor import protocol
from locust_tpu.io.loader import count_lines
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")


class MasterError(RuntimeError):
    pass


class IntegrityError(MasterError):
    """A fetched intermediate failed sha256 verification."""


def _scoped_call(tracer, fn, *args, **kw):
    """Run ``fn(*args, **kw)`` with the obs thread-local pinned to
    ``tracer`` — the ONE copy of the pool-thread scoping rule: worker
    threads otherwise fall back to the process tracer, which need not be
    the one the job was scoped to (or may leak spans a scoped(None)
    caller masked off)."""
    with obs.scoped(tracer):
        return fn(*args, **kw)


def rpc(node: tuple[str, int], req: dict, secret: bytes, timeout: float = 1800.0) -> dict:
    """One request/reply against a worker on a fresh connection — the
    control-plane primitive shared by the job driver and the serve
    tier's warm-cache RPCs (``serve_stats``, serve/pool.py)."""
    faultplan.check_connect(node[0], node[1])
    with socket.create_connection(node, timeout=timeout) as sock:
        protocol.send_frame(sock, req, secret)
        return protocol.recv_frame(sock, secret)


_rpc = rpc  # internal call sites predate the public name


def _verify_chunk(obj: dict, data: bytes, node, offset: int) -> None:
    # Per-chunk digest over the RAW window: catches corruption between
    # the worker's disk read and this process (the HMAC covers the
    # frame, but not a worker-side read or encode gone wrong).
    chunk_sha = obj.get("sha256")
    if chunk_sha is not None and chunk_sha != hashlib.sha256(data).hexdigest():
        raise IntegrityError(
            f"fetch chunk at offset {offset} from {node} failed "
            "sha256 verification"
        )


def _verify_whole(whole, expect_sha, remote, node) -> None:
    # End-to-end digest: the worker hashed the intermediate at map
    # time, so any corruption after the map — disk rot, a truncated
    # read, a lying chunk stream — surfaces here, not as wrong counts.
    if expect_sha is not None and whole.hexdigest() != expect_sha:
        raise IntegrityError(
            f"intermediate {remote} from {node} failed end-to-end sha256 "
            "verification (corrupted after map)"
        )


def _fetch_via_rpc(
    node, remote: str, expect_sha, stats: dict, f, whole,
    rpc, secret: bytes, chunk_bytes: int, offset: int = 0,
) -> None:
    """Chunk loop through an ``rpc`` callable: the pre-binary path, used
    when the caller injected an rpc (tests intercept every chunk there)
    or after a JSON-only worker answered the negotiation.  ``wire_bytes``
    counts the base64 text (the dominant term; exact wire framing is
    only visible on the socket path)."""
    while True:
        got = rpc(
            node,
            {"cmd": "fetch", "path": remote, "offset": offset,
             "max_bytes": chunk_bytes},
            secret,
        )
        if got.get("status") != "ok":
            raise MasterError(
                f"fetch failed on node {node}: {got.get('error')}"
            )
        b64 = got.get("data_b64", "")
        data = base64.b64decode(b64)
        _verify_chunk(got, data, node, offset)
        f.write(data)
        whole.update(data)
        offset += len(data)
        stats["bytes"] += len(data)
        stats["wire_bytes"] += len(b64)
        stats["chunks"] += 1
        if got.get("eof", True) or not data:
            break
    _verify_whole(whole, expect_sha, remote, node)


def _fetch_pipelined(
    node, remote: str, expect_sha, stats: dict, f, whole,
    secret: bytes, chunk_bytes: int, window: int, use_zlib: bool,
    rpc, timeout: float,
) -> None:
    """Windowed fetch over ONE connection: up to ``window`` chunk
    requests in flight, answered strictly in order by the worker.  The
    first reply tells binary support and the file size; a JSON reply
    means a pre-binary peer (which may close after one reply), so the
    transfer degrades to the per-request ``rpc`` loop."""
    faultplan.check_connect(node[0], node[1])
    with socket.create_connection(node, timeout=timeout) as sock:
        sock.settimeout(timeout)
        stamp = protocol.trace_stamp()  # chunk replies echo it in meta

        def send_req(off: int) -> None:
            req = {"cmd": "fetch", "path": remote, "offset": off,
                   "max_bytes": chunk_bytes, "bin": 1}
            if use_zlib:
                req["accept_zlib"] = True
            if stamp is not None:
                req[protocol.TRACE_KEY] = stamp
            protocol.send_frame(sock, req, secret)

        send_req(0)
        next_off = None  # unknown until the first reply carries total
        total = None
        expected = 0  # next offset we must receive
        inflight = 1
        while True:
            fr = protocol.recv_frame_ex(sock, secret)
            inflight -= 1
            obj = fr.obj
            if obj.get("status") != "ok":
                raise MasterError(
                    f"fetch failed on node {node}: {obj.get('error')}"
                )
            data = (
                fr.payload
                if fr.binary
                else base64.b64decode(obj.get("data_b64", ""))
            )
            got_off = int(obj.get("offset", expected))
            if got_off != expected:
                raise IntegrityError(
                    f"out-of-order fetch chunk from {node}: got offset "
                    f"{got_off}, expected {expected}"
                )
            _verify_chunk(obj, data, node, got_off)
            f.write(data)
            whole.update(data)
            expected += len(data)
            stats["bytes"] += len(data)
            stats["wire_bytes"] += fr.wire_bytes
            stats["chunks"] += 1
            stats["zlib"] = stats["zlib"] or fr.compressed
            if not fr.binary:
                # Pre-binary peer: drop to the per-request path for the
                # rest of the file (it may close this socket any time).
                # One chunk is already on disk.
                stats["binary"] = False
                if obj.get("eof", True) or not data:
                    _verify_whole(whole, expect_sha, remote, node)
                    return
                return _fetch_via_rpc(
                    node, remote, expect_sha, stats, f, whole,
                    rpc, secret, chunk_bytes, offset=expected,
                )
            if total is None:
                total = int(obj.get("total", 0))
                next_off = chunk_bytes
            # Keep the window full: schedule more chunk requests as long
            # as un-requested bytes remain.
            while inflight < window and next_off is not None and next_off < total:
                send_req(next_off)
                next_off += chunk_bytes
                inflight += 1
            if (obj.get("eof") or not data) and inflight == 0:
                break
        _verify_whole(whole, expect_sha, remote, node)


def fetch_file(
    node: tuple[str, int],
    remote: str,
    local: str,
    secret: bytes,
    expect_sha: str | None = None,
    rpc=None,
    rpc_timeout: float = 1800.0,
    use_binary: bool = True,
    use_zlib: bool = True,
    window: int = 4,
    chunk_bytes: int | None = None,
) -> dict:
    """One verified intermediate transfer; returns the per-fetch stats
    dict (payload/wire bytes, chunks, binary/zlib, elapsed, MB/s) that
    lands in ``JobResult.shards`` — also the microbench's measuring
    primitive (scripts/bench_dataplane.py).  A custom ``rpc`` routes
    every chunk through it (unpipelined) so tests can intercept."""
    # Clamp to the worker's own window cap: the pipelined scheduler
    # derives offsets from the REQUESTED size, so requesting more than
    # the worker will ever return (worker clamps to FETCH_CHUNK_MAX)
    # would desync offsets into a bogus out-of-order IntegrityError.
    chunk = max(1, min(int(chunk_bytes or protocol.FETCH_CHUNK),
                       protocol.FETCH_CHUNK_MAX))
    window = max(1, int(window))
    stats = {
        "node": list(node), "bytes": 0, "wire_bytes": 0, "chunks": 0,
        "binary": bool(use_binary and rpc is None),
        "zlib": False, "window": window, "elapsed_s": None, "mb_s": None,
    }
    t0 = time.perf_counter()
    whole = hashlib.sha256()
    rpc_fn = rpc or (lambda nd, rq, s: _rpc(nd, rq, s, timeout=rpc_timeout))
    # One span per transfer = one fetch-pipeline window on the timeline;
    # byte/throughput metrics aggregate across every fetch of the job.
    with obs.span(
        "master.fetch",
        node=f"{node[0]}:{node[1]}", path=remote,
        window=window, chunk_bytes=chunk,
    ):
        with open(local, "wb") as f:
            if rpc is None and use_binary:
                _fetch_pipelined(
                    node, remote, expect_sha, stats, f, whole,
                    secret, chunk, window, use_zlib, rpc_fn, rpc_timeout,
                )
            else:
                stats["binary"] = False
                _fetch_via_rpc(
                    node, remote, expect_sha, stats, f, whole,
                    rpc_fn, secret, chunk,
                )
    stats["elapsed_s"] = round(time.perf_counter() - t0, 6)
    if stats["elapsed_s"] > 0:
        stats["mb_s"] = round(stats["bytes"] / 1e6 / stats["elapsed_s"], 3)
    obs.metric_inc("fetch.bytes", stats["bytes"])
    if stats["mb_s"]:
        obs.metric_observe("fetch.mb_s", stats["mb_s"])
    return stats


class WorkerHealth:
    """Per-worker liveness with exponential backoff + deterministic jitter.

    A failure quarantines the worker for ``base_s * 2**(consecutive-1)``
    seconds (capped at ``cap_s``), stretched by up to ``jitter`` fraction
    of deterministic (seeded) noise so a fleet of masters doesn't re-probe
    a recovering worker in lockstep.  ``ok()`` clears the slate — the
    un-quarantine-on-recovery half of the contract.  Injectable ``clock``
    keeps the unit tests fake-clock deterministic (tests/test_faults.py).
    Thread-safe: the shard tasks and the heartbeat loop mutate it
    concurrently.
    """

    def __init__(
        self,
        n: int,
        clock=time.monotonic,
        base_s: float = 0.5,
        cap_s: float = 30.0,
        jitter: float = 0.5,
        seed: int = 0,
    ):
        self.n = n
        self.clock = clock
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self.seed = seed
        self._failures = [0] * n
        self._until = [0.0] * n
        self._lock = threading.Lock()

    def fail(self, idx: int) -> float:
        """Record a failure; returns the backoff applied (seconds)."""
        with self._lock:
            self._failures[idx] += 1
            f = self._failures[idx]
            back = min(self.cap_s, self.base_s * (2 ** (f - 1)))
            back *= 1.0 + self.jitter * self._unit(idx, f)
            self._until[idx] = self.clock() + back
            return back

    def ok(self, idx: int) -> None:
        with self._lock:
            self._failures[idx] = 0
            self._until[idx] = 0.0

    def healthy(self, idx: int) -> bool:
        """Never-failed-recently: not quarantined at all."""
        with self._lock:
            return self._failures[idx] == 0

    def probe_due(self, idx: int) -> bool:
        """Quarantined AND its backoff has expired: eligible for a
        heartbeat probe (or a direct work attempt, which doubles as one)."""
        with self._lock:
            return self._failures[idx] > 0 and self.clock() >= self._until[idx]

    def quarantined(self, idx: int) -> bool:
        with self._lock:
            return self._failures[idx] > 0 and self.clock() < self._until[idx]

    def failures(self, idx: int) -> int:
        with self._lock:
            return self._failures[idx]

    def _unit(self, idx: int, f: int) -> float:
        """Deterministic jitter in [0, 1): seeded, not wall-clock."""
        h = hashlib.sha256(f"{self.seed}:{idx}:{f}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64


class ShardStats:
    """Timing/attempt record for one shard (JobResult.shards).

    Each attempt dict additionally carries a ``fetch`` sub-dict once its
    intermediate transfer ran: payload/wire byte counts, chunk count,
    window, whether binary framing and zlib were used, elapsed seconds
    and MB/s — the per-node data-plane evidence (docs/DATAPLANE.md).
    """

    def __init__(self, shard: int):
        self.shard = shard
        self.attempts: list[dict] = []  # worker, speculative, t0, t1, outcome
        self.winner: int | None = None  # worker index that produced the file
        self.speculated = False
        self.elapsed_s: float | None = None

    def as_dict(self) -> dict:
        return {
            "shard": self.shard,
            "winner": self.winner,
            "speculated": self.speculated,
            "elapsed_s": self.elapsed_s,
            "attempts": list(self.attempts),
        }


class JobResult(list):
    """The collected local intermediate paths (list API unchanged for
    callers that only reduce), plus per-shard timing stats, the final
    health view, and — when telemetry was enabled — the job's merged
    cross-node trace."""

    def __init__(self, paths, shards: list[ShardStats], health: WorkerHealth,
                 trace=None):
        super().__init__(paths)
        self.shards = shards
        self.health = health
        self._trace = trace

    def timeline(self) -> dict | None:
        """The merged cross-node Chrome-trace document: master spans plus
        every worker's shipped span list, clock-offset-adjusted into the
        master clock under one trace_id (docs/OBSERVABILITY.md).  None
        when telemetry was disabled for the job.  Deliberately carries NO
        metrics snapshot: the job tracer's spans are per-job, but metrics
        are process-scoped (concurrent jobs share them) — the process
        snapshot belongs to ``obs.export`` (the master CLI's trace file),
        not to one job's timeline."""
        if self._trace is None:
            return None
        return self._trace.to_chrome()

    def dataplane(self) -> dict:
        """Aggregate data-plane stats over every completed fetch: what
        ``bench.py`` reports as the ``dataplane`` sub-dict."""
        fetches = [
            a["fetch"]
            for s in self.shards
            for a in s.attempts
            if isinstance(a.get("fetch"), dict)
        ]
        payload = sum(f.get("bytes", 0) for f in fetches)
        wire = sum(f.get("wire_bytes", 0) for f in fetches)
        elapsed = sum(f.get("elapsed_s") or 0.0 for f in fetches)
        return {
            "fetches": len(fetches),
            "payload_bytes": payload,
            "wire_bytes": wire,
            "chunks": sum(f.get("chunks", 0) for f in fetches),
            "binary": all(f.get("binary") for f in fetches) if fetches else False,
            "zlib": any(f.get("zlib") for f in fetches),
            "fetch_mb_s": round(payload / 1e6 / elapsed, 3) if elapsed > 0 else None,
            "compression_ratio": round(payload / wire, 3) if wire else None,
        }


def _heartbeat_loop(
    stop: threading.Event,
    health: WorkerHealth,
    cluster: list[tuple[str, int]],
    rpc,
    secret: bytes,
    interval: float,
) -> None:
    """Ping quarantined workers whose backoff expired; un-quarantine on a
    good pong, deepen the backoff otherwise.  Runs until the job ends."""
    while not stop.wait(interval):
        try:
            for idx in range(len(cluster)):
                if stop.is_set():
                    return
                if not health.probe_due(idx):
                    continue
                try:
                    resp = rpc(cluster[idx], {"cmd": "ping"}, secret)
                    if resp.get("pong"):
                        health.ok(idx)
                        logger.info(
                            "worker %d recovered; un-quarantined", idx
                        )
                    else:
                        health.fail(idx)
                except (OSError, MasterError, ValueError, PermissionError):
                    health.fail(idx)
        except Exception:  # noqa: BLE001 - a surprise here (health
            # bookkeeping, logging) must not kill the heartbeat: with it
            # dead, quarantined workers stay quarantined FOREVER and the
            # job narrows to the survivors one fault at a time.
            logger.warning(
                "heartbeat pass failed; retrying next interval",
                exc_info=True,
            )


def run_job(
    cluster: list[tuple[str, int]],
    input_file: str,
    secret: bytes,
    workdir: str | None = None,
    extra_args: list[str] | None = None,
    rpc=None,
    max_retries: int = 2,
    rpc_timeout: float = 1800.0,
    heartbeat_interval: float = 2.0,
    ping_timeout: float = 10.0,
    speculate_after: float | None = None,
    health: WorkerHealth | None = None,
    poll_s: float = 0.05,
    inter_format: str = "bin",
    use_binary: bool = True,
    use_zlib: bool = True,
    fetch_window: int = 4,
    fetch_chunk: int | None = None,
    max_parallel_fetch: int | None = None,
) -> JobResult:
    """Fan out map stages, collect + verify intermediates; returns a
    ``JobResult`` (local paths for the reduce, plus ``.shards`` stats).

    Data plane (docs/DATAPLANE.md): workers write packed binary KV
    intermediates (``inter_format="bin"``; ``"tsv"`` restores reference
    parity) and the master pulls them with ``fetch_window`` chunk
    requests pipelined down one connection per fetch, binary frames with
    raw (optionally zlib) payloads when the worker speaks them — a
    JSON-only worker transparently degrades to the base64 per-request
    path.  Concurrent fetches across nodes run on a bounded pool of
    ``max_parallel_fetch`` (default ``min(8, len(cluster))``).  A custom
    ``rpc`` (tests) routes every chunk through it instead, unpipelined.

    Each of the ``len(cluster)`` line-range shards tolerates up to
    ``max_retries`` FAILED attempts (each on a distinct worker) before the
    job fails with ``MasterError``.  ``speculate_after`` seconds after a
    shard's latest attempt started with no finisher, one speculative
    backup attempt launches on a different worker — first success wins
    (None disables speculation).  All waits are bounded: RPCs by
    ``rpc_timeout`` and the scheduler poll by ``poll_s``, so a straggling
    or injected-faulty worker can delay but never hang the job.
    """
    n = len(cluster)
    total = count_lines(input_file)
    per = -(-total // n) if total else 1
    workdir = workdir or tempfile.mkdtemp(prefix="locust_master_")
    os.makedirs(workdir, exist_ok=True)
    # Unique per-job intermediate names: concurrent jobs against the same
    # worker pool must not clobber each other's TSVs.
    job_id = uuid.uuid4().hex[:12]
    # Cross-node telemetry (docs/OBSERVABILITY.md): when a tracer is
    # active, every map request carries its trace_id + shard, workers run
    # under request-scoped child tracers and ship serialized span lists
    # back in their replies, and _ingest_worker_spans merges them —
    # shifted by the reply-time clock-offset estimate — into ONE
    # timeline, surfaced as JobResult.timeline().
    tracer = obs.current()
    obs.metric_set("job.workers", n)

    def _ingest_worker_spans(resp, node, t_recv: float) -> None:
        """Merge a reply's shipped spans (ok AND error replies carry
        them).  Offset estimate: the worker stamps its wall clock while
        building the reply, so worker_clock ≈ master t_recv minus the
        one-way reply latency — good to ~net/2, plenty for timelines."""
        if tracer is None or not isinstance(resp, dict):
            return
        spans = resp.get("spans")
        if not spans:
            return
        clock = resp.get("clock")
        offset = float(clock) - t_recv if isinstance(clock, (int, float)) else 0.0
        tracer.ingest(
            spans, offset_s=offset, process=f"worker {node[0]}:{node[1]}"
        )

    health = health or WorkerHealth(n)
    if inter_format not in ("tsv", "bin"):
        raise ValueError(f"unknown inter_format {inter_format!r}")
    # An injected rpc (tests) must see EVERY chunk — the socket-pipelined
    # path would bypass it, so it forces the per-request loop.
    rpc_is_default = rpc is None
    if rpc is None:
        def rpc(node, req, s, _to=rpc_timeout):  # noqa: E306
            return _rpc(node, req, s, timeout=_to)

        # Heartbeat pings are LIVENESS checks: a worker that accepts TCP
        # but never replies (the wedged-tunnel mode, CLAUDE.md) must cost
        # the serial probe loop seconds, not the map-stage timeout —
        # otherwise one hung ping disables recovery probing for the rest
        # of the job (code review, this PR).
        def ping_rpc(node, req, s, _to=ping_timeout):
            return _rpc(node, req, s, timeout=_to)
    else:
        ping_rpc = rpc

    # Bounded fetch pool: shard attempt threads hand their transfer to
    # this pool, so at most ``max_parallel_fetch`` node fetches run at
    # once however many shards are in flight (each fetch is already
    # pipelined internally; unbounded concurrency would just thrash the
    # master's NIC and disk).
    fetch_pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=int(max_parallel_fetch or min(8, max(1, n))),
        thread_name_prefix="locust-fetch",
    )

    def fetch_chunked(
        node, remote: str, local: str, expect_sha: str | None
    ) -> dict:
        """One intermediate transfer through the bounded fetch pool;
        returns the per-fetch stats dict (JobResult.shards evidence)."""
        try:
            fut = fetch_pool.submit(
                _scoped_call, tracer, fetch_file,
                node, remote, local, secret,
                expect_sha=expect_sha,
                rpc=None if rpc_is_default else rpc,
                rpc_timeout=rpc_timeout,
                use_binary=use_binary,
                use_zlib=use_zlib,
                window=fetch_window,
                chunk_bytes=fetch_chunk,
            )
        except RuntimeError as e:
            # An abandoned speculative/retry loser can reach here AFTER
            # the job finished and the pool shut down: a failed attempt,
            # not an unhandled thread death.
            raise MasterError(f"fetch pool closed (job ended): {e}")
        # Bounded wait (R013): the fetch itself is bounded by per-socket
        # timeouts, but a saturated pool queues this future behind other
        # transfers — one rpc_timeout of queueing slack on top of the
        # transfer's own budget keeps a wedged peer from parking this
        # attempt thread forever.
        try:
            return fut.result(timeout=rpc_timeout * 2)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            raise MasterError(
                f"fetch of {remote} from {node} did not complete within "
                f"{rpc_timeout * 2:.0f}s (pool saturated or peer wedged)"
            )

    def try_shard(shard: int, node_idx: int, attempt: int) -> tuple[str, dict]:
        node = cluster[node_idx]
        start, end = shard * per, min((shard + 1) * per, total)
        # Attempt-unique remote/local paths: a speculative loser must not
        # clobber the winner's file (loopback runs share one /tmp).
        ext = "kvb" if inter_format == "bin" else "tsv"
        inter = f"/tmp/locust_{job_id}_shard{shard}_a{attempt}.{ext}"
        req = {
            "cmd": "map",
            "file": input_file,
            "line_start": start,
            "line_end": end,
            "node_num": shard,
            "intermediate": inter,
            "inter_format": inter_format,
            "extra_args": extra_args or [],
        }
        # Attempt threads run scoped to the job's tracer (_run_scoped /
        # attempt()), so the one stamp helper sees the right trace_id.
        stamp = protocol.trace_stamp(shard)
        if stamp is not None:
            req[protocol.TRACE_KEY] = stamp
        with obs.span("master.map_rpc", shard=shard, worker=node_idx,
                      attempt=attempt):
            resp = rpc(node, req, secret)
        _ingest_worker_spans(resp, node, time.time())
        if resp.get("status") != "ok":
            raise MasterError(
                f"map failed on node {node}: rc={resp.get('returncode')} "
                f"err={resp.get('error', '')}\n{resp.get('log', '')}"
            )
        local = os.path.join(workdir, f"node{shard}.a{attempt}.{ext}")
        fstats = fetch_chunked(node, inter, local, resp.get("sha256"))
        return local, fstats

    def pick_node(shard: int, tried: set[int], busy: set[int]) -> int | None:
        """Next worker for this shard: home node first, then rotation;
        healthy workers before quarantine-expired ones (a work attempt on
        an expired-quarantine worker doubles as its heartbeat probe);
        never one still inside its backoff window or already running an
        attempt for this shard.  Once EVERY worker has been tried, a
        recovered (or probe-eligible) one may be re-tried: two transient
        flaps must not exhaust a two-worker pool while retry budget
        remains — total attempts stay bounded by ``max_retries``."""
        order = [(shard + k) % n for k in range(n)]
        for idx in order:
            if idx not in tried and idx not in busy and health.healthy(idx):
                return idx
        for idx in order:
            if idx not in tried and idx not in busy and health.probe_due(idx):
                return idx
        if all(i in tried for i in order):
            for idx in order:
                if idx not in busy and (
                    health.healthy(idx) or health.probe_due(idx)
                ):
                    return idx
        return None

    def one(shard: int) -> tuple[str, ShardStats]:
        stats = ShardStats(shard)
        shard_t0 = time.perf_counter()
        done_q: queue.Queue = queue.Queue()
        tried: set[int] = set()
        pending: dict[int, dict] = {}  # attempt id -> {"worker", "t0", ...}
        seq = 0
        failed_attempts = 0
        last_err: Exception | None = None
        last_launch = time.perf_counter()
        speculation_spent = False

        def launch(speculative: bool) -> bool:
            nonlocal seq, last_launch
            busy = {r["worker"] for r in pending.values()}
            node_idx = pick_node(shard, tried, busy)
            if node_idx is None:
                return False
            tried.add(node_idx)
            aid = seq
            seq += 1
            rec = {
                "worker": node_idx,
                "speculative": speculative,
                "t0": time.perf_counter() - shard_t0,
                "t1": None,
                "outcome": "running",
            }
            stats.attempts.append(rec)
            last_launch = time.perf_counter()

            def attempt() -> None:
                try:
                    local = _scoped_call(
                        tracer, try_shard, shard, node_idx, aid
                    )
                    done_q.put((aid, node_idx, rec, local, None))
                except (MasterError, OSError, ValueError) as e:
                    done_q.put((aid, node_idx, rec, None, e))
                except Exception as e:  # noqa: BLE001 - an attempt thread
                    # must NEVER die unhandled (pytest turns that into a
                    # spurious failure in whatever test runs next); an
                    # unexpected type is still just a failed attempt.
                    done_q.put(
                        (aid, node_idx, rec, None,
                         MasterError(f"{type(e).__name__}: {e}"))
                    )

            threading.Thread(target=attempt, daemon=True).start()
            pending[aid] = rec
            if speculative:
                stats.speculated = True
                logger.info(
                    "shard %d straggling; speculative backup on worker %d",
                    shard, node_idx,
                )
            return True

        def launch_or_wait() -> bool:
            """Launch a retry, WAITING (bounded by the backoff cap) for a
            quarantined worker to become probe-eligible: a cluster-wide
            transient flap — every worker backing off at once — must cost
            seconds of patience, not the whole job.  Returns False only
            when the bounded wait expired with no launchable worker."""
            deadline = time.perf_counter() + health.cap_s + 1.0
            while time.perf_counter() < deadline:
                if launch(speculative=False):
                    return True
                time.sleep(poll_s)
            return False

        if not launch_or_wait():
            raise MasterError(
                f"shard {shard} failed on every tried worker "
                f"(max_retries={max_retries}): no live worker to start on"
            )
        while True:
            try:
                aid, node_idx, rec, local, err = done_q.get(timeout=poll_s)
            except queue.Empty:
                if (
                    speculate_after is not None
                    and not speculation_spent
                    and pending
                    and time.perf_counter() - last_launch >= speculate_after
                ):
                    # One backup per shard: Dean & Ghemawat's backup tasks,
                    # not an unbounded fork-bomb.  A failed pick (no spare
                    # worker) also spends the budget — re-polling an empty
                    # pool every tick buys nothing.
                    speculation_spent = True
                    launch(speculative=True)
                continue
            rec["t1"] = time.perf_counter() - shard_t0
            if err is None:
                local, rec["fetch"] = local
                rec["outcome"] = "ok"
                health.ok(node_idx)
                for other in pending.values():
                    if other is not rec and other["outcome"] == "running":
                        other["outcome"] = "cancelled"  # abandoned loser
                stats.winner = node_idx
                stats.elapsed_s = time.perf_counter() - shard_t0
                return local, stats
            pending.pop(aid, None)
            rec["outcome"] = (
                "integrity" if isinstance(err, IntegrityError) else "error"
            )
            last_err = err
            failed_attempts += 1
            back = health.fail(node_idx)
            logger.warning(
                "shard %d attempt on worker %d failed (%s); worker backed "
                "off %.2fs", shard, node_idx, err, back,
            )
            if failed_attempts > max_retries and not pending:
                break
            if not pending and not launch_or_wait():
                break
        raise MasterError(
            f"shard {shard} failed on every tried worker "
            f"(max_retries={max_retries}): {last_err}"
        )

    stop = threading.Event()
    hb = threading.Thread(
        target=_heartbeat_loop,
        args=(stop, health, cluster, ping_rpc, secret, heartbeat_interval),
        daemon=True,
    )
    hb.start()
    try:
        with obs.span("job.run", job=job_id, workers=n, input=input_file):
            with concurrent.futures.ThreadPoolExecutor(max_workers=n) as ex:
                # Shard-driver threads likewise pin to the job's tracer.
                results = list(
                    ex.map(
                        lambda shard: _scoped_call(tracer, one, shard),
                        range(n),
                    )
                )
    finally:
        stop.set()
        fetch_pool.shutdown(wait=False)
    paths = [p for p, _ in results]
    shards = [s for _, s in results]
    for s in shards:
        logger.info(
            "shard %d: %.3fs on worker %s (%d attempt(s)%s)",
            s.shard, s.elapsed_s or -1.0, s.winner, len(s.attempts),
            ", speculated" if s.speculated else "",
        )
    return JobResult(paths, shards, health, trace=tracer)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="locust-master")
    p.add_argument("cluster_file", help="lines of 'ip port' (reference README.md:18-22)")
    p.add_argument("input_file")
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    p.add_argument("--workdir", default=None)
    p.add_argument("--max-retries", type=int, default=2)
    p.add_argument("--speculate-after", type=float, default=None,
                   help="seconds before a straggling shard gets a "
                        "speculative backup attempt (default: disabled)")
    p.add_argument("--inter-format", choices=["tsv", "bin"], default="bin",
                   help="intermediate format workers write (bin = packed "
                        "binary KV, docs/DATAPLANE.md; tsv = reference parity)")
    p.add_argument("--fetch-window", type=int, default=4,
                   help="chunk requests kept in flight per node fetch")
    p.add_argument("--fetch-chunk", type=int, default=None,
                   help=f"bytes per fetch chunk (default {protocol.FETCH_CHUNK})")
    p.add_argument("--json-plane", action="store_true",
                   help="disable binary framing: base64 JSON chunks "
                        "(interop/debugging)")
    p.add_argument("--no-zlib", action="store_true",
                   help="disable wire compression of fetch chunks")
    p.add_argument("--fault-plan", default=None,
                   help="chaos-test fault plan: JSON text or a path "
                        f"(also ${faultplan.ENV_VAR}); see docs/FAULTS.md")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="structured telemetry: record master spans, merge "
                        "every worker's shipped map spans under one "
                        "trace_id, and export the job as Chrome-trace/"
                        "Perfetto JSON to FILE (docs/OBSERVABILITY.md)")
    args, passthrough = p.parse_known_args(argv)
    faultplan.install(args.fault_plan)
    if args.trace_out:
        obs.enable(process="master")
    try:
        return _main(args, passthrough)
    finally:
        if args.trace_out:
            # Export on EVERY path — a failed chaos run's timeline is
            # the one worth reading; and a broken export must not mask
            # the run's own outcome (telemetry never takes down a job).
            try:
                obs.export(args.trace_out)
                print(f"[master] trace written to {args.trace_out}",
                      file=sys.stderr)
            except OSError as e:
                print(f"[master] trace export to {args.trace_out} "
                      f"failed: {e}", file=sys.stderr)
            obs.disable()


def _main(args, passthrough) -> int:
    secret = os.environ.get(args.secret_env, "").encode()
    if not secret:
        print(f"error: set ${args.secret_env}", file=sys.stderr)
        return 2
    cluster = protocol.parse_cluster_file(args.cluster_file)
    print(f"[master] {len(cluster)} worker(s)", file=sys.stderr)
    tsvs = run_job(cluster, args.input_file, secret,
                   workdir=args.workdir, extra_args=passthrough,
                   max_retries=args.max_retries,
                   speculate_after=args.speculate_after,
                   inter_format=args.inter_format,
                   use_binary=not args.json_plane,
                   use_zlib=not args.no_zlib,
                   fetch_window=args.fetch_window,
                   fetch_chunk=args.fetch_chunk)
    for s in tsvs.shards:
        print(
            f"[master] shard {s.shard}: {s.elapsed_s:.3f}s on worker "
            f"{s.winner}, {len(s.attempts)} attempt(s)"
            + (", speculated" if s.speculated else ""),
            file=sys.stderr,
        )
    dp = tsvs.dataplane()
    print(
        f"[master] dataplane: {dp['payload_bytes']}B payload / "
        f"{dp['wire_bytes']}B wire in {dp['chunks']} chunk(s), "
        f"binary={dp['binary']} zlib={dp['zlib']} "
        f"fetch={dp['fetch_mb_s']} MB/s",
        file=sys.stderr,
    )

    # Local reduce over all collected TSVs (stage 2; re-sorts — Q6 fix).
    from locust_tpu import cli

    reduce_args = [args.input_file, "-1", "-1", "0", "2"]
    for t in tsvs:
        reduce_args += ["-i", t]
    # The exported timeline (main()'s finally) then holds master job
    # spans + every worker's map spans + the in-process reduce's spans.
    return cli.main(reduce_args + passthrough)


if __name__ == "__main__":
    raise SystemExit(main())
