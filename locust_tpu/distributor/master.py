"""Distributor master: the launcher the reference documents but never shipped.

The reference README promises "the provided bash script will launch the
MapReduce program for all nodes" over a cluster file of ``ip port`` lines
(reference README.md:18-24) — no such script exists in the repo
(SURVEY.md C12).  This module implements that role:

  1. parse the cluster file (protocol.parse_cluster_file),
  2. shard the input by line ranges — the reference's per-node
     ``[line_start, line_end)`` CLI contract (main.cu:369-374),
  3. fan the staged map out to all workers in parallel,
  4. collect each node's intermediate TSV over the authenticated channel
     (the transport step missing from the reference, SURVEY.md §3.2),
  5. run the reduce stage locally over all collected TSVs — which re-sorts,
     fixing the reference's unsorted-reduce-input bug (Q6).
"""

from __future__ import annotations

import argparse
import base64
import concurrent.futures
import os
import socket
import sys
import tempfile
import uuid

from locust_tpu.distributor import protocol


class MasterError(RuntimeError):
    pass


def _rpc(node: tuple[str, int], req: dict, secret: bytes, timeout: float = 1800.0) -> dict:
    with socket.create_connection(node, timeout=timeout) as sock:
        protocol.send_frame(sock, req, secret)
        return protocol.recv_frame(sock, secret)


def count_lines(path: str) -> int:
    """Streaming line count (O(1) memory; multi-GB corpora are fine)."""
    n = 0
    last = b"\n"
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            n += chunk.count(b"\n")
            last = chunk[-1:]
    if last != b"\n":
        n += 1  # trailing fragment counts (Q1 semantics)
    return n


def run_job(
    cluster: list[tuple[str, int]],
    input_file: str,
    secret: bytes,
    workdir: str | None = None,
    extra_args: list[str] | None = None,
    rpc=_rpc,
) -> list[str]:
    """Fan out map stages, collect TSVs; returns local TSV paths for reduce."""
    n = len(cluster)
    total = count_lines(input_file)
    per = -(-total // n) if total else 1
    workdir = workdir or tempfile.mkdtemp(prefix="locust_master_")
    os.makedirs(workdir, exist_ok=True)
    # Unique per-job intermediate names: concurrent jobs against the same
    # worker pool must not clobber each other's TSVs.
    job_id = uuid.uuid4().hex[:12]

    def one(i_node):
        i, node = i_node
        start, end = i * per, min((i + 1) * per, total)
        inter = f"/tmp/locust_{job_id}_node{i}.tsv"
        resp = rpc(
            node,
            {
                "cmd": "map",
                "file": input_file,
                "line_start": start,
                "line_end": end,
                "node_num": i,
                "intermediate": inter,
                "extra_args": extra_args or [],
            },
            secret,
        )
        if resp.get("status") != "ok":
            raise MasterError(
                f"map failed on node {node}: rc={resp.get('returncode')} "
                f"err={resp.get('error', '')}\n{resp.get('log', '')}"
            )
        fetched = rpc(node, {"cmd": "fetch", "path": inter}, secret)
        if fetched.get("status") != "ok":
            raise MasterError(f"fetch failed on node {node}: {fetched.get('error')}")
        local = os.path.join(workdir, f"node{i}.tsv")
        with open(local, "wb") as f:
            f.write(base64.b64decode(fetched["data_b64"]))
        return local

    with concurrent.futures.ThreadPoolExecutor(max_workers=n) as ex:
        return list(ex.map(one, enumerate(cluster)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="locust-master")
    p.add_argument("cluster_file", help="lines of 'ip port' (reference README.md:18-22)")
    p.add_argument("input_file")
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    p.add_argument("--workdir", default=None)
    args, passthrough = p.parse_known_args(argv)
    secret = os.environ.get(args.secret_env, "").encode()
    if not secret:
        print(f"error: set ${args.secret_env}", file=sys.stderr)
        return 2
    cluster = protocol.parse_cluster_file(args.cluster_file)
    print(f"[master] {len(cluster)} worker(s)", file=sys.stderr)
    tsvs = run_job(cluster, args.input_file, secret,
                   workdir=args.workdir, extra_args=passthrough)

    # Local reduce over all collected TSVs (stage 2; re-sorts — Q6 fix).
    from locust_tpu import cli

    reduce_args = [args.input_file, "-1", "-1", "0", "2"]
    for t in tsvs:
        reduce_args += ["-i", t]
    return cli.main(reduce_args + passthrough)


if __name__ == "__main__":
    raise SystemExit(main())
