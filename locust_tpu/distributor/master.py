"""Distributor master: the launcher the reference documents but never shipped.

The reference README promises "the provided bash script will launch the
MapReduce program for all nodes" over a cluster file of ``ip port`` lines
(reference README.md:18-24) — no such script exists in the repo
(SURVEY.md C12).  This module implements that role:

  1. parse the cluster file (protocol.parse_cluster_file),
  2. shard the input by line ranges — the reference's per-node
     ``[line_start, line_end)`` CLI contract (main.cu:369-374),
  3. fan the staged map out to all workers in parallel,
  4. collect each node's intermediate TSV over the authenticated channel
     (the transport step missing from the reference, SURVEY.md §3.2) —
     streamed in bounded offset-addressed chunks, so intermediates larger
     than one protocol frame round-trip fine,
  5. run the reduce stage locally over all collected TSVs — which re-sorts,
     fixing the reference's unsorted-reduce-input bug (Q6).

Fault tolerance (VERDICT r2 missing #6 — the reference has none, its slave
ACKs unconditionally, slave.py:19-20): a shard whose worker fails (dead
connection, timeout, non-zero map exit) is REASSIGNED to the next live
worker, bounded by ``max_retries``; a worker that failed is quarantined
for the rest of the job.  Line-range shards are deterministic and
idempotent (same [start, end) slice on any node produces the same TSV), so
re-running a shard elsewhere is always safe.
"""

from __future__ import annotations

import argparse
import base64
import concurrent.futures
import logging
import os
import socket
import sys
import tempfile
import threading
import uuid

from locust_tpu.distributor import protocol
from locust_tpu.io.loader import count_lines

logger = logging.getLogger("locust_tpu")


class MasterError(RuntimeError):
    pass


def _rpc(node: tuple[str, int], req: dict, secret: bytes, timeout: float = 1800.0) -> dict:
    with socket.create_connection(node, timeout=timeout) as sock:
        protocol.send_frame(sock, req, secret)
        return protocol.recv_frame(sock, secret)


def run_job(
    cluster: list[tuple[str, int]],
    input_file: str,
    secret: bytes,
    workdir: str | None = None,
    extra_args: list[str] | None = None,
    rpc=_rpc,
    max_retries: int = 2,
) -> list[str]:
    """Fan out map stages, collect TSVs; returns local TSV paths for reduce.

    Each of the ``len(cluster)`` line-range shards is tried on up to
    ``max_retries + 1`` distinct live workers before the job fails.
    """
    n = len(cluster)
    total = count_lines(input_file)
    per = -(-total // n) if total else 1
    workdir = workdir or tempfile.mkdtemp(prefix="locust_master_")
    os.makedirs(workdir, exist_ok=True)
    # Unique per-job intermediate names: concurrent jobs against the same
    # worker pool must not clobber each other's TSVs.
    job_id = uuid.uuid4().hex[:12]
    dead: set[int] = set()
    dead_lock = threading.Lock()

    def fetch_chunked(node, remote: str, local: str) -> None:
        offset = 0
        with open(local, "wb") as f:
            while True:
                got = rpc(
                    node,
                    {"cmd": "fetch", "path": remote, "offset": offset},
                    secret,
                )
                if got.get("status") != "ok":
                    raise MasterError(
                        f"fetch failed on node {node}: {got.get('error')}"
                    )
                data = base64.b64decode(got["data_b64"])
                f.write(data)
                offset += len(data)
                if got.get("eof", True) or not data:
                    return

    def try_shard(shard: int, node_idx: int) -> str:
        node = cluster[node_idx]
        start, end = shard * per, min((shard + 1) * per, total)
        inter = f"/tmp/locust_{job_id}_node{shard}.tsv"
        resp = rpc(
            node,
            {
                "cmd": "map",
                "file": input_file,
                "line_start": start,
                "line_end": end,
                "node_num": shard,
                "intermediate": inter,
                "extra_args": extra_args or [],
            },
            secret,
        )
        if resp.get("status") != "ok":
            raise MasterError(
                f"map failed on node {node}: rc={resp.get('returncode')} "
                f"err={resp.get('error', '')}\n{resp.get('log', '')}"
            )
        local = os.path.join(workdir, f"node{shard}.tsv")
        fetch_chunked(node, inter, local)
        return local

    def one(shard: int) -> str:
        last_err: Exception | None = None
        tried: set[int] = set()
        for _ in range(max_retries + 1):
            with dead_lock:
                # Prefer the shard's home node, then rotate; skip workers
                # already dead or already tried for this shard.
                alive = [
                    (shard + k) % n
                    for k in range(n)
                    if (shard + k) % n not in dead
                    and (shard + k) % n not in tried
                ]
            if not alive:
                break
            node_idx = alive[0]
            tried.add(node_idx)
            try:
                return try_shard(shard, node_idx)
            except (MasterError, OSError) as e:
                last_err = e
                with dead_lock:
                    dead.add(node_idx)
                logger.warning(
                    "shard %d failed on worker %d (%s); reassigning",
                    shard,
                    node_idx,
                    e,
                )
        raise MasterError(
            f"shard {shard} failed on every tried worker "
            f"(max_retries={max_retries}): {last_err}"
        )

    with concurrent.futures.ThreadPoolExecutor(max_workers=n) as ex:
        return list(ex.map(one, range(n)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="locust-master")
    p.add_argument("cluster_file", help="lines of 'ip port' (reference README.md:18-22)")
    p.add_argument("input_file")
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    p.add_argument("--workdir", default=None)
    args, passthrough = p.parse_known_args(argv)
    secret = os.environ.get(args.secret_env, "").encode()
    if not secret:
        print(f"error: set ${args.secret_env}", file=sys.stderr)
        return 2
    cluster = protocol.parse_cluster_file(args.cluster_file)
    print(f"[master] {len(cluster)} worker(s)", file=sys.stderr)
    tsvs = run_job(cluster, args.input_file, secret,
                   workdir=args.workdir, extra_args=passthrough)

    # Local reduce over all collected TSVs (stage 2; re-sorts — Q6 fix).
    from locust_tpu import cli

    reduce_args = [args.input_file, "-1", "-1", "0", "2"]
    for t in tsvs:
        reduce_args += ["-i", t]
    return cli.main(reduce_args + passthrough)


if __name__ == "__main__":
    raise SystemExit(main())
