"""Backend selection: resilient TPU init with CPU fallback.

The ambient environment may inject a remote-TPU PJRT plugin ("axon", one
real chip behind a high-latency tunnel) into every interpreter via
sitecustomize.  Two failure modes were observed in production:

  * the plugin raises ``UNAVAILABLE: TPU backend setup/compile error``
    during init (the round-1 bench failure, BENCH_r01.json), or
  * init *hangs* indefinitely — and because jax initializes ALL registered
    plugins on first backend use even when ``JAX_PLATFORMS=cpu``, the hang
    takes down pure-CPU runs too (the round-1 CLI hang).

The reference has no analog (single local GPU, CUDA init either works or
aborts, reference MapReduce/src/main.cu:393); on a remote-accelerator
tunnel, resilience is part of the driver's job.  Strategy:

  * Never initialize a possibly-wedged backend in-process first.  Probe it
    in a SUBPROCESS with a hard timeout and bounded retries; a wedged
    tunnel kills the child, not us.
  * CPU mode deregisters the TPU plugin factory *before* first backend
    use, so a wedged tunnel cannot stall a CPU run.

``select_backend()`` must run before anything touches a jax backend
(``jax.devices()``, ``jnp.asarray`` on a concrete value, jit execution).
Plugin *registration* happens at import; *initialization* is lazy — the
window where deregistration works.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time

logger = logging.getLogger("locust_tpu")

# jax's own backend factories.  Only THIRD-PARTY PJRT plugins (e.g. the
# injected remote-TPU tunnel "axon") get deregistered by force_cpu: jax
# initializes those eagerly even under JAX_PLATFORMS=cpu, whereas the
# built-ins respect the platform pin — and popping the built-in "tpu"
# factory breaks later mlir platform registration (checkify import).
_BUILTIN_FACTORIES = ("cpu", "interpreter", "tpu", "cuda", "rocm", "gpu", "metal")

# Probe results are cached in timestamp markers so back-to-back invocations
# (CLI runs, distributor workers, bench retries) neither pay a duplicate
# child-process backend init (tens of seconds on a remote tunnel) nor
# re-probe a known-down tunnel (minutes of retry budget per run).  Markers
# live in a 0700 per-user cache dir, not world-shared /tmp, so another
# local user can neither pre-create them to poison probe results nor plant
# a symlink for _touch to follow (ADVICE r2, low #2).


def _marker_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    d = os.path.join(base, "locust_tpu")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
    except OSError:  # pragma: no cover - markers are best-effort
        pass
    return d


_PROBE_OK_MARKER = os.path.join(_marker_dir(), "probe_ok")
_PROBE_OK_TTL_S = 300.0
_PROBE_FAIL_MARKER = os.path.join(_marker_dir(), "probe_fail")
_PROBE_FAIL_TTL_S = 120.0

_PROBE_SRC = (
    "import jax; d = jax.devices(); "
    "print('PLATFORM=' + d[0].platform, flush=True)"
)


def force_cpu() -> None:
    """Pin this process to the XLA CPU backend, immune to a wedged TPU tunnel.

    Deregisters every non-CPU PJRT plugin factory (still possible while
    backends are uninitialized) and pins ``jax_platforms=cpu``.  Safe to
    call more than once; a no-op after a backend initialized (jax then
    keeps whatever it has).
    """
    try:
        import jax
        import jax._src.xla_bridge as xb

        for name in list(xb._backend_factories):
            if name not in _BUILTIN_FACTORIES:
                xb._backend_factories.pop(name, None)
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # pragma: no cover - defensive: never block a run
        logger.warning("force_cpu: could not adjust jax backends: %s", e)


def _unpin_platforms() -> None:
    """Clear any CPU pin so a tpu selection actually runs on the accelerator.

    An ambient ``JAX_PLATFORMS=cpu`` (or an earlier ``force_cpu`` config
    update) would otherwise make the real run silently execute on CPU after
    a passing probe — the probe child strips the pin, the parent must too.
    ``None`` restores jax's default plugin-priority resolution.
    """
    os.environ.pop("JAX_PLATFORMS", None)
    try:
        import jax

        jax.config.update("jax_platforms", None)
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("could not unpin jax_platforms: %s", e)


def probe_tpu(
    timeout_s: float = 120.0, retries: int = 3, backoff_s: float = 5.0
) -> tuple[bool, str]:
    """Check from a SUBPROCESS whether a non-CPU backend initializes.

    Returns (ok, detail).  ``ok`` is True iff a child process ran
    ``jax.devices()`` to completion within ``timeout_s`` and the default
    platform is not CPU.  Retries with linear backoff — the round-1
    failure (BENCH_r01.json rc=1) was a transient tunnel UNAVAILABLE.
    """
    for marker, ttl, ok in (
        (_PROBE_OK_MARKER, _PROBE_OK_TTL_S, True),
        (_PROBE_FAIL_MARKER, _PROBE_FAIL_TTL_S, False),
    ):
        try:
            age = time.time() - os.path.getmtime(marker)
        except OSError:
            continue
        if 0 <= age < ttl:
            word = "up" if ok else "down"
            return ok, f"cached probe: backend {word} ({age:.0f}s ago)"
    detail = "no attempts"
    env = dict(os.environ)
    # The probe must see the ambient TPU config, not a CPU pin.
    env.pop("JAX_PLATFORMS", None)
    for attempt in range(max(1, retries)):
        if attempt:
            time.sleep(backoff_s * attempt)
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                env=env,
            )
        except subprocess.TimeoutExpired:
            detail = f"attempt {attempt + 1}: init timed out after {timeout_s:.0f}s"
            logger.warning("probe_tpu: %s", detail)
            continue
        dt = time.perf_counter() - t0
        if proc.returncode == 0 and "PLATFORM=" in proc.stdout:
            platform = proc.stdout.rsplit("PLATFORM=", 1)[1].strip()
            if platform != "cpu":
                _touch(_PROBE_OK_MARKER, platform)
                return True, f"{platform} backend up ({dt:.1f}s init)"
            # Cache the negative result too: on a CPU-only host every
            # auto-mode run would otherwise re-pay a full subprocess jax
            # init per invocation (ADVICE r2, low #3).
            _touch(_PROBE_FAIL_MARKER, "only the CPU backend is available")
            return False, "only the CPU backend is available"
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        detail = f"attempt {attempt + 1}: rc={proc.returncode} {tail[-1] if tail else ''}"
        logger.warning("probe_tpu: %s", detail)
    _touch(_PROBE_FAIL_MARKER, detail)
    return False, detail


def _touch(path: str, content: str) -> None:
    try:
        with open(path, "w") as f:
            f.write(content)
    except OSError:  # pragma: no cover - markers are best-effort
        pass


def _eager_init(timeout_s: float) -> str:
    """Initialize the jax backend NOW, under a hang-watchdog.

    The probe is a different process: this process's own init can still
    hang if the tunnel wedges in between (or a cached ok-marker was
    trusted).  A hang here would otherwise be unbounded — the round-1 CLI
    failure mode — so a daemon timer turns it into a bounded, explained
    exit.  With ``jax_platforms`` unpinned (None), a plugin that fails
    FAST is skipped by jax's default resolution and this returns "cpu"
    instead of raising — callers decide whether that's acceptable.
    """
    done = threading.Event()

    def watch() -> None:  # locust: noqa[R017] the exit is in a finally — the watchdog cannot die without firing; a broad except that _exit()s would turn a print failure into a spurious abort
        if not done.wait(timeout_s):
            try:
                print(
                    f"locust_tpu: backend init exceeded {timeout_s:.0f}s "
                    "(wedged TPU tunnel?); aborting. "
                    "Re-run with backend=cpu.",
                    file=sys.stderr,
                    flush=True,
                )
            finally:
                os._exit(3)

    threading.Thread(target=watch, daemon=True).start()
    try:
        import jax

        platform = jax.devices()[0].platform
    finally:
        done.set()
    return platform


def select_backend(
    mode: str = "auto",
    probe_timeout_s: float = 120.0,
    retries: int = 3,
    init_timeout_s: float | None = None,
) -> str:
    """Resolve AND initialize the backend for this process: "cpu" or "tpu".

    Modes:
      * ``cpu``  — pin CPU, deregister the TPU plugin (never hangs).
      * ``tpu``  — require an accelerator; RuntimeError if the probe fails
        or this process's own init lands on CPU anyway.
      * ``auto`` — accelerator if the probe passes, else CPU fallback.

    An ambient ``JAX_PLATFORMS=cpu`` forces CPU in auto mode: that env var
    is the user's explicit ask and round 1 showed it must actually work
    when the tunnel is down (VERDICT.md weak #1).

    On a tpu selection the backend is initialized HERE, under a watchdog
    (``init_timeout_s``, default ``probe_timeout_s + 60``) that exits the
    process rather than hanging forever if the tunnel wedged after the
    probe (or a cached probe marker was trusted).
    """
    if mode not in ("auto", "cpu", "tpu"):
        raise ValueError(f"backend mode must be auto|cpu|tpu, got {mode!r}")
    if mode == "cpu" or (
        mode == "auto" and os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
    ):
        force_cpu()
        return "cpu"

    ok, detail = probe_tpu(timeout_s=probe_timeout_s, retries=retries)
    if ok:
        logger.info("select_backend: %s", detail)
        _unpin_platforms()
        platform = _eager_init(init_timeout_s or probe_timeout_s + 60)
        if platform != "cpu":
            return "tpu"
        detail = "probe passed but this process's init landed on CPU"
    if mode == "tpu":
        raise RuntimeError(f"TPU backend required but unavailable: {detail}")
    logger.warning("select_backend: falling back to CPU (%s)", detail)
    force_cpu()
    return "cpu"


class CircuitBreaker:
    """Consecutive-failure circuit breaker for accelerator dispatches.

    A passing probe does NOT mean the window survives: on 2026-07-31 the
    axon tunnel wedged between a 2.4 s-init probe and the dispatch 60 s
    later (CLAUDE.md).  Per-run retries alone turn that into minutes of
    timeout ladders on EVERY dispatch; the breaker remembers instead
    (Nygard, "Release It!", the canonical stability pattern):

      * ``closed``    — primary dispatches flow; consecutive failures
        count, a success resets the count;
      * ``open``      — ``threshold`` consecutive failures trip it: the
        primary is ineligible (``allow()`` is False) for ``cooldown_s``,
        callers run their fallback (CPU, resumed from the last
        checkpoint — engine.run_checkpointed);
      * ``half_open`` — cooldown over: ``allow()`` returns True exactly
        ONCE (the probe dispatch); success closes the breaker, failure
        re-opens it for another full cooldown.

    Thread-safe; transitions emit ``backend.breaker_*`` instant events so
    a trace timeline shows the trip, the probe and the recovery
    (docs/OBSERVABILITY.md).  ``clock`` is injectable for tests.
    """

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 clock=time.monotonic):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        if cooldown_s <= 0:
            raise ValueError("breaker cooldown_s must be > 0")
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0       # consecutive, resets on success
        self._open_until = 0.0
        self._probing = False    # a half-open probe is in flight
        self._trips = 0
        self._successes = 0
        self._failures_total = 0

    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the caller dispatch on the primary backend right now?"""
        event = None
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() < self._open_until:
                    return False
                self._state = "half_open"
                self._probing = True
                event = "half_open"
            elif self._probing:
                return False  # one probe at a time; others stay fallback
            else:
                self._probing = True
        if event is not None:
            from locust_tpu import obs

            obs.event("backend.breaker_half_open", cooldown_s=self.cooldown_s)
        return True

    def record_success(self) -> None:
        closed = False
        with self._lock:
            self._successes += 1
            self._failures = 0
            self._probing = False
            if self._state != "closed":
                self._state = "closed"
                closed = True
        if closed:
            from locust_tpu import obs

            obs.event("backend.breaker_close")
            logger.info("backend breaker closed: primary backend restored")

    def record_failure(self) -> None:
        opened = None
        with self._lock:
            self._failures += 1
            self._failures_total += 1
            if self._state == "half_open":
                # The probe failed: a full new cooldown, not a trip.
                self._state = "open"
                self._probing = False
                self._open_until = self._clock() + self.cooldown_s
                opened = "reopen"
            elif self._state == "closed" and self._failures >= self.threshold:
                self._state = "open"
                self._open_until = self._clock() + self.cooldown_s
                self._trips += 1
                opened = "trip"
        if opened is not None:
            from locust_tpu import obs

            obs.event(
                "backend.breaker_open",
                failures=self.threshold if opened == "trip" else 1,
                cooldown_s=self.cooldown_s,
            )
            if opened == "trip":
                obs.metric_inc("backend.breaker_trips")
            logger.warning(
                "backend breaker %s: primary ineligible for %.1fs",
                "tripped" if opened == "trip" else "re-opened",
                self.cooldown_s,
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "failures": self._failures_total,
                "successes": self._successes,
                "trips": self._trips,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
            }


def guarded_dispatch(breaker: CircuitBreaker, fn, **ctx):
    """Run one primary-backend dispatch under the breaker's accounting.

    The ``backend.dispatch`` chaos site fires HERE (docs/FAULTS.md) —
    "error" models the tunnel dying between probe and dispatch, "delay" a
    slow tunnel — so the whole trip/failover/half-open ladder is
    drivable from a fault plan.  Any exception out of ``fn`` counts as a
    dispatch failure and re-raises; the caller decides whether to retry
    on the primary or fail over (engine.run_checkpointed reloads the
    last checkpoint either way).
    """
    from locust_tpu.utils import faultplan

    rule = faultplan.fire("backend.dispatch", **ctx)
    if rule is not None:
        if rule.action == "delay":
            time.sleep(rule.delay_s)
        else:
            breaker.record_failure()
            raise faultplan.FaultInjected(
                "[faultplan] injected backend dispatch failure"
            )
    try:
        out = fn()
    except Exception:
        breaker.record_failure()
        raise
    breaker.record_success()
    return out


def cpu_fallback_device():
    """The CPU device in-flight work fails over onto, or None when jax
    has no CPU client (then there is nothing to fail over TO and the
    caller re-raises).  Defensive the same way as the mesh collectives
    flip: a jax refactor degrades to no-failover, never to a crash."""
    try:
        import jax

        return jax.local_devices(backend="cpu")[0]
    except Exception as e:  # pragma: no cover - defensive
        logger.warning("no CPU fallback device available: %s", e)
        return None


def select_backend_cli(mode: str, prog: str = "locust_tpu") -> str | None:
    """CLI-entrypoint wrapper: resolve the backend with the CLI's probe
    policy, print failures to stderr, return None on failure.  The ONE
    policy both the WordCount driver (cli.py) and the workload-ladder
    subcommands (cli_apps.py) use, so probe-timeout tuning can never
    drift between entrypoints."""
    try:
        backend = select_backend(mode, probe_timeout_s=90, retries=2)
    except RuntimeError as e:
        print(f"{prog}: error: {e}", file=sys.stderr)
        return None
    print(f"[locust] backend: {backend}", file=sys.stderr)
    return backend
