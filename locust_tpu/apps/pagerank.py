"""PageRank: iterative MapReduce over an edge list (BASELINE.json configs[3]).

MapReduce formulation (the reference engine never shipped a second workload,
but its map/emit/reduce contract extends directly — SURVEY.md §7.1 "API"):
per iteration, map each edge (s -> d) to the emit ``(d, rank[s]/deg[s])``
and reduce by key with sum; then apply damping.

TPU-native formulation: node ids ARE the keys, so the shuffle degenerates to
a dense ``segment_sum`` into a ``[num_nodes]`` vector — no byte keys, no
sort.  Iterations run under ``lax.scan`` (static trip count) or a
``while_loop`` on the L1 residual.  Distributed: edges shard across the
mesh, each device computes a partial dense contribution vector, and the
"shuffle" is a single ``psum`` — the degenerate all-to-all for dense integer
keys.  Dangling mass (deg==0 nodes) redistributes uniformly, the standard
correction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from locust_tpu.parallel.mesh import DATA_AXIS


def _contributions(src, dst, ranks, inv_deg, num_nodes):
    """Dense map+reduce of one iteration: sum_d rank[s]/deg[s]."""
    contrib = ranks[src] * inv_deg[src]
    return jax.ops.segment_sum(contrib, dst, num_segments=num_nodes)


@functools.partial(jax.jit, static_argnames=("num_nodes", "num_iters"))
def pagerank(
    src: jax.Array,
    dst: jax.Array,
    num_nodes: int,
    num_iters: int = 20,
    damping: float = 0.85,
) -> jax.Array:
    """Single-device PageRank over int32 edge arrays ``[E]``.

    Pass valid edges only (no padding); the distributed variant supports
    masked edge padding for equal shard sizes.
    """
    deg = jax.ops.segment_sum(
        jnp.ones_like(src, dtype=jnp.float32), src, num_segments=num_nodes
    )
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)
    dangling = deg == 0
    ranks0 = jnp.full((num_nodes,), 1.0 / num_nodes, dtype=jnp.float32)

    def body(ranks, _):
        contrib = _contributions(src, dst, ranks, inv_deg, num_nodes)
        dangling_mass = jnp.sum(jnp.where(dangling, ranks, 0.0))
        ranks_new = (1.0 - damping) / num_nodes + damping * (
            contrib + dangling_mass / num_nodes
        )
        return ranks_new, None

    ranks, _ = jax.lax.scan(body, ranks0, None, length=num_iters)
    return ranks


class DistributedPageRank:
    """Edge-sharded PageRank on a mesh: local segment_sum + psum combine.

    The mesh/axis contract matches DistributedMapReduce; ranks and degrees
    are replicated (dense [num_nodes] vectors), edges shard along the axis.
    Edge padding: pad with (-1 -> clamped) masked edges via ``edge_mask``.
    """

    def __init__(self, mesh, num_nodes: int, axis_name: str = DATA_AXIS,
                 damping: float = 0.85):
        self.mesh = mesh
        self.num_nodes = num_nodes
        self.axis = axis_name
        self.damping = damping
        n_dev = mesh.shape[axis_name]
        num = num_nodes
        damp = damping

        def step(src, dst, mask, ranks, inv_deg, dangling_vec):
            # Local partial: masked edges contribute 0.
            w = ranks[src] * inv_deg[src] * mask
            partial = jax.ops.segment_sum(w, dst, num_segments=num)
            contrib = jax.lax.psum(partial, axis_name)          # the combine
            local_dangling = jnp.sum(jnp.where(dangling_vec, ranks, 0.0))
            ranks_new = (1.0 - damp) / num + damp * (
                contrib + local_dangling / num
            )
            return ranks_new

        self._step = jax.jit(
            jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(P(axis_name), P(axis_name), P(axis_name), P(), P(), P()),
                out_specs=P(),
            )
        )
        self.n_dev = n_dev

    def run(self, src: np.ndarray, dst: np.ndarray, num_iters: int = 20) -> np.ndarray:
        num = self.num_nodes
        deg = np.bincount(src, minlength=num).astype(np.float32)
        inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1.0), 0.0).astype(
            np.float32
        )
        dangling = deg == 0
        # Pad edge shards to equal length per device.
        e = len(src)
        per = -(-e // self.n_dev)
        pad = per * self.n_dev - e
        src_p = np.concatenate([src, np.zeros(pad, src.dtype)]).astype(np.int32)
        dst_p = np.concatenate([dst, np.zeros(pad, dst.dtype)]).astype(np.int32)
        mask = np.concatenate(
            [np.ones(e, np.float32), np.zeros(pad, np.float32)]
        )
        ranks = np.full((num,), 1.0 / num, dtype=np.float32)
        for _ in range(num_iters):
            ranks = self._step(src_p, dst_p, mask, ranks, inv_deg, dangling)
        return np.asarray(jax.device_get(ranks))
