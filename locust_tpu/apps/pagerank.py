"""PageRank: iterative MapReduce over an edge list (BASELINE.json configs[3]).

MapReduce formulation (the reference engine never shipped a second workload,
but its map/emit/reduce contract extends directly — SURVEY.md §7.1 "API"):
per iteration, map each edge (s -> d) to the emit ``(d, rank[s]/deg[s])``
and reduce by key with sum; then apply damping.

TPU-native formulation: node ids ARE the keys, so the shuffle degenerates to
a dense ``segment_sum`` into a ``[num_nodes]`` vector — no byte keys, no
sort.  Iterations run under ``lax.scan`` (static trip count) or a
``while_loop`` on the L1 residual.  Distributed: edges shard across the
mesh, each device computes a partial dense contribution vector, and the
"shuffle" is a single ``psum`` — the degenerate all-to-all for dense integer
keys.  Dangling mass (deg==0 nodes) redistributes uniformly, the standard
correction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from locust_tpu.parallel.mesh import DATA_AXIS, compat_shard_map


def _contributions(src, dst, ranks, inv_deg, num_nodes):
    """Dense map+reduce of one iteration: sum_d rank[s]/deg[s]."""
    contrib = ranks[src] * inv_deg[src]
    return jax.ops.segment_sum(contrib, dst, num_segments=num_nodes)


@functools.partial(jax.jit, static_argnames=("num_nodes", "num_iters"))
def pagerank(
    src: jax.Array,
    dst: jax.Array,
    num_nodes: int,
    num_iters: int = 20,
    damping: float = 0.85,
) -> jax.Array:
    """Single-device PageRank over int32 edge arrays ``[E]``.

    Pass valid edges only (no padding); the distributed variant supports
    masked edge padding for equal shard sizes.
    """
    deg = jax.ops.segment_sum(
        jnp.ones_like(src, dtype=jnp.float32), src, num_segments=num_nodes
    )
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)
    dangling = deg == 0
    ranks0 = jnp.full((num_nodes,), 1.0 / num_nodes, dtype=jnp.float32)

    def body(ranks, _):
        contrib = _contributions(src, dst, ranks, inv_deg, num_nodes)
        dangling_mass = jnp.sum(jnp.where(dangling, ranks, 0.0))
        ranks_new = (1.0 - damping) / num_nodes + damping * (
            contrib + dangling_mass / num_nodes
        )
        return ranks_new, None

    ranks, _ = jax.lax.scan(body, ranks0, None, length=num_iters)
    return ranks


@functools.partial(jax.jit, static_argnames=("num_nodes",))
def pagerank_prep(src: jax.Array, num_nodes: int):
    """The loop-invariant state of ``pagerank`` as a standalone jit:
    (inv_deg, dangling mask) from the FULL edge source column — spelled
    exactly as the fused kernel above so the distributed epoch sweep
    (plan/distribute.py IterateShape) reproduces its bits."""
    deg = jax.ops.segment_sum(
        jnp.ones_like(src, dtype=jnp.float32), src, num_segments=num_nodes
    )
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)
    return inv_deg, deg == 0


@functools.partial(jax.jit, static_argnames=("num_nodes",))
def pagerank_step(
    src: jax.Array,
    dst: jax.Array,
    ranks: jax.Array,
    inv_deg: jax.Array,
    dangling: jax.Array,
    damping,
    num_nodes: int,
) -> jax.Array:
    """ONE ``pagerank`` iteration as a standalone jit, bit-identical to
    the scan body above.  ``damping`` is a TRACED f32 operand on
    purpose: the fused kernel traces it too, so ``(1-damping)/n``
    computes in f32 on device — marking it static would constant-fold
    that expression in python float64 and change the low bits (pinned
    by tests/test_serve.py's distributed-iterate identity).

    Epoch sharding rides dst-restriction: calling this with the edge
    SUBSET ``dst in [lo, hi)`` (full ranks/inv_deg/dangling vectors)
    yields a vector whose ``[lo:hi)`` slice is bit-identical to the
    full step's — segment_sum contributions land only on in-range dst,
    and the dangling/teleport terms are global scalars either way.
    """
    contrib = _contributions(src, dst, ranks, inv_deg, num_nodes)
    dangling_mass = jnp.sum(jnp.where(dangling, ranks, 0.0))
    return (1.0 - damping) / num_nodes + damping * (
        contrib + dangling_mass / num_nodes
    )


class DistributedPageRank:
    """Edge-sharded PageRank on a mesh: local segment_sum + psum combine.

    The mesh/axis contract matches DistributedMapReduce; ranks and degrees
    are replicated (dense [num_nodes] vectors), edges shard along the axis.
    Edge padding: pad with (-1 -> clamped) masked edges via ``edge_mask``.
    """

    def __init__(self, mesh, num_nodes: int, axis_name: str = DATA_AXIS,
                 damping: float = 0.85):
        self.mesh = mesh
        self.num_nodes = num_nodes
        self.axis = axis_name
        self.damping = damping
        n_dev = mesh.shape[axis_name]
        num = num_nodes
        damp = damping

        def step(src, dst, mask, ranks, inv_deg, dangling_vec):
            # Local partial: masked edges contribute 0.
            w = ranks[src] * inv_deg[src] * mask
            partial = jax.ops.segment_sum(w, dst, num_segments=num)
            contrib = jax.lax.psum(partial, axis_name)          # the combine
            local_dangling = jnp.sum(jnp.where(dangling_vec, ranks, 0.0))
            ranks_new = (1.0 - damp) / num + damp * (
                contrib + local_dangling / num
            )
            return ranks_new

        self._step = jax.jit(
            compat_shard_map(
                step,
                mesh=mesh,
                in_specs=(P(axis_name), P(axis_name), P(axis_name), P(), P(), P()),
                out_specs=P(),
            )
        )
        self.n_dev = n_dev

    def run(self, src: np.ndarray, dst: np.ndarray, num_iters: int = 20) -> np.ndarray:
        num = self.num_nodes
        deg = np.bincount(src, minlength=num).astype(np.float32)
        inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1.0), 0.0).astype(
            np.float32
        )
        dangling = deg == 0
        # Pad edge shards to equal length per device.
        e = len(src)
        per = -(-e // self.n_dev)
        pad = per * self.n_dev - e
        src_p = np.concatenate([src, np.zeros(pad, src.dtype)]).astype(np.int32)
        dst_p = np.concatenate([dst, np.zeros(pad, dst.dtype)]).astype(np.int32)
        mask = np.concatenate(
            [np.ones(e, np.float32), np.zeros(pad, np.float32)]
        )
        ranks = np.full((num,), 1.0 / num, dtype=np.float32)
        for _ in range(num_iters):
            ranks = self._step(src_p, dst_p, mask, ranks, inv_deg, dangling)
        return np.asarray(jax.device_get(ranks))


class ShardedPageRank:
    """Node-partitioned PageRank: rank state sharded, not replicated.

    ``DistributedPageRank`` replicates dense ``[num_nodes]`` rank/degree
    vectors on every device, capping graph size at one device's HBM
    (VERDICT r1 weak #5 / r2 missing #5).  Here device ``d`` owns the
    contiguous node block ``[d*npd, (d+1)*npd)`` and only ever holds

      * its rank/degree block                  O(nodes / n_dev)
      * its edge shard (grouped by src owner)  O(edges / n_dev)
      * fixed-size send/recv buffers           O(n_dev * send_cap)

    The per-iteration exchange is the sparse analog of the shuffle in
    parallel/shuffle.py: contributions pre-aggregate into a STATIC send
    slot per (device, destination-shard, distinct-destination-node) —
    the graph is static, so the entire routing plan (slot ids, receive
    maps) is computed ONCE on the host and the device step is just

      gather local ranks -> segment_sum into send slots ->
      lax.all_to_all -> segment_sum into the local rank block -> damp,

    with the dangling-mass correction as a scalar psum.  Because slots
    are per *distinct* destination node, capacity is exact (no skew
    overflow, no drop/retry path — unlike hash bins, a destination node
    can appear in a given sender's buffer at most once).
    """

    def __init__(self, mesh, num_nodes: int, axis_name: str = DATA_AXIS,
                 damping: float = 0.85):
        if num_nodes < 1:
            raise ValueError(f"num_nodes must be >= 1, got {num_nodes}")
        self.mesh = mesh
        self.num_nodes = num_nodes
        self.axis = axis_name
        self.damping = damping
        self.n_dev = int(mesh.shape[axis_name])
        self.npd = -(-num_nodes // self.n_dev)  # nodes per device (padded)

    # -------------------------------------------------------- host-side plan

    def _build_plan(self, src: np.ndarray, dst: np.ndarray):
        """Static routing plan: all data-dependent indexing leaves the
        device loop.  Returns dict of per-device arrays (leading axis =
        device, sharded over the mesh in the step).

        Fully vectorized — ONE lexsort over (owner, dest_shard, dst) plus
        run-length boundaries; the per-(device, shard) ``np.unique`` loop
        it replaces was O(n_dev^2) host work, quadratic in devices on a
        real pod (VERDICT r3 weak #6).  A dst's slot id is its rank among
        the distinct dsts of its (owner, dest_shard) pair, which after
        the lexsort is a prefix count of run starts — identical to the
        old builder's ``searchsorted(uniq, dst)`` because uniq was
        ascending.  O(E log E) total.
        """
        n_dev, npd = self.n_dev, self.npd
        src = np.asarray(src, np.int64).ravel()
        dst = np.asarray(dst, np.int64).ravel()
        owner = src // npd
        dest = dst // npd
        n_edges = src.shape[0]

        order = np.lexsort((dst, dest, owner))
        src, dst, owner, dest = (
            src[order], dst[order], owner[order], dest[order]
        )
        counts = np.bincount(owner, minlength=n_dev)
        starts = np.concatenate([[0], np.cumsum(counts)])
        e_max = max(1, int(counts.max()))

        if n_edges:
            # Run starts: first edge of each distinct (owner, dest, dst);
            # pair starts: first edge of each (owner, dest) group.
            same_run = (
                (owner[1:] == owner[:-1])
                & (dest[1:] == dest[:-1])
                & (dst[1:] == dst[:-1])
            )
            new_run = np.concatenate([[True], ~same_run])
            pair_change = np.concatenate(
                [[True], (owner[1:] != owner[:-1]) | (dest[1:] != dest[:-1])]
            )
            run_id = np.cumsum(new_run) - 1
            pair_id = np.cumsum(pair_change) - 1
            pair_first_run = run_id[pair_change]          # [n_pairs]
            rank = run_id - pair_first_run[pair_id]       # dst rank in pair
            n_pairs = int(pair_id[-1]) + 1
            nuniq = np.bincount(pair_id[new_run], minlength=n_pairs)
            cap = max(1, int(nuniq.max()))
        else:
            rank = np.zeros(0, np.int64)
            cap = 1
        cap = -(-cap // 8) * 8  # lane-align the all-to-all payload

        src_l = np.zeros((n_dev, e_max), np.int32)        # src local id
        mask = np.zeros((n_dev, e_max), np.float32)
        # Padded (and only padded) edge slots scatter to the dump slot.
        send_seg = np.full((n_dev, e_max), n_dev * cap, np.int32)
        recv_map = np.full((n_dev, n_dev, cap), npd, np.int32)  # npd = dump
        if n_edges:
            col = np.arange(n_edges) - starts[owner]      # slot within device
            src_l[owner, col] = (src - owner * npd).astype(np.int32)
            mask[owner, col] = 1.0
            send_seg[owner, col] = (dest * cap + rank).astype(np.int32)
            # Receiver p's map for sender d: slot -> its local node id,
            # one entry per distinct (owner, dest, dst) run.
            r_owner, r_dest = owner[new_run], dest[new_run]
            recv_map[r_dest, r_owner, rank[new_run]] = (
                dst[new_run] - r_dest * npd
            ).astype(np.int32)

        return dict(
            src_l=src_l, mask=mask, send_seg=send_seg, recv_map=recv_map,
            cap=cap, e_max=e_max,
        )

    # ------------------------------------------------------------------- run

    def run(self, src: np.ndarray, dst: np.ndarray, num_iters: int = 20) -> np.ndarray:
        n_dev, npd, num = self.n_dev, self.npd, self.num_nodes
        axis = self.axis
        damp = self.damping
        plan = self._build_plan(src, dst)
        cap = plan["cap"]

        # Node-block-local static vectors.
        deg = np.bincount(np.asarray(src), minlength=n_dev * npd).astype(np.float32)
        inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1.0), 0.0)
        node_valid = (np.arange(n_dev * npd) < num).astype(np.float32)
        dangling = ((deg == 0) & (node_valid > 0)).astype(np.float32)
        ranks0 = (node_valid / num).astype(np.float32)

        def step(src_l, mask, send_seg, recv_map, ranks_l, inv_deg_l,
                 dangling_l, valid_l):
            # shard_map gives [1, ...] blocks along the device axis; drop it.
            src_l, mask, send_seg = src_l[0], mask[0], send_seg[0]
            recv_map = recv_map[0]
            ranks_l, inv_deg_l = ranks_l[0], inv_deg_l[0]
            dangling_l, valid_l = dangling_l[0], valid_l[0]

            w = ranks_l[src_l] * inv_deg_l[src_l] * mask
            send = jax.ops.segment_sum(
                w, send_seg, num_segments=n_dev * cap + 1
            )[: n_dev * cap].reshape(n_dev, cap)
            recv = jax.lax.all_to_all(send, axis, 0, 0)
            contrib = jax.ops.segment_sum(
                recv.reshape(-1), recv_map.reshape(-1), num_segments=npd + 1
            )[:npd]
            dangling_mass = jax.lax.psum(
                jnp.sum(ranks_l * dangling_l), axis
            )
            new_ranks = valid_l * (
                (1.0 - damp) / num + damp * (contrib + dangling_mass / num)
            )
            return new_ranks[None]

        spec = P(axis)
        step_j = jax.jit(
            compat_shard_map(
                step,
                mesh=self.mesh,
                in_specs=(spec,) * 8,
                out_specs=spec,
            )
        )

        from locust_tpu.parallel.mesh import scatter_host_array

        sharding = jax.sharding.NamedSharding(self.mesh, spec)

        def put(x):
            # Every process holds the full plan (host-replicated build);
            # the shared multi-controller scatter serves each process's
            # addressable shards by slicing.
            return scatter_host_array(x, sharding)
        src_l = put(plan["src_l"])
        mask = put(plan["mask"])
        send_seg = put(plan["send_seg"])
        recv_map = put(plan["recv_map"])
        inv_deg_l = put(inv_deg.reshape(n_dev, npd))
        dangling_l = put(dangling.reshape(n_dev, npd))
        valid_l = put(node_valid.reshape(n_dev, npd))
        ranks = put(ranks0.reshape(n_dev, npd))
        for _ in range(num_iters):
            ranks = step_j(
                src_l, mask, send_seg, recv_map, ranks, inv_deg_l,
                dangling_l, valid_l,
            )
        from locust_tpu.parallel.mesh import gather_host_array

        return gather_host_array(ranks).reshape(-1)[:num]
