from locust_tpu.apps.inverted_index import build_inverted_index  # noqa: F401
from locust_tpu.apps.pagerank import DistributedPageRank, pagerank  # noqa: F401
