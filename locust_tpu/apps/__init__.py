from locust_tpu.apps.inverted_index import (  # noqa: F401
    DistributedInvertedIndex,
    build_inverted_index,
    build_inverted_index_mesh,
)
from locust_tpu.apps.pagerank import (  # noqa: F401
    DistributedPageRank,
    ShardedPageRank,
    pagerank,
)
from locust_tpu.apps.sample_sort import DistributedSort, sort_strings  # noqa: F401
from locust_tpu.apps.tfidf import (  # noqa: F401
    build_tfidf,
    term_doc_counts,
    term_doc_counts_stream,
)
