"""Inverted index: word -> sorted unique doc ids (BASELINE.json configs[4]).

The stretch workload: emits are (word, doc_id) and the reduce is "collect
the distinct values per key" — a variable-length output that stresses the
fixed-slot emit contract (SURVEY.md §7.2 M5).

TPU-native formulation with static shapes throughout:

  1. Map: tokenize lines (ops/map_stage), value = the line's doc id.
  2. Sort by (validity, key, value): ONE multi-operand sort groups words
     AND orders each word's doc ids — num_keys covers the value too.
  3. Dedup (word, doc) pairs with a boundary mask on pair equality, then
     one more sort-compact pushes surviving pairs to the prefix.
  4. Word segment boundaries over the deduped prefix give the postings
     offsets: the index is (concatenated doc-id postings, per-word counts)
     — the standard CSR layout, assembled on host into {word: [doc ids]}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.config import EngineConfig
from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch
from locust_tpu.ops.map_stage import tokenize_block
from locust_tpu.ops.reduce_stage import segment_reduce


def _sort_pairs(batch: KVBatch) -> KVBatch:
    """Sort by (validity desc, key lex, value asc) — values are sort keys too."""
    lanes = batch.key_lanes
    n_lanes = lanes.shape[-1]
    invalid = (~batch.valid).astype(jnp.uint32)
    ops = (invalid, *(lanes[:, i] for i in range(n_lanes)), batch.values)
    out = jax.lax.sort(ops, num_keys=2 + n_lanes)  # value participates
    return KVBatch(
        key_lanes=jnp.stack(out[1 : 1 + n_lanes], axis=-1),
        values=out[1 + n_lanes],
        valid=out[0] == 0,
    )


def _index_block(lines: jax.Array, doc_ids: jax.Array, cfg: EngineConfig):
    """One block -> (word rows, postings doc ids, per-word counts, n_words)."""
    res = tokenize_block(lines, cfg)
    flat_keys = res.keys.reshape(-1, cfg.key_width)
    flat_valid = res.valid.reshape(-1)
    values = jnp.repeat(doc_ids.astype(jnp.int32), cfg.emits_per_line)
    batch = KVBatch.from_bytes(flat_keys, values, flat_valid)

    s = _sort_pairs(batch)
    n = s.size
    # Dedup identical (word, doc) pairs: keep first of each run.
    prev_lanes = jnp.roll(s.key_lanes, 1, axis=0)
    prev_vals = jnp.roll(s.values, 1)
    first = jnp.arange(n) == 0
    pair_new = first | jnp.any(s.key_lanes != prev_lanes, axis=-1) | (
        s.values != prev_vals
    )
    deduped = KVBatch(
        key_lanes=s.key_lanes, values=s.values, valid=s.valid & pair_new
    )
    d = _sort_pairs(deduped)  # compact survivors to the prefix, still ordered

    # Per-word postings counts via segment reduce with combine="count".
    counts = segment_reduce(d, "count")
    return d, counts, res.overflow


# Module-level jit: one compile per (shapes, cfg), shared across calls.
_index_block_jit = jax.jit(_index_block, static_argnames="cfg")


def build_inverted_index(
    lines: list[bytes] | np.ndarray,
    doc_ids: np.ndarray,
    cfg: EngineConfig | None = None,
) -> dict[bytes, list[int]]:
    """Host API: lines + per-line doc ids -> {word: sorted unique doc ids}.

    Single-block for now (cap: cfg.block_lines lines per call); the engine's
    merge machinery extends this to streamed corpora the same way WordCount
    merges block tables.
    """
    cfg = cfg or EngineConfig()
    if not isinstance(lines, np.ndarray):
        rows = bytes_ops.strings_to_rows(list(lines), cfg.line_width)
    else:
        rows = lines
    n = rows.shape[0]
    if n > cfg.block_lines:
        raise ValueError(
            f"{n} lines exceed block capacity {cfg.block_lines}; "
            "raise cfg.block_lines or chunk the corpus"
        )
    pad = cfg.block_lines - n
    rows = np.concatenate([rows, np.zeros((pad, cfg.line_width), np.uint8)])
    ids = np.concatenate([np.asarray(doc_ids, np.int32), np.zeros(pad, np.int32)])

    d, counts, _ = _index_block_jit(jnp.asarray(rows), jnp.asarray(ids), cfg)

    # Host assembly: postings prefix + per-word counts -> dict.
    pairs_keys = np.asarray(jax.device_get(d.keys_bytes()))
    pairs_vals = np.asarray(jax.device_get(d.values))
    pairs_valid = np.asarray(jax.device_get(d.valid))
    word_counts = counts.to_host_pairs()

    out: dict[bytes, list[int]] = {}
    pos = 0
    live_vals = pairs_vals[pairs_valid]
    live_keys = pairs_keys[pairs_valid]
    for word, cnt in word_counts:
        out[word] = [int(v) for v in live_vals[pos : pos + cnt]]
        pos += cnt
    assert pos == len(live_vals), "postings/count bookkeeping diverged"
    del live_keys
    return out
