"""Inverted index: word -> sorted unique doc ids (BASELINE.json configs[4]).

The stretch workload: emits are (word, doc_id) and the reduce is "collect
the distinct values per key" — a variable-length output that stresses the
fixed-slot emit contract (SURVEY.md §7.2 M5).

TPU-native formulation with static shapes throughout:

  1. Map: tokenize lines (ops/map_stage), value = the line's doc id.
  2. Sort by (validity, hash64(key), value): the 64-bit grouping-hash trick
     from the Process stage (ops/process_stage "hash" mode) — 4 key
     operands regardless of key width groups words AND orders each word's
     doc ids; payload rows follow via one index gather.  Full-key compares
     drive all downstream boundaries, so hash collisions cannot merge
     words; host assembly re-merges the ~2^-64 duplicate-run case.
  3. Dedup (word, doc) pairs with a boundary mask on pair equality, then
     one more sort-compact pushes surviving pairs to the prefix.
  4. Word segment boundaries over the deduped prefix give the postings
     offsets: the index is (concatenated doc-id postings, per-word counts)
     — the standard CSR layout, assembled on host into {word: [doc ids]}.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.config import EngineConfig
from locust_tpu.core import bytes_ops, packing
from locust_tpu.core.kv import KVBatch
from locust_tpu.ops.map_stage import tokenize_block
from locust_tpu.ops.reduce_stage import segment_reduce

logger = logging.getLogger("locust_tpu")


def _sort_pairs(batch: KVBatch) -> KVBatch:
    """Group by (validity, hash64(key)) with values as a tie-break sort key.

    4 sort operands + an index payload regardless of key width — the hash
    trick from ops/process_stage._hash_sort, extended with the value as the
    least-significant key so each word's doc ids come out ascending.
    """
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n = lanes.shape[0]
    invalid = (~valid).astype(jnp.uint32)
    h1, h2 = packing.hash_pair(lanes)
    idx = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort((invalid, h1, h2, values, idx), num_keys=4)
    sidx = out[4]
    return KVBatch(
        key_lanes=lanes[sidx], values=values[sidx], valid=valid[sidx]
    )


def _dedup_sorted_pairs(s: KVBatch) -> tuple[KVBatch, jax.Array]:
    """Mark the first of each identical (word, doc) run; return the
    re-compacted batch and the surviving-pair count."""
    n = s.size
    prev_lanes = jnp.roll(s.key_lanes, 1, axis=0)
    prev_vals = jnp.roll(s.values, 1)
    first = jnp.arange(n) == 0
    pair_new = first | jnp.any(s.key_lanes != prev_lanes, axis=-1) | (
        s.values != prev_vals
    )
    keep = s.valid & pair_new
    deduped = KVBatch(key_lanes=s.key_lanes, values=s.values, valid=keep)
    d = _sort_pairs(deduped)  # compact survivors to the prefix, still ordered
    return d, jnp.sum(keep.astype(jnp.int32))


def _fold_index_block(
    acc: KVBatch,
    lines: jax.Array,
    doc_ids: jax.Array,
    cfg: EngineConfig,
    cap: int,
):
    """Merge one block's (word, doc) pairs into the running deduped table.

    Same one-sort-per-block fold as the WordCount engine (engine.py
    fold_block), but the carried state is the PAIR set, which the final
    segment count turns into CSR postings.
    """
    res = tokenize_block(lines, cfg)
    flat_keys = res.keys.reshape(-1, cfg.key_width)
    flat_valid = res.valid.reshape(-1)
    values = jnp.repeat(doc_ids.astype(jnp.int32), cfg.emits_per_line)
    batch = KVBatch.from_bytes(flat_keys, values, flat_valid)

    d, n_pairs = _dedup_sorted_pairs(_sort_pairs(KVBatch.concat(acc, batch)))
    head = KVBatch(
        key_lanes=d.key_lanes[:cap], values=d.values[:cap], valid=d.valid[:cap]
    )
    return head, n_pairs, res.overflow


_fold_index_jit = jax.jit(_fold_index_block, static_argnames=("cfg", "cap"))


def default_pairs_capacity(cfg: EngineConfig, mult: int = 2) -> int:
    """Default distinct-(word, doc) pair capacity: ``mult`` rounds of
    emits with a 4096 floor.  The pair table is CORPUS-level state, not
    per-block — a small block size must not shrink it (r4 apps battery:
    tiny-block configs raised on ordinary vocabularies; the floor costs
    ~150KB).  The ONE sizing rule for the single-device index, the
    distributed index (``mult=4``: pairs accumulate across rounds), and
    the tf counter."""
    return max(mult * cfg.emits_per_block, 4096)


def build_inverted_index(
    lines: list[bytes] | np.ndarray,
    doc_ids: np.ndarray,
    cfg: EngineConfig | None = None,
    pairs_capacity: int | None = None,
) -> dict[bytes, list[int]]:
    """Host API: lines + per-line doc ids -> {word: sorted unique doc ids}.

    Streams the corpus through fixed-shape blocks like the WordCount engine
    — no line-count cap.  ``pairs_capacity`` bounds the distinct (word, doc)
    pair table carried across blocks (default ``default_pairs_capacity``:
    2x emits_per_block, floor 4096); exceeding it raises, since a
    truncated index is silently wrong.
    """
    cfg = cfg or EngineConfig()
    cap = pairs_capacity or default_pairs_capacity(cfg)
    if not isinstance(lines, np.ndarray):
        rows = bytes_ops.strings_to_rows(list(lines), cfg.line_width)
    else:
        rows = lines
    ids = np.asarray(doc_ids, np.int32)
    if rows.shape[0] != ids.shape[0]:
        raise ValueError(f"{rows.shape[0]} lines but {ids.shape[0]} doc ids")

    bl = cfg.block_lines
    nblocks = max(1, -(-rows.shape[0] // bl))
    pad = nblocks * bl - rows.shape[0]
    rows = np.concatenate([rows, np.zeros((pad, cfg.line_width), np.uint8)])
    ids = np.concatenate([ids, np.zeros(pad, np.int32)])

    acc = KVBatch.empty(cap, cfg.key_lanes)
    # The pair count stays a DEVICE scalar across the loop — an int() here
    # would host-sync every block and serialize dispatch (round-1 advisor
    # finding); the capacity check only needs the value once, after.
    n_pairs_dev = jnp.int32(0)
    overflow_dev = jnp.int32(0)
    for b in range(nblocks):
        sl = slice(b * bl, (b + 1) * bl)
        acc, blk_pairs, blk_ovf = _fold_index_jit(
            acc, jnp.asarray(rows[sl]), jnp.asarray(ids[sl]), cfg, cap
        )
        n_pairs_dev = jnp.maximum(n_pairs_dev, blk_pairs)
        overflow_dev = overflow_dev + blk_ovf
    n_pairs = int(n_pairs_dev)
    if int(overflow_dev):
        # Missing postings make a silently-wrong index; surface it loudly
        # (the WordCount per-line drop is reference semantics, but an index
        # user needs to know postings are absent).
        logger.warning(
            "inverted index dropped %d tokens beyond the %d-per-line cap; "
            "their postings are MISSING — raise emits_per_line",
            int(overflow_dev),
            cfg.emits_per_line,
        )
    if n_pairs > cap:
        raise ValueError(
            f"distinct (word, doc) pairs ({n_pairs}) exceed pairs_capacity "
            f"({cap}); pass a larger pairs_capacity"
        )
    d = acc
    counts = segment_reduce(d, "count")

    # Host assembly: postings prefix + per-word counts -> dict.
    pairs_keys = np.asarray(jax.device_get(d.keys_bytes()))
    pairs_vals = np.asarray(jax.device_get(d.values))
    pairs_valid = np.asarray(jax.device_get(d.valid))
    word_counts = counts.to_host_pairs()

    out: dict[bytes, list[int]] = {}
    pos = 0
    live_vals = pairs_vals[pairs_valid]
    for word, cnt in word_counts:
        run = [int(v) for v in live_vals[pos : pos + cnt]]
        if word in out:  # 64-bit hash collision split a word into two runs
            run = sorted(set(out[word] + run))
        out[word] = run
        pos += cnt
    assert pos == len(live_vals), "postings/count bookkeeping diverged"
    del pairs_keys
    return out


class DistributedInvertedIndex:
    """Mesh-parallel inverted index (VERDICT.md round-1 #7).

    The same collective recipe as parallel/shuffle.DistributedMapReduce —
    hash-partition, equal bins, one ``lax.all_to_all`` per round, carried
    per-device state, lossless backlog retry — but the shuffled unit is the
    (word, doc) PAIR and the per-shard merge is a dedup, not a segment
    reduce.  Partitioning hashes the WORD only, so every posting of a word
    lands on one shard and host assembly is a plain per-shard union.
    """

    def __init__(
        self,
        mesh: jax.sharding.Mesh,
        cfg: EngineConfig,
        axis_name: str | None = None,
        skew_factor: float = 2.0,
        pairs_capacity: int | None = None,
    ):
        from jax.sharding import PartitionSpec as P

        from locust_tpu.parallel.mesh import DATA_AXIS, compat_shard_map
        from locust_tpu.parallel.shuffle import partition_to_bins, sized_bins

        axis = axis_name or DATA_AXIS
        self.mesh = mesh
        self.cfg = cfg
        self.axis = axis
        self.n_dev = mesh.shape[axis]
        self.bin_capacity = sized_bins(
            cfg.emits_per_block, self.n_dev, skew_factor
        )
        self.leftover_capacity = cfg.emits_per_block
        # Distinct (word, doc) pairs carried per shard; exceeding it raises
        # (a truncated index is silently wrong, like the single-device API).
        # Pairs accumulate across ALL rounds, so the floor is deliberately
        # larger than one round's emits.
        self.pairs_capacity = pairs_capacity or default_pairs_capacity(cfg, mult=4)
        self.max_drain_rounds = 2 + -(-cfg.emits_per_block // self.bin_capacity)
        max_drains = self.max_drain_rounds
        n_lanes = cfg.key_lanes

        def shuffle_round(local: KVBatch, acc: KVBatch, leftover: KVBatch):
            """One partition + all-to-all + dedup-merge; feed and drain
            share it (mirror of shuffle.DistributedMapReduce)."""
            send_lanes, send_vals, send_valid, shuf_ovf, new_leftover = (
                partition_to_bins(
                    KVBatch.concat(local, leftover),
                    self.n_dev,
                    self.bin_capacity,
                    leftover_capacity=self.leftover_capacity,
                )
            )
            recv_lanes = jax.lax.all_to_all(send_lanes, axis, 0, 0)
            recv_vals = jax.lax.all_to_all(send_vals, axis, 0, 0)
            recv_valid = jax.lax.all_to_all(send_valid, axis, 0, 0)
            received = KVBatch(
                key_lanes=recv_lanes.reshape(-1, n_lanes),
                values=recv_vals.reshape(-1),
                valid=recv_valid.reshape(-1),
            )
            merged, n_pairs = _dedup_sorted_pairs(
                _sort_pairs(KVBatch.concat(acc, received))
            )
            cap = self.pairs_capacity
            new_acc = KVBatch(
                key_lanes=merged.key_lanes[:cap],
                values=merged.values[:cap],
                valid=merged.valid[:cap],
            )
            # psum'd so every device sees the same value — the while_loop
            # below then takes the same trip count on all devices.
            backlog = jax.lax.psum(
                jnp.sum(new_leftover.valid.astype(jnp.int32)), axis
            )
            return new_acc, new_leftover, shuf_ovf, n_pairs, backlog

        def local_step(
            lines: jax.Array, doc_ids: jax.Array, acc: KVBatch, leftover: KVBatch
        ):
            """Feed + ON-DEVICE drain (lax.while_loop): one dispatch per
            round with no host sync, like DistributedMapReduce.local_step."""
            res = tokenize_block(lines, cfg)
            flat_keys = res.keys.reshape(-1, cfg.key_width)
            flat_valid = res.valid.reshape(-1)
            values = jnp.repeat(doc_ids.astype(jnp.int32), cfg.emits_per_line)
            batch = KVBatch.from_bytes(flat_keys, values, flat_valid)
            # Local pre-dedup: repeated (word, doc) pairs within the shard
            # collapse before touching the network (the combiner analog).
            local, _ = _dedup_sorted_pairs(_sort_pairs(batch))

            acc, leftover, shuf_ovf, n_pairs, backlog = shuffle_round(
                local, acc, leftover
            )
            zero_local = KVBatch.empty(local.size, n_lanes)

            def cond(state):
                _, _, _, _, backlog, drains = state
                return (backlog > 0) & (drains < max_drains)

            def body(state):
                acc, leftover, shuf_ovf, _, _, drains = state
                acc, leftover, so, n_pairs, backlog = shuffle_round(
                    zero_local, acc, leftover
                )
                return (acc, leftover, shuf_ovf + so, n_pairs, backlog,
                        drains + 1)

            acc, leftover, shuf_ovf, n_pairs, backlog, drains = (
                jax.lax.while_loop(
                    cond,
                    body,
                    (acc, leftover, shuf_ovf, n_pairs, backlog, jnp.int32(0)),
                )
            )
            stats = jnp.stack(
                [
                    jax.lax.psum(res.overflow, axis),
                    jax.lax.psum(shuf_ovf, axis),
                    jax.lax.pmax(n_pairs, axis),
                    backlog,
                    drains,
                ]
            )
            return acc, leftover, stats

        kv_spec = KVBatch(key_lanes=P(axis), values=P(axis), valid=P(axis))
        self._step = jax.jit(
            compat_shard_map(
                local_step,
                mesh=mesh,
                in_specs=(P(axis), P(axis), kv_spec, kv_spec),
                out_specs=(kv_spec, kv_spec, P()),
            )
        )
        # Across-round stats combiner, jitted ONCE per index builder:
        # overflows/drains ADD, worst-shard pairs MAX, backlog LAST.
        self._stats_merge = jax.jit(
            lambda a, b: jnp.stack(
                [a[0] + b[0], a[1] + b[1], jnp.maximum(a[2], b[2]), b[3],
                 a[4] + b[4]]
            )
        )

    @property
    def lines_per_round(self) -> int:
        return self.n_dev * self.cfg.block_lines

    def run(
        self,
        lines: list[bytes] | np.ndarray,
        doc_ids: np.ndarray,
        stats_sync_every: int = 16,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
    ) -> dict[bytes, list[int]]:
        cfg = self.cfg
        if not isinstance(lines, np.ndarray):
            rows = bytes_ops.strings_to_rows(list(lines), cfg.line_width)
        else:
            rows = lines
        ids = np.asarray(doc_ids, np.int32)
        if rows.shape[0] != ids.shape[0]:
            raise ValueError(f"{rows.shape[0]} lines but {ids.shape[0]} doc ids")

        lpr = self.lines_per_round
        nrounds = max(1, -(-rows.shape[0] // lpr))
        chunks = (
            (rows[r * lpr : (r + 1) * lpr], ids[r * lpr : (r + 1) * lpr])
            for r in range(nrounds)
        )
        fingerprint = None
        if checkpoint_dir is not None:
            from locust_tpu.io.serde import fingerprint_corpus

            # Doc ids are part of the corpus identity: the same lines with
            # different sharding produce a different index.
            fingerprint = fingerprint_corpus(
                rows, doc_ids=fingerprint_corpus(ids), **self._identity()
            )
        return self._run_rounds(
            chunks,
            stats_sync_every,
            fingerprint=fingerprint,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
        )

    def _identity(self) -> dict:
        """Engine/pipeline/mesh identity bound into checkpoint
        fingerprints (shuffle.DistributedMapReduce._identity mirror)."""
        return dict(
            engine="inverted_index",
            cfg=repr(self.cfg),
            mesh=f"{self.n_dev}x{self.axis}",
            bin_capacity=self.bin_capacity,
            pairs_capacity=self.pairs_capacity,
        )

    def run_stream(
        self,
        blocks,
        stats_sync_every: int = 16,
        fingerprint: str | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
    ) -> dict[bytes, list[int]]:
        """Bounded-memory variant: ``blocks`` yields
        ``(rows [<=lines_per_round, width], doc_ids [same length])`` chunk
        pairs — e.g. zip a ``StreamingCorpus(..., block_lines=
        self.lines_per_round)`` with a doc-id generator.  Only one chunk
        plus the sharded pair table are ever resident.  Pass a corpus
        ``fingerprint`` to enable checkpoint/resume.
        """
        from locust_tpu.io.loader import prefetch_blocks
        from locust_tpu.parallel.shuffle import stream_checkpoint_fingerprint

        fingerprint = stream_checkpoint_fingerprint(
            fingerprint, checkpoint_dir, self._identity()
        )
        return self._run_rounds(
            prefetch_blocks(blocks),
            stats_sync_every,
            fingerprint=fingerprint,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
        )

    def _run_rounds(
        self,
        chunk_iter,
        stats_sync_every: int,
        fingerprint: str | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
    ):
        from jax.sharding import PartitionSpec as P

        from locust_tpu.parallel.mesh import shard_rows
        from locust_tpu.parallel.shuffle import (
            ShardedCheckpoint,
            _gather_batch_host,
            drive_checkpointed_rounds,
        )

        cfg = self.cfg
        lpr = self.lines_per_round
        width = cfg.line_width

        sharding = jax.sharding.NamedSharding(self.mesh, P(self.axis))
        acc = jax.device_put(
            KVBatch.empty(self.n_dev * self.pairs_capacity, cfg.key_lanes), sharding
        )
        leftover = jax.device_put(
            KVBatch.empty(self.n_dev * self.leftover_capacity, cfg.key_lanes),
            sharding,
        )

        # Drains run ON DEVICE inside the step; the host only folds stats
        # in every ``stats_sync_every`` rounds, so round dispatch pipelines
        # (same RoundStats protocol as DistributedMapReduce.run).
        n_pairs = 0
        shuf_ovf = 0
        emit_ovf = 0
        start_round = 0

        ckpt = None
        if checkpoint_dir is not None:
            ckpt = ShardedCheckpoint(
                checkpoint_dir, fingerprint, sharding,
                async_writes=cfg.async_checkpoint,
            )
            restored = ckpt.load()
            if restored is not None:
                start_round, extras, acc, leftover = restored
                n_pairs = int(extras["n_pairs"])
                shuf_ovf = int(extras["shuf_ovf"])
                emit_ovf = int(extras["emit_ovf"])

        def snapshot(next_round: int) -> None:
            ckpt.snapshot(
                next_round,
                acc,
                leftover,
                n_pairs=np.int64(n_pairs),
                shuf_ovf=np.int64(shuf_ovf),
                emit_ovf=np.int64(emit_ovf),
            )

        def on_sync(st) -> None:
            nonlocal n_pairs, shuf_ovf, emit_ovf
            emit_ovf += int(st[0])
            shuf_ovf += int(st[1])
            n_pairs = max(n_pairs, int(st[2]))
            backlog = int(st[3])
            if backlog > 0:
                raise RuntimeError(
                    f"index backlog failed to drain in "
                    f"{self.max_drain_rounds} rounds ({backlog} pairs "
                    "remain); raise skew_factor"
                )
            if shuf_ovf:
                raise RuntimeError(
                    f"index shuffle lost {shuf_ovf} pairs; "
                    "emits exceeded cfg.emits_per_block"
                )

        from locust_tpu.parallel.shuffle import RoundStats

        round_stats = RoundStats(self._stats_merge, on_sync, stats_sync_every)
        from locust_tpu.parallel.shuffle import normalize_round_chunk

        def fold_round(chunk) -> None:
            nonlocal acc, leftover
            rows_chunk, ids_chunk = chunk
            ids_chunk = np.asarray(ids_chunk, dtype=np.int32)
            rows_chunk = np.asarray(rows_chunk, dtype=np.uint8)
            if rows_chunk.shape[0] != ids_chunk.shape[0]:
                raise ValueError(
                    f"chunk has {rows_chunk.shape[0]} lines but "
                    f"{ids_chunk.shape[0]} doc ids"
                )
            rows_chunk = normalize_round_chunk(rows_chunk, lpr, width)
            if ids_chunk.shape[0] < lpr:
                ids_chunk = np.concatenate(
                    [ids_chunk, np.zeros(lpr - ids_chunk.shape[0], np.int32)]
                )
            acc, leftover, stats = self._step(
                shard_rows(rows_chunk, self.mesh, self.axis),
                shard_rows(ids_chunk, self.mesh, self.axis),
                acc,
                leftover,
            )
            round_stats.push(stats)

        drive_checkpointed_rounds(
            chunk_iter, fold_round, round_stats, ckpt, snapshot,
            checkpoint_every, start_round,
        )
        if emit_ovf:
            # Missing postings make a silently-wrong index; unlike WordCount
            # (whose per-line cap is reference semantics, main.cu:141-144),
            # surface it loudly.
            logger.warning(
                "inverted index dropped %d tokens beyond the %d-per-line "
                "cap; their postings are MISSING — raise emits_per_line",
                emit_ovf,
                cfg.emits_per_line,
            )
        if n_pairs > self.pairs_capacity:
            raise ValueError(
                f"distinct (word, doc) pairs per shard ({n_pairs}) exceed "
                f"pairs_capacity ({self.pairs_capacity}); pass a larger one"
            )

        # Host assembly: shards are disjoint by word (hash partition) and
        # internally (hash, doc)-sorted + deduped, so a plain grouping union
        # yields ascending unique doc ids per word.
        out: dict[bytes, list[int]] = {}
        for k, v in _gather_batch_host(acc).to_host_pairs():
            out.setdefault(k, []).append(int(v))
        return out


def build_inverted_index_mesh(
    lines: list[bytes] | np.ndarray,
    doc_ids: np.ndarray,
    mesh: jax.sharding.Mesh,
    cfg: EngineConfig | None = None,
    **kw,
) -> dict[bytes, list[int]]:
    """Mesh convenience wrapper: build the index across all devices."""
    return DistributedInvertedIndex(mesh, cfg or EngineConfig(), **kw).run(
        lines, doc_ids
    )
