"""TF-IDF scoring: one composite-key WordCount gives tf AND df.

Beyond the reference's workload set (it ships WordCount only), but a
direct composition of the framework's primitives that pressure-tests the
key machinery's generality: the emit key is (word, doc) — the word's
packed byte lanes plus ONE extra uint32 lane carrying the doc id — and
the STANDARD Process/Reduce stages (ops/process_stage.sort_and_compact,
ops/reduce_stage.segment_reduce_into) fold those composite pairs across
blocks unchanged, because every sort mode and boundary compare is
generic over the lane count.

From the resulting {(word, doc): tf} table both remaining quantities are
host-side folds over a table that is orders of magnitude smaller than
the corpus: df(word) = number of pairs with that word, n_docs = distinct
doc ids seen, and

    score(word, doc) = tf * ln(n_docs / df(word))

(the classic unsmoothed formulation; a df of n_docs scores 0).
"""

from __future__ import annotations

import logging
import math

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.config import EngineConfig
from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch
from locust_tpu.ops.map_stage import tokenize_block
from locust_tpu.ops.process_stage import sort_and_compact
from locust_tpu.ops.reduce_stage import segment_reduce_into

logger = logging.getLogger("locust_tpu")


def _fold_tf_block(
    acc: KVBatch,
    lines: jax.Array,
    doc_ids: jax.Array,
    cfg: EngineConfig,
    tsize: int,
):
    """Merge one block's (word, doc) -> 1 emits into the running tf table.

    Identical shape to the WordCount engine's fold (engine.py fold_block)
    — concat with the accumulator, ONE sort, segment-sum into capacity —
    on a batch whose key has ``cfg.key_lanes + 1`` lanes: the word plus a
    big-endian doc-id lane (lane order IS byte order, core/packing, so
    the host can split the decoded key back into word and doc id).
    """
    res = tokenize_block(lines, cfg)
    flat_keys = res.keys.reshape(-1, cfg.key_width)
    flat_valid = res.valid.reshape(-1)
    word_lanes = KVBatch.from_bytes(
        flat_keys, jnp.ones(flat_keys.shape[0], jnp.int32), flat_valid
    ).key_lanes
    docs = jnp.repeat(doc_ids.astype(jnp.uint32), cfg.emits_per_line)
    comp = KVBatch(
        key_lanes=jnp.concatenate([word_lanes, docs[:, None]], axis=-1),
        values=jnp.ones(flat_keys.shape[0], jnp.int32),
        valid=flat_valid,
    )
    merged, distinct = segment_reduce_into(
        sort_and_compact(KVBatch.concat(acc, comp), cfg.sort_mode),
        tsize,
        "sum",
    )
    return merged, distinct, res.overflow


_fold_tf_jit = jax.jit(_fold_tf_block, static_argnames=("cfg", "tsize"))


def term_doc_counts(
    lines: list[bytes] | np.ndarray,
    doc_ids: np.ndarray,
    cfg: EngineConfig | None = None,
    pairs_capacity: int | None = None,
    allow_overflow: bool = False,
) -> dict[tuple[bytes, int], int]:
    """Host API: lines + per-line doc ids -> {(word, doc id): count}.

    Streams fixed-shape blocks like the WordCount engine.  Exceeding
    ``pairs_capacity`` (default ``default_pairs_capacity``: 2x
    emits_per_block, floor 4096) raises, and so does
    dropping tokens past the per-line emit cap (unless
    ``allow_overflow=True`` downgrades that to a warning) — either loss
    makes tf-idf scores silently wrong, and a plain dict return has no
    other channel to signal it.
    """
    cfg = cfg or EngineConfig()
    if not isinstance(lines, np.ndarray):
        rows = bytes_ops.strings_to_rows(list(lines), cfg.line_width)
    else:
        rows = lines
    ids = np.asarray(doc_ids, np.int32)
    if rows.shape[0] != ids.shape[0]:
        raise ValueError(f"{rows.shape[0]} lines but {ids.shape[0]} doc ids")

    bl = cfg.block_lines
    chunks = (
        (rows[i : i + bl], ids[i : i + bl])
        for i in range(0, max(rows.shape[0], 1), bl)
    )
    return _fold_tf_chunks(
        chunks, cfg, pairs_capacity, allow_overflow, prefetch=False
    )


def _finish_tf(
    acc: KVBatch, distinct_dev, overflow_dev, cfg, cap, allow_overflow
) -> dict[tuple[bytes, int], int]:
    """Shared tail of the tf folds: loss checks + host decode.

    Decodes the composite key NUMERICALLY (KVBatch.to_host_pairs would
    NUL-strip the key bytes, eating a doc-id lane whose low bytes are
    zero): word lanes -> bytes, doc lane -> int.
    """
    if int(overflow_dev):
        msg = (
            f"tf-idf dropped {int(overflow_dev)} tokens beyond the "
            f"{cfg.emits_per_line}-per-line cap; their counts are MISSING "
            "— raise emits_per_line"
        )
        if not allow_overflow:
            raise ValueError(msg)
        logger.warning(msg)
    if int(distinct_dev) > cap:
        raise ValueError(
            f"distinct (word, doc) pairs ({int(distinct_dev)}) exceed "
            f"pairs_capacity ({cap}); pass a larger pairs_capacity"
        )

    lanes, values, valid = jax.device_get((acc.key_lanes, acc.values, acc.valid))
    live = np.asarray(valid)
    lanes = np.asarray(lanes)[live]
    counts = np.asarray(values)[live]
    n_live = lanes.shape[0]
    if n_live == 0:
        return {}
    word_bytes = (
        lanes[:, :-1].astype(">u4").view(np.uint8).reshape(n_live, -1)
    )
    words = bytes_ops.rows_to_strings(word_bytes)
    docs = lanes[:, -1].astype(np.int64)
    out: dict[tuple[bytes, int], int] = {}
    for word, doc, count in zip(words, docs, counts):
        pair = (word, int(doc))
        # A full-hash collision can split a pair into two table rows
        # (same ~2^-64 story as the engine, engine.finalize_host_pairs).
        out[pair] = out.get(pair, 0) + int(count)
    return out


def term_doc_counts_stream(
    chunks,
    cfg: EngineConfig | None = None,
    pairs_capacity: int | None = None,
    allow_overflow: bool = False,
) -> dict[tuple[bytes, int], int]:
    """Bounded-memory tf: ``chunks`` yields ``(rows [<=block_lines, width],
    doc_ids [same length])`` pairs — e.g. zip a ``StreamingCorpus(path,
    width, cfg.block_lines)`` with a doc-id generator.  Same result and
    loss guarantees as ``term_doc_counts``; only one chunk plus the pair
    table are ever resident, and the reader prefetches ahead of the fold.
    """
    return _fold_tf_chunks(
        chunks, cfg or EngineConfig(), pairs_capacity, allow_overflow,
        prefetch=True,
    )


def _fold_tf_chunks(
    chunks, cfg, pairs_capacity, allow_overflow, prefetch: bool
) -> dict[tuple[bytes, int], int]:
    """The ONE tf fold loop behind both entry points (validation, padding,
    accumulate); ``prefetch`` adds the reader thread for the streaming
    path only — the in-memory path stays thread-free."""
    from locust_tpu.io.loader import prefetch_blocks
    from locust_tpu.parallel.shuffle import normalize_round_chunk

    from locust_tpu.apps.inverted_index import default_pairs_capacity

    cap = pairs_capacity or default_pairs_capacity(cfg)
    bl, w = cfg.block_lines, cfg.line_width
    acc = KVBatch.empty(cap, cfg.key_lanes + 1)
    distinct_dev = jnp.int32(0)  # device scalars: no per-block host sync
    overflow_dev = jnp.int32(0)
    if prefetch:
        chunks = prefetch_blocks(chunks)
    for rows_chunk, ids_chunk in chunks:
        ids_chunk = np.asarray(ids_chunk, np.int32)
        rows_chunk = np.asarray(rows_chunk, np.uint8)
        if rows_chunk.shape[0] != ids_chunk.shape[0]:
            raise ValueError(
                f"chunk has {rows_chunk.shape[0]} lines but "
                f"{ids_chunk.shape[0]} doc ids"
            )
        if ids_chunk.size and ids_chunk.min() < 0:
            # The doc id rides a uint32 key lane; -1 would wrap to
            # 2**32-1 and come back as a different key than passed in.
            raise ValueError(
                f"doc ids must be >= 0, got min {int(ids_chunk.min())}"
            )
        rows_chunk = normalize_round_chunk(rows_chunk, bl, w)
        if ids_chunk.shape[0] < bl:
            ids_chunk = np.concatenate(
                [ids_chunk, np.zeros(bl - ids_chunk.shape[0], np.int32)]
            )
        acc, blk_distinct, blk_ovf = _fold_tf_jit(
            acc, jnp.asarray(rows_chunk), jnp.asarray(ids_chunk), cfg, cap
        )
        distinct_dev = jnp.maximum(distinct_dev, blk_distinct)
        overflow_dev = overflow_dev + blk_ovf
    return _finish_tf(acc, distinct_dev, overflow_dev, cfg, cap, allow_overflow)


def scores_from_tf(
    tf: dict[tuple[bytes, int], int], n_docs: int
) -> dict[tuple[bytes, int], float]:
    """The tf-table -> score fold: ``score = tf * ln(n_docs / df)`` with
    df counted over the pair table.  The ONE spelling — ``build_tfidf``
    and the plan compiler's ``tfidf_score`` stage (plan/compile.py) both
    call it, so the plan layer's byte-identity guarantee cannot drift
    from a one-sided formula change."""
    df: dict[bytes, int] = {}
    for word, _ in tf:
        df[word] = df.get(word, 0) + 1
    return {
        (word, doc): count * math.log(n_docs / df[word])
        for (word, doc), count in tf.items()
    }


def build_tfidf(
    lines: list[bytes] | np.ndarray,
    doc_ids: np.ndarray,
    cfg: EngineConfig | None = None,
    pairs_capacity: int | None = None,
    allow_overflow: bool = False,
) -> dict[tuple[bytes, int], float]:
    """{(word, doc id): tf-idf score} over line-sharded documents.

    ``score = tf * ln(n_docs / df)`` — tf from the device pair table,
    df and n_docs as host folds over that same (already tiny) table.
    """
    ids = np.asarray(doc_ids, np.int32)
    tf = term_doc_counts(lines, ids, cfg, pairs_capacity, allow_overflow)
    n_docs = len(set(int(d) for d in ids)) or 1
    return scores_from_tf(tf, n_docs)
