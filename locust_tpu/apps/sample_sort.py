"""Distributed sample sort: TeraSort-style global ordering over the mesh.

A capability the reference gestures at but never delivers: its "Process"
stage sorts one GPU's emits (thrust::sort, reference MapReduce/src/
main.cu:414-415) and its multi-node mode simply assumes globally sorted
intermediate input (SURVEY.md Q6).  This app provides the real thing — a
global sort of (key, value) records across all mesh devices — using the
classic sample-sort recipe on TPU collectives:

  1. SAMPLE   every device takes a strided sample of its local keys; one
              ``all_gather`` shares all samples; every device sorts the
              (small) sample set identically and picks n_dev-1 splitters.
  2. PARTITION bucket = #splitters <= key (vectorized lexicographic compare
              on packed lanes, core/packing.lanes_geq_table); scatter into
              equal-capacity bins; one ``all_to_all`` — the range shuffle.
  3. LOCAL SORT each device lex-sorts what it received (full-lane
              ``lax.sort``: exact byte order, ops/process_stage "lex" mode).

Device d then holds range-shard d, internally sorted, and every key on
device d precedes every key on device d+1 — a globally sorted sequence.
Skewed inputs (duplicate-heavy keys) can overflow a range bin; overflow is
counted and psum'd like the hash shuffle's (SURVEY.md §7.3.3).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from locust_tpu.config import EngineConfig
from locust_tpu.core import bytes_ops, packing
from locust_tpu.core.kv import KVBatch
from locust_tpu.ops.process_stage import sort_and_compact
from locust_tpu.parallel.mesh import DATA_AXIS, compat_shard_map, shard_rows
from locust_tpu.parallel.shuffle import partition_to_bins


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


class DistributedSort:
    """Globally sort fixed-width byte keys (with int32 payloads) on a mesh."""

    def __init__(
        self,
        mesh: jax.sharding.Mesh,
        cfg: EngineConfig,
        rows_per_device: int,
        axis_name: str = DATA_AXIS,
        sample_per_device: int = 64,
        skew_factor: float = 2.0,
    ):
        self.mesh = mesh
        self.cfg = cfg
        self.axis = axis_name
        self.n_dev = mesh.shape[axis_name]
        self.rows_per_device = rows_per_device
        from locust_tpu.parallel.shuffle import sized_bins

        self.bin_capacity = sized_bins(rows_per_device, self.n_dev, skew_factor)
        self.shard_capacity = self.n_dev * self.bin_capacity
        n_lanes = cfg.key_lanes
        axis = axis_name
        n_dev = self.n_dev

        def local_sort(keys_rows: jax.Array, values: jax.Array, valid: jax.Array):
            """Per-device body (under shard_map): sample -> range shuffle -> sort."""
            kv = KVBatch.from_bytes(keys_rows, values, valid)
            lanes = kv.key_lanes

            # 1. SAMPLE: prefer VALID rows (padding rows would drag splitters
            # to zero and funnel every real key into one overflowing bin) —
            # compact valid rows to the front with a 1-key sort, then sample
            # the valid prefix AT A STRIDE: shard_rows hands each device a
            # contiguous (often internally clustered) line range, so a
            # prefix sample would bias the splitters and skew the bins.
            inv = (~valid).astype(jnp.uint32)
            row_idx = jnp.arange(lanes.shape[0], dtype=jnp.int32)
            _, compact_idx = jax.lax.sort((inv, row_idx), num_keys=1)
            n_valid_local = jnp.sum(valid.astype(jnp.int32))
            s = sample_per_device
            # floor(i*n/s) computed without the i*n product, which would
            # wrap int32 once rows_per_device * s exceeds 2^31 (x64 is off).
            i = jnp.arange(s, dtype=jnp.int32)
            stride_idx = i * (n_valid_local // s) + (i * (n_valid_local % s)) // s
            take = compact_idx[jnp.clip(stride_idx, 0, lanes.shape[0] - 1)]
            sample = lanes[take]                             # [s, L]
            sample_ok = valid[take]                          # [s]
            all_samples = jax.lax.all_gather(sample, axis)   # [n_dev, s, L]
            all_ok = jax.lax.all_gather(sample_ok, axis)     # [n_dev, s]
            flat = all_samples.reshape(-1, n_lanes)
            flat_inv = (~all_ok.reshape(-1)).astype(jnp.uint32)
            # Sort samples with invalid LAST, then place the n_dev-1
            # splitters at quantiles of the VALID prefix only.
            ops = (flat_inv, *(flat[:, i] for i in range(n_lanes)))
            s_out = jax.lax.sort(ops, num_keys=1 + n_lanes)
            sorted_lanes = jnp.stack(s_out[1:], axis=-1)     # [n_dev*s, L]
            n_valid_samples = jnp.sum(all_ok.astype(jnp.int32))
            j = jnp.arange(n_dev - 1, dtype=jnp.int32) + 1
            idx = jnp.clip(
                j * n_valid_samples // n_dev, 0, sorted_lanes.shape[0] - 1
            )
            splitters = sorted_lanes[idx]                    # [n_dev-1, L]

            # 2. PARTITION + all_to_all (range shuffle).
            bucket = jnp.sum(
                packing.lanes_geq_table(lanes, splitters).astype(jnp.int32),
                axis=-1,
            ).astype(jnp.uint32)                             # [N] in [0, n_dev)
            send_lanes, send_vals, send_valid, overflow, _ = partition_to_bins(
                kv, n_dev, self.bin_capacity, bucket=bucket
            )
            recv_lanes = jax.lax.all_to_all(send_lanes, axis, 0, 0)
            recv_vals = jax.lax.all_to_all(send_vals, axis, 0, 0)
            recv_valid = jax.lax.all_to_all(send_valid, axis, 0, 0)

            # 3. LOCAL SORT: exact lexicographic order within the range shard.
            received = KVBatch(
                key_lanes=recv_lanes.reshape(-1, n_lanes),
                values=recv_vals.reshape(-1),
                valid=recv_valid.reshape(-1),
            )
            srt = sort_and_compact(received, mode="lex")
            return srt, jax.lax.psum(overflow, axis)

        kv_spec = KVBatch(key_lanes=P(axis), values=P(axis), valid=P(axis))
        self._step = jax.jit(
            compat_shard_map(
                local_sort,
                mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis)),
                out_specs=(kv_spec, P()),
            )
        )

    # ------------------------------------------------------------------ api

    def sort_rows(
        self, keys: np.ndarray, values: np.ndarray | None = None
    ) -> "SortResult":
        """Globally sort host ``[n, key_width]`` byte rows (+ optional values).

        n must be <= n_dev * rows_per_device; shorter inputs are padded with
        invalid rows.
        """
        total = self.n_dev * self.rows_per_device
        n = keys.shape[0]
        if n > total:
            raise ValueError(f"{n} rows > capacity {total}; raise rows_per_device")
        if values is None:
            values = np.arange(n, dtype=np.int32)  # original index payload
        pk = np.zeros((total, self.cfg.key_width), np.uint8)
        pk[:n] = keys[:, : self.cfg.key_width]
        pv = np.zeros((total,), np.int32)
        pv[:n] = values
        pvalid = np.zeros((total,), bool)
        pvalid[:n] = True
        table, overflow = self._step(
            shard_rows(pk, self.mesh, self.axis),
            shard_rows(pv, self.mesh, self.axis),
            shard_rows(pvalid, self.mesh, self.axis),
        )
        return SortResult(table, int(jax.device_get(overflow)), self.shard_capacity)


class SortResult:
    def __init__(self, table: KVBatch, overflow: int, shard_capacity: int):
        self.table = table
        self.overflow = overflow
        self.shard_capacity = shard_capacity

    def to_host_sorted(self) -> list[tuple[bytes, int]]:
        """Concatenate per-device sorted valid prefixes -> global order.

        Warns loudly if rows were dropped (overflowed range bins): the
        result is then NOT a permutation of the input — re-sort with a
        higher skew_factor (sort_strings does this automatically).
        """
        if self.overflow:
            import logging

            logging.getLogger("locust_tpu").warning(
                "sample sort dropped %d rows (range-bin overflow); "
                "output is truncated — raise skew_factor",
                self.overflow,
            )
        if jax.process_count() > 1:  # exercised by tests/test_multiprocess.py
            from jax.experimental import multihost_utils

            lanes, values, valid = multihost_utils.process_allgather(
                (self.table.key_lanes, self.table.values, self.table.valid),
                tiled=True,
            )
        else:
            lanes, values, valid = jax.device_get(
                (self.table.key_lanes, self.table.values, self.table.valid)
            )
        out: list[tuple[bytes, int]] = []
        n_shards = lanes.shape[0] // self.shard_capacity
        for d in range(n_shards):
            lo, hi = d * self.shard_capacity, (d + 1) * self.shard_capacity
            m = np.asarray(valid[lo:hi])
            shard_lanes = np.asarray(lanes[lo:hi])[m]
            shard_vals = np.asarray(values[lo:hi])[m]
            n_rows, n_lanes = shard_lanes.shape
            keys = shard_lanes.astype(">u4").view(np.uint8).reshape(n_rows, n_lanes * 4)
            out.extend(
                (k, int(v))
                for k, v in zip(bytes_ops.rows_to_strings(keys), shard_vals)
            )
        return out


def sort_strings(
    strings: list[bytes],
    mesh: jax.sharding.Mesh,
    cfg: EngineConfig | None = None,
    max_retries: int | None = None,
    **kw,
) -> list[bytes]:
    """Convenience: globally sort byte strings, truncated to key_width.

    Lossless: if a skewed/duplicate-heavy distribution overflows a range
    bin, the sort is retried with DOUBLED skew_factor (bigger bins).  The
    default budget doubles until ``skew_factor >= n_dev``, at which point
    one bin holds an entire device shard and overflow is impossible — so
    the default path cannot fail on ANY input that fits the mesh.  An
    explicit ``max_retries`` caps the doublings instead, raising
    ``ValueError`` rather than returning a silently truncated "sorted"
    list (round-1 advisor finding: the old code dropped rows with only a
    counter).
    """
    cfg = cfg or EngineConfig()
    n_dev = mesh.shape[DATA_AXIS]
    rows_per_device = _round_up(max(1, -(-len(strings) // n_dev)), 8)
    rows = bytes_ops.strings_to_rows(strings, cfg.key_width)
    skew = kw.pop("skew_factor", 2.0)
    if max_retries is None:
        max_retries = max(1, math.ceil(math.log2(max(2.0, n_dev / skew))) + 1)
    for _ in range(max_retries + 1):
        ds = DistributedSort(mesh, cfg, rows_per_device, skew_factor=skew, **kw)
        res = ds.sort_rows(rows)
        if res.overflow == 0:
            return [k for k, _ in res.to_host_sorted()]
        skew *= 2.0
    raise ValueError(
        f"sample sort still dropped {res.overflow} rows at "
        f"skew_factor={skew / 2}; input too skewed for this mesh"
    )
