"""CLI driver: the ``mapreduce <file> [start] [end] [node] [stage]`` contract.

Preserves the reference's positional CLI (reference MapReduce/src/main.cu:362-387)
and staged execution model:

  stage 0 (or absent)  single mode: map -> process -> reduce, print table
  stage 1              staged map: process this node's [start, end) line
                       slice, write the intermediate TSV, exit
                       ("master will start back up", main.cu:432)
  stage 2              staged reduce: load intermediate TSV(s), reduce,
                       print table

Fixes over the reference, each documented in SURVEY.md Appendix A:
  Q9 — unguarded argv reads -> argparse with the same positional contract.
  Q6 — the reference's reduce stage never re-sorts loaded intermediate data
       (correct only if the missing master pre-sorted globally); our reduce
       stage always sorts, so any concatenation order is correct.
  Q5/Q10 — clean TSV keys; only live entries written.

Timing report mirrors the reference's three chrono spans (main.cu:405-468)
— in milliseconds, not its UB %d-of-duration printf (Q7).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

STAGE_SINGLE, STAGE_MAP, STAGE_REDUCE = 0, 1, 2
DEFAULT_INTERMEDIATE = "/tmp/out.txt"  # reference path, main.cu:428


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mapreduce",
        description="TPU-native MapReduce (WordCount) with staged multi-node mode",
    )
    p.add_argument("filename", help="input text file (stage 0/1); ignored for stage 2")
    p.add_argument("line_start", nargs="?", type=int, default=-1)
    p.add_argument("line_end", nargs="?", type=int, default=-1)
    p.add_argument("node_num", nargs="?", type=int, default=0)
    p.add_argument("stage", nargs="?", type=int, default=STAGE_SINGLE,
                   choices=[STAGE_SINGLE, STAGE_MAP, STAGE_REDUCE])
    p.add_argument("--intermediate", "-i", action="append", default=None,
                   help="intermediate TSV path(s); default "
                        f"{DEFAULT_INTERMEDIATE} (reference main.cu:428)")
    p.add_argument("--block-lines", type=int, default=4096)
    p.add_argument("--line-width", type=int, default=128)
    p.add_argument("--key-width", type=int, default=32)
    p.add_argument("--emits-per-line", type=int, default=20)
    p.add_argument("--no-timing", action="store_true")
    p.add_argument("--limit", type=int, default=None,
                   help="print only the first N table rows")
    p.add_argument("--checkpoint-dir", default=None,
                   help="crash-resumable block-granular snapshots: a re-run "
                        "with the same corpus+config resumes at the last "
                        "snapshot (TPU upgrade of the reference's "
                        "/tmp/out.txt restartability, SURVEY.md §5)")
    def positive_int(s: str) -> int:
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    p.add_argument("--checkpoint-every", type=positive_int, default=8,
                   help="blocks between snapshots (with --checkpoint-dir)")
    p.add_argument("--backend", choices=["auto", "cpu", "tpu"], default="auto",
                   help="auto: accelerator if its init probe passes, else CPU; "
                        "cpu: pin CPU and deregister the TPU plugin (immune to "
                        "a wedged tunnel); tpu: require an accelerator")
    p.add_argument("--trace", action="store_true",
                   help="print a wall-clock span report (load/run/output) "
                        "on stderr in addition to the stage report")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax/XLA profiler trace of the run into "
                        "this directory (view with TensorBoard/XProf)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return _run(args)
    except OSError as e:
        print(f"mapreduce: error: {e}", file=sys.stderr)
        return 1


def _run(args) -> int:

    # Backend resolution MUST precede any jax backend use: a wedged remote-
    # TPU plugin would otherwise hang even JAX_PLATFORMS=cpu runs
    # (locust_tpu/backend.py; VERDICT.md round-1 weak #1).
    from locust_tpu.backend import select_backend

    try:
        backend = select_backend(args.backend, probe_timeout_s=90, retries=2)
    except RuntimeError as e:
        print(f"mapreduce: error: {e}", file=sys.stderr)
        return 1
    print(f"[locust] backend: {backend}", file=sys.stderr)

    # Import jax lazily so --help works instantly.
    from locust_tpu.config import EngineConfig
    from locust_tpu.core.kv import KVBatch
    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.io import loader, serde
    import jax.numpy as jnp

    cfg = EngineConfig(
        block_lines=args.block_lines,
        line_width=args.line_width,
        key_width=args.key_width,
        emits_per_line=args.emits_per_line,
    )
    eng = MapReduceEngine(cfg)
    inter = args.intermediate or [DEFAULT_INTERMEDIATE]

    # --trace / --profile-dir wire the hardening utils (SURVEY.md §5
    # tracing): wall-clock spans + optional XLA profiler capture.
    import contextlib

    from locust_tpu.utils import SpanTimer, device_trace

    timer = SpanTimer()
    prof = (
        device_trace(args.profile_dir)
        if args.profile_dir
        else contextlib.nullcontext()
    )

    if args.stage in (STAGE_SINGLE, STAGE_MAP):
        with prof:
            with timer.span("load"):
                rows = loader.load_rows(
                    args.filename, cfg.line_width, args.line_start, args.line_end
                )
            print(f"[locust] {rows.shape[0]} lines loaded", file=sys.stderr)
            with timer.span("run"):
                # Each run method syncs internally, so the span is accurate.
                if args.checkpoint_dir:
                    res = eng.run_checkpointed(
                        rows, args.checkpoint_dir, every=args.checkpoint_every
                    )
                elif args.no_timing:
                    res = eng.run_fused(rows)
                else:
                    res = eng.timed_run(rows)
            if not args.no_timing:
                # The reference's per-stage report (README.md:72-88 format).
                print(f"Map stage:     {res.times.map_ms:10.3f} ms", file=sys.stderr)
                print(f"Process stage: {res.times.process_ms:10.3f} ms", file=sys.stderr)
                print(f"Reduce stage:  {res.times.reduce_ms:10.3f} ms", file=sys.stderr)
            if res.truncated:
                print("[locust] WARN: table capacity exceeded; tail keys dropped",
                      file=sys.stderr)
            with timer.span("output"):
                if args.stage == STAGE_MAP:
                    out = inter[0]
                    serde.write_tsv(res.to_host_pairs(), out)
                    print(f"[locust] node {args.node_num}: intermediate written to {out}",
                          file=sys.stderr)
                else:
                    _print_table(res.to_host_pairs(), args.limit)
        if args.trace:
            print(timer.report(), file=sys.stderr)
        return 0

    # STAGE_REDUCE: merge intermediate TSVs from map nodes; always re-sort (Q6).
    with prof:
        with timer.span("load"):
            key_rows_list, values_list = [], []
            for path in inter:
                k, v = serde.read_tsv(path, cfg.key_width)
                key_rows_list.append(k)
                values_list.append(v)
            keys = np.concatenate(key_rows_list) if key_rows_list else np.zeros((0, cfg.key_width), np.uint8)
            values = np.concatenate(values_list) if values_list else np.zeros((0,), np.int32)
        print(f"[locust] node {args.node_num}: {keys.shape[0]} intermediate pairs "
              f"from {len(inter)} file(s)", file=sys.stderr)
        batch = KVBatch.from_bytes(
            jnp.asarray(keys), jnp.asarray(values), jnp.ones(keys.shape[0], bool)
        )
        from locust_tpu.engine import finalize_host_pairs
        from locust_tpu.ops import segment_reduce, sort_and_compact

        with timer.span("run"):
            table = segment_reduce(sort_and_compact(batch, cfg.sort_mode), eng.combine)
            pairs = finalize_host_pairs(table, eng.combine)  # device sync
        with timer.span("output"):
            _print_table(pairs, args.limit)
    if args.trace:
        print(timer.report(), file=sys.stderr)
    return 0


def _print_table(pairs: list[tuple[bytes, int]], limit=None) -> None:
    """Final ``key<TAB>count`` table on stdout (analog of printKeyIntValues,
    main.cu:126-134 — we print two columns, not its internal three)."""
    for k, v in pairs[: limit if limit is not None else len(pairs)]:
        sys.stdout.buffer.write(k + b"\t" + str(v).encode() + b"\n")
    sys.stdout.flush()


if __name__ == "__main__":
    raise SystemExit(main())
