"""CLI driver: the ``mapreduce <file> [start] [end] [node] [stage]`` contract.

Preserves the reference's positional CLI (reference MapReduce/src/main.cu:362-387)
and staged execution model:

  stage 0 (or absent)  single mode: map -> process -> reduce, print table
  stage 1              staged map: process this node's [start, end) line
                       slice, write the intermediate TSV, exit
                       ("master will start back up", main.cu:432)
  stage 2              staged reduce: load intermediate TSV(s), reduce,
                       print table

Fixes over the reference, each documented in SURVEY.md Appendix A:
  Q9 — unguarded argv reads -> argparse with the same positional contract.
  Q6 — the reference's reduce stage never re-sorts loaded intermediate data
       (correct only if the missing master pre-sorted globally); our reduce
       stage always sorts, so any concatenation order is correct.
  Q5/Q10 — clean TSV keys; only live entries written.

Timing report mirrors the reference's three chrono spans (main.cu:405-468)
— in milliseconds, not its UB %d-of-duration printf (Q7).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from locust_tpu import obs  # jax-free; zero-overhead unless --trace-out

STAGE_SINGLE, STAGE_MAP, STAGE_REDUCE = 0, 1, 2
DEFAULT_INTERMEDIATE = "/tmp/out.txt"  # reference path, main.cu:428


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mapreduce",
        description="TPU-native MapReduce (WordCount) with staged multi-node mode",
    )
    p.add_argument("filename", help="input text file (stage 0/1); ignored for stage 2")
    p.add_argument("line_start", nargs="?", type=int, default=-1)
    p.add_argument("line_end", nargs="?", type=int, default=-1)
    p.add_argument("node_num", nargs="?", type=int, default=0)
    p.add_argument("stage", nargs="?", type=int, default=STAGE_SINGLE,
                   choices=[STAGE_SINGLE, STAGE_MAP, STAGE_REDUCE])
    p.add_argument("--intermediate", "-i", action="append", default=None,
                   help="intermediate path(s); default "
                        f"{DEFAULT_INTERMEDIATE} (reference main.cu:428)")
    p.add_argument("--inter-format", choices=["tsv", "bin"], default="tsv",
                   help="stage-1 intermediate format: 'tsv' (reference "
                        "parity, key\\tvalue text) or 'bin' (packed binary "
                        "KV, docs/DATAPLANE.md — what the distributor "
                        "master requests).  Stage 2 sniffs the format per "
                        "file, so mixed inputs reduce fine.")
    p.add_argument("--block-lines", type=int, default=4096)
    p.add_argument("--line-width", type=int, default=128)
    p.add_argument("--key-width", type=int, default=32)
    p.add_argument("--emits-per-line", type=int, default=20)
    p.add_argument("--auto-caps", action="store_true",
                   help="size key_width / emits_per_line to the corpus's "
                        "measured maxima (one host pass; lossless — output "
                        "identical to the configured caps, smaller sorted "
                        "arrays).  With --stream the measuring pass re-reads "
                        "the file in bounded memory.  No effect for stage 2.")
    p.add_argument("--no-timing", action="store_true")
    p.add_argument("--limit", type=int, default=None,
                   help="print only the first N table rows")
    p.add_argument("--checkpoint-dir", default=None,
                   help="crash-resumable block-granular snapshots: a re-run "
                        "with the same corpus+config resumes at the last "
                        "snapshot (TPU upgrade of the reference's "
                        "/tmp/out.txt restartability, SURVEY.md §5)")
    def positive_int(s: str) -> int:
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    p.add_argument("--checkpoint-every", type=positive_int, default=8,
                   help="blocks between snapshots (with --checkpoint-dir)")
    p.add_argument("--sync-checkpoint", action="store_true",
                   help="write snapshots synchronously inside the fold "
                        "loop instead of on the bounded background writer "
                        "(EngineConfig.async_checkpoint; identical on-disk "
                        "format — async marks a generation and the writer "
                        "copies/serializes off the hot path, latest-wins "
                        "if the loop laps it; docs/DESIGN.md)")
    from locust_tpu.config import SORT_MODES

    p.add_argument("--sort-mode", choices=list(SORT_MODES),
                   default=None,
                   help="Process-stage sort strategy (config.EngineConfig."
                        "sort_mode); default follows the measured "
                        "per-backend choice (config.default_sort_mode); "
                        "variant timings in artifacts/")
    p.add_argument("--mesh", action="store_true",
                   help="run stage 0/1 on ALL visible devices via the "
                        "all-to-all shuffle engine (DistributedMapReduce) "
                        "instead of the single-device engine; prints "
                        "per-shard stats on stderr")
    p.add_argument("--slices", type=positive_int, default=None,
                   help="with --mesh: use the hierarchical engine on a "
                        "[slices, devices/slice] mesh — per-round shuffle "
                        "stays intra-slice (ICI), slices combine once at "
                        "the end (DCN)")
    p.add_argument("--stream", action="store_true",
                   help="bounded-memory ingest: stream the corpus in "
                        "blocks instead of materializing it (for corpora "
                        "that do not fit RAM)")
    p.add_argument("--backend", choices=["auto", "cpu", "tpu"], default="auto",
                   help="auto: accelerator if its init probe passes, else CPU; "
                        "cpu: pin CPU and deregister the TPU plugin (immune to "
                        "a wedged tunnel); tpu: require an accelerator")
    p.add_argument("--coordinator", default=None,
                   help="multi-process pod launch: coordinator address "
                        "host:port (jax.distributed.initialize); every "
                        "process runs the same command with its own "
                        "--process-id.  Requires --mesh; only process 0 "
                        "prints the table.  Inside managed TPU "
                        "environments pass --coordinator alone and the "
                        "process count/id are auto-detected.")
    p.add_argument("--num-processes", type=int, default=None,
                   help="with --coordinator: total process count")
    p.add_argument("--process-id", type=int, default=None,
                   help="with --coordinator: this process's index")
    p.add_argument("--trace", action="store_true",
                   help="print a wall-clock span report (load/run/output) "
                        "on stderr in addition to the stage report")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="structured telemetry (locust_tpu.obs): record "
                        "the run's spans/events/metrics and export a "
                        "Chrome-trace/Perfetto JSON timeline to FILE "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--fault-plan", default=None,
                   help="chaos-test fault injection plan: JSON text or a "
                        "path to a JSON file (also $LOCUST_FAULT_PLAN); "
                        "zero overhead when unset — see docs/FAULTS.md")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax/XLA profiler trace of the run into "
                        "this directory (view with TensorBoard/XProf)")
    return p


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    # Workload-ladder subcommands (PageRank / inverted index / TF-IDF,
    # cli_apps.py).  Dispatch on the first argument so the reference's
    # bare positional WordCount contract stays intact; a FILE literally
    # named "pagerank" needs ./pagerank.
    from locust_tpu.cli_apps import SUBCOMMANDS
    from locust_tpu import cli_apps

    if argv and argv[0] in SUBCOMMANDS:
        return cli_apps.main(argv[0], argv[1:])
    args = build_parser().parse_args(argv)
    if args.trace_out:
        obs.enable(process="cli")
    try:
        return _run(args)
    except OSError as e:
        print(f"mapreduce: error: {e}", file=sys.stderr)
        return 1
    finally:
        if args.trace_out:
            # Telemetry must not take down (or re-color) the run: an
            # unwritable trace path is a warning, never the exit status.
            try:
                obs.export(args.trace_out)
                print(f"[locust] trace written to {args.trace_out}",
                      file=sys.stderr)
            except OSError as e:
                print(f"[locust] trace export to {args.trace_out} "
                      f"failed: {e}", file=sys.stderr)
            obs.disable()


def _run(args) -> int:

    # Fault injection first: the plan must be live before any distributor
    # RPC or checkpoint write it is meant to intercept (docs/FAULTS.md).
    # Pure host-side control-plane hooks; a run with no plan pays one
    # None-check per hook and nothing else.
    from locust_tpu.utils import faultplan

    faultplan.install(args.fault_plan)

    # Pod launch: join the coordination service BEFORE any in-process jax
    # backend init (jax.distributed.initialize is a no-op too late once
    # jax.devices() has run).  The same command line runs on every
    # process with its own --process-id — the JAX-native analog of the
    # reference's per-node [start, end) staged contract (main.cu:47-54).
    multiproc = (
        args.coordinator is not None
        or args.num_processes is not None
        or args.process_id is not None
    )
    if multiproc:
        if not (args.mesh or args.slices):
            print(
                "mapreduce: error: --coordinator/--num-processes/"
                "--process-id require --mesh",
                file=sys.stderr,
            )
            return 2
        from locust_tpu.parallel.mesh import initialize_multihost

        initialize_multihost(
            args.coordinator, args.num_processes, args.process_id
        )

    # Backend resolution MUST precede any jax backend use: a wedged remote-
    # TPU plugin would otherwise hang even JAX_PLATFORMS=cpu runs
    # (locust_tpu/backend.py; VERDICT.md round-1 weak #1).
    from locust_tpu.backend import select_backend_cli

    if select_backend_cli(args.backend, prog="mapreduce") is None:
        return 1

    if args.slices and not args.mesh:
        args.mesh = True  # --slices implies the mesh engine; never ignore it

    # Import jax lazily so --help works instantly.
    from locust_tpu.config import EngineConfig, default_sort_mode
    from locust_tpu.core.kv import KVBatch
    from locust_tpu.engine import MapReduceEngine
    from locust_tpu.io import loader, serde
    import jax
    import jax.numpy as jnp

    if args.sort_mode is None:
        # Safe to touch jax here: select_backend_cli above already pinned
        # the platform (a wedged tunnel was handled there), so
        # default_backend() initializes exactly what was selected.
        args.sort_mode = default_sort_mode(jax.default_backend())

    cfg = EngineConfig(
        block_lines=args.block_lines,
        line_width=args.line_width,
        key_width=args.key_width,
        emits_per_line=args.emits_per_line,
        sort_mode=args.sort_mode,
        async_checkpoint=not args.sync_checkpoint,
    )

    # --trace / --profile-dir wire the hardening utils (SURVEY.md §5
    # tracing): wall-clock spans + optional XLA profiler capture.
    import contextlib

    from locust_tpu.utils import SpanTimer, device_trace

    timer = SpanTimer()
    prof = (
        device_trace(args.profile_dir)
        if args.profile_dir
        else contextlib.nullcontext()
    )

    # --auto-caps: measure the corpus once and shrink key_width /
    # emits_per_line to their lossless floors (never above the flags);
    # table_size pins to the flag-config resolution so the output table is
    # byte-identical either way (see bench.py, 1.7x CPU on hamlet).
    # SpanTimer spans accumulate per name, so this preload bills to the
    # same "load" span the main path uses.
    preloaded_rows = None
    auto_caps_fp = None  # stream identity at measure time (checked at run)
    if args.auto_caps and args.stage in (STAGE_SINGLE, STAGE_MAP):
        import dataclasses

        with timer.span("load"), obs.span("cli.load"):
            if args.stream:
                # Bounded-memory measuring pass: the file is read twice
                # (measure, then run) but never materialized — the caps
                # win usually dwarfs the extra host read on device-bound
                # streaming runs.  The fingerprint pins the file identity
                # so a corpus mutated between the passes is caught
                # instead of silently under-sizing the caps.
                measure_stream = loader.StreamingCorpus(
                    args.filename, cfg.line_width, cfg.block_lines,
                    args.line_start, args.line_end,
                )
                auto_caps_fp = measure_stream.fingerprint()
                max_tok, max_per_line = loader.measure_caps_stream(
                    measure_stream
                )
            else:
                preloaded_rows = loader.load_rows(
                    args.filename, cfg.line_width,
                    args.line_start, args.line_end,
                )
                # Measured on the width-truncated rows the engine will
                # actually see (full row bytes, NOT NUL-truncated: an
                # embedded NUL is a token boundary to the device
                # tokenizer and post-NUL tokens still count).
                max_tok, max_per_line = loader.measure_caps(
                    [r.tobytes() for r in preloaded_rows]
                )
        kw, epl = loader.size_caps(
            max_tok, max_per_line, cfg.key_width, cfg.emits_per_line
        )
        cfg = dataclasses.replace(
            cfg,
            key_width=kw,
            emits_per_line=epl,
            table_size=cfg.resolved_table_size,
        )
        print(
            f"[locust] auto-caps: max_token={max_tok}B "
            f"max_tokens/line={max_per_line} -> key_width="
            f"{cfg.key_width} emits_per_line={cfg.emits_per_line}",
            file=sys.stderr,
        )

    # The single-device stage-0/1 path builds its engine INSIDE the
    # compiled plan below; only the stage-2 reduce branch needs one
    # directly (for the normalized combine), so nothing is built twice.
    inter = args.intermediate or [DEFAULT_INTERMEDIATE]

    if args.mesh and args.stage in (STAGE_SINGLE, STAGE_MAP):
        rc = _run_mesh(args, cfg, timer, prof, preloaded_rows, auto_caps_fp)
        if args.trace:
            print(timer.report(), file=sys.stderr)
        return rc

    if args.stage in (STAGE_SINGLE, STAGE_MAP):
        # WordCount runs as a compiled PLAN (docs/PLAN.md): the driver
        # constructs the canonical DAG (source -> tokenize -> group ->
        # sum -> table) and the compiler lowers it back onto this same
        # engine — byte-identical output, the reference's staged timing
        # report intact, and checkpoints still land at the fold-stage
        # boundary (plan/compile.py).
        from locust_tpu.plan import wordcount_plan
        from locust_tpu.plan.compile import compile_plan

        wc_plan = compile_plan(wordcount_plan(), cfg)
        with prof:
            with timer.span("load"), obs.span("cli.load"):
                if args.stream:
                    rows = None
                    stream = loader.StreamingCorpus(
                        args.filename, cfg.line_width, cfg.block_lines,
                        args.line_start, args.line_end,
                    )
                    if _stale_auto_caps(stream, auto_caps_fp):
                        return 1
                else:
                    rows = (
                        preloaded_rows
                        if preloaded_rows is not None
                        else loader.load_rows(
                            args.filename, cfg.line_width,
                            args.line_start, args.line_end,
                        )
                    )
                    print(f"[locust] {rows.shape[0]} lines loaded", file=sys.stderr)
            with timer.span("run"), obs.span("cli.run"):
                # Each run method syncs internally, so the span is accurate.
                pairs = None
                if args.stream:
                    kw = {}
                    if args.checkpoint_dir:
                        kw = dict(
                            checkpoint_dir=args.checkpoint_dir,
                            every=args.checkpoint_every,
                            fingerprint=stream.fingerprint(),
                        )
                    res = wc_plan.run_stream(stream, **kw)
                else:
                    pres = wc_plan.run(
                        rows,
                        timed=not args.no_timing,
                        render=False,
                        # The staged map node only dumps the raw table
                        # (dump_intermediate): skip the host finalize
                        # its output path would discard.
                        finalize=args.stage != STAGE_MAP,
                        checkpoint_dir=args.checkpoint_dir or None,
                        every=args.checkpoint_every,
                    )
                    res = pres.run_result
                    pairs = pres.value
            if args.stream and res.stream is not None:
                # Zero-stall executor accounting: backpressure stall +
                # checkpoint mark/write stats (engine.run_stream).
                print(f"[locust] stream: {res.stream}", file=sys.stderr)
            if not args.no_timing:
                # The reference's per-stage report (README.md:72-88
                # stages), through SpanTimer.report(): stable descending
                # sort + percent-of-total (format pinned by
                # tests/test_profiling.py).
                st = SpanTimer()
                st.spans_ms = {
                    "Map stage": res.times.map_ms,
                    "Process stage": res.times.process_ms,
                    "Reduce stage": res.times.reduce_ms,
                }
                print(st.report(), file=sys.stderr)
            # Opportunistic TPU evidence (no-op on CPU): any CLI run that
            # lands on real hardware leaves a stage-timing row behind.
            from locust_tpu.utils import artifacts

            artifacts.record(
                "cli_run",
                {
                    "lines": int(rows.shape[0]) if rows is not None else -1,
                    "map_ms": round(res.times.map_ms, 3),
                    "process_ms": round(res.times.process_ms, 3),
                    "reduce_ms": round(res.times.reduce_ms, 3),
                    "total_ms": round(res.times.total_ms, 3),
                    "distinct": res.num_segments,
                    "stage": args.stage,
                },
            )
            if res.truncated:
                print("[locust] WARN: table capacity exceeded; tail keys dropped",
                      file=sys.stderr)
            with timer.span("output"), obs.span("cli.output"):
                if args.stage == STAGE_MAP:
                    out = inter[0]
                    res.dump_intermediate(out, args.inter_format)
                    print(f"[locust] node {args.node_num}: intermediate written to {out}",
                          file=sys.stderr)
                else:
                    # The plan run already host-finalized the table
                    # (PlanResult.value); the stream path decodes here.
                    _print_table(
                        pairs if pairs is not None else res.to_host_pairs(),
                        args.limit,
                    )
        if args.trace:
            print(timer.report(), file=sys.stderr)
        return 0

    # STAGE_REDUCE: merge intermediate TSVs from map nodes; always re-sort (Q6).
    with prof:
        with timer.span("load"), obs.span("cli.load"):
            key_rows_list, values_list = [], []
            for path in inter:
                k, v = serde.read_intermediate(path, cfg.key_width)
                key_rows_list.append(k)
                values_list.append(v)
            keys = np.concatenate(key_rows_list) if key_rows_list else np.zeros((0, cfg.key_width), np.uint8)
            values = np.concatenate(values_list) if values_list else np.zeros((0,), np.int32)
        print(f"[locust] node {args.node_num}: {keys.shape[0]} intermediate pairs "
              f"from {len(inter)} file(s)", file=sys.stderr)
        batch = KVBatch.from_bytes(
            jnp.asarray(keys), jnp.asarray(values), jnp.ones(keys.shape[0], bool)
        )
        from locust_tpu.engine import finalize_host_pairs
        from locust_tpu.ops import segment_reduce, sort_and_compact

        eng = MapReduceEngine(cfg)  # stage 2 only: the normalized combine
        with timer.span("run"), obs.span("cli.run"):
            table = segment_reduce(sort_and_compact(batch, cfg.sort_mode), eng.combine)
            pairs = finalize_host_pairs(table, eng.combine)  # device sync
        with timer.span("output"), obs.span("cli.output"):
            _print_table(pairs, args.limit)
    if args.trace:
        print(timer.report(), file=sys.stderr)
    return 0


def _stale_auto_caps(stream, auto_caps_fp) -> bool:
    """True (and prints the error) if the corpus changed between the
    --auto-caps measuring pass and the run pass — under-sized caps would
    silently truncate or drop the new content's tokens otherwise."""
    if auto_caps_fp is None or stream.fingerprint() == auto_caps_fp:
        return False
    print(
        "mapreduce: error: corpus changed between the --auto-caps "
        "measuring pass and the run; re-run (or drop --auto-caps for a "
        "file that is being written to)",
        file=sys.stderr,
    )
    return True


def _run_mesh(args, cfg, timer, prof, preloaded_rows=None,
              auto_caps_fp=None) -> int:
    """Stage 0/1 over ALL visible devices: the CLI face of the mesh engine.

    The reference's distributed mode is CLI-driven (main.cu:358-387,
    README.md:12-24) but its shipped entrypoint is single-GPU; here one
    ``--mesh`` flag routes the same positional contract through the
    all-to-all shuffle (parallel/shuffle.py), so a multi-chip host uses
    every chip (VERDICT r2 missing #3).
    """
    import time as _time

    import numpy as np

    import jax

    from locust_tpu.io import loader, serde
    from locust_tpu.parallel.mesh import make_mesh
    from locust_tpu.parallel.shuffle import DistributedMapReduce

    inter = args.intermediate or [DEFAULT_INTERMEDIATE]
    if args.slices:
        from locust_tpu.parallel.hierarchical import HierarchicalMapReduce
        from locust_tpu.parallel.mesh import make_mesh_2d

        mesh = make_mesh_2d(args.slices)
        dmr = HierarchicalMapReduce(mesh, cfg)
        print(
            f"[locust] hierarchical mesh: {dmr.n_slices} slice(s) x "
            f"{dmr.devs_per_slice} device(s), {dmr.lines_per_round} "
            f"lines/round, bin_capacity={dmr.bin_capacity}, "
            f"shard_capacity={dmr.shard_capacity}",
            file=sys.stderr,
        )
    else:
        mesh = make_mesh()
        dmr = DistributedMapReduce(mesh, cfg)
        print(
            f"[locust] mesh: {dmr.n_dev} device(s), {dmr.lines_per_round} "
            f"lines/round, bin_capacity={dmr.bin_capacity}, "
            f"shard_capacity={dmr.shard_capacity}",
            file=sys.stderr,
        )
    n_dev = dmr.n_dev
    with prof:
        t0 = _time.perf_counter()
        with timer.span("load"), obs.span("cli.load"):
            kw = {}
            if args.checkpoint_dir:
                kw = dict(
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every,
                )
            if args.stream:
                stream = loader.StreamingCorpus(
                    args.filename, cfg.line_width, dmr.lines_per_round,
                    args.line_start, args.line_end,
                )
                if _stale_auto_caps(stream, auto_caps_fp):
                    return 1
                if args.checkpoint_dir:
                    kw["fingerprint"] = stream.fingerprint()
            else:
                rows = (
                    preloaded_rows
                    if preloaded_rows is not None
                    else loader.load_rows(
                        args.filename, cfg.line_width,
                        args.line_start, args.line_end,
                    )
                )
                print(f"[locust] {rows.shape[0]} lines loaded", file=sys.stderr)
        with timer.span("run"), obs.span("cli.run"):
            res = (
                dmr.run_stream(stream, **kw)
                if args.stream
                else dmr.run(rows, **kw)
            )
            pairs = res.to_host_pairs()  # gathers + syncs
        run_ms = (_time.perf_counter() - t0) * 1e3

        # Per-shard report: one hash shard per shard_capacity rows (the
        # hierarchical table has devs_per_slice shards, the flat one n_dev).
        # Gather ONLY the valid mask through the multi-process-safe path —
        # a plain device_get of the sharded table touches non-addressable
        # devices on a pod, and the full-table gather would move
        # key_lanes+values over DCN just to be discarded.
        from locust_tpu.parallel.mesh import gather_host_array

        shard_live = gather_host_array(res.table.valid).reshape(
            -1, dmr.shard_capacity
        ).sum(axis=1)
        for d in range(shard_live.shape[0]):
            print(
                f"[locust] shard {d}: {int(shard_live[d])} keys",
                file=sys.stderr,
            )
        print(
            f"[locust] distinct={res.distinct} drain_rounds={res.drain_rounds} "
            f"emit_overflow={res.emit_overflow} "
            f"shuffle_overflow={res.shuffle_overflow} "
            f"truncated={res.truncated} total={run_ms:.1f} ms",
            file=sys.stderr,
        )
        if res.truncated:
            print(
                "[locust] WARN: a shard's table capacity was exceeded; "
                "tail keys dropped",
                file=sys.stderr,
            )
        from locust_tpu.utils import artifacts

        artifacts.record(
            "cli_mesh_run",
            {
                "n_dev": n_dev,
                "distinct": res.distinct,
                "drain_rounds": res.drain_rounds,
                "truncated": res.truncated,
                "total_ms": round(run_ms, 3),
                "stage": args.stage,
            },
        )
        with timer.span("output"), obs.span("cli.output"):
            if args.stage == STAGE_MAP:
                out = inter[0]
                serde.write_intermediate(pairs, out, args.inter_format)
                print(
                    f"[locust] node {args.node_num}: intermediate written "
                    f"to {out}",
                    file=sys.stderr,
                )
            else:
                _print_table(pairs, args.limit)
    return 0


def _print_table(pairs: list[tuple[bytes, int]], limit=None) -> None:
    """Final ``key<TAB>count`` table on stdout (analog of printKeyIntValues,
    main.cu:126-134 — we print two columns, not its internal three).  On a
    multi-process pod every process holds the gathered table
    (to_host_pairs allgathers); only process 0 prints so the pod's
    combined stdout is one table, not N interleaved copies."""
    import jax

    if jax.process_count() > 1 and jax.process_index() != 0:
        return
    for k, v in pairs[: limit if limit is not None else len(pairs)]:
        sys.stdout.buffer.write(k + b"\t" + str(v).encode() + b"\n")
    sys.stdout.flush()


if __name__ == "__main__":
    raise SystemExit(main())
