"""Lower a validated logical plan onto the existing engine/mesh tiers.

NO new device code lives here (the tentpole's constraint): compilation
pattern-matches subgraphs of the DAG onto the primitives the repo
already trusts —

  * ``map(tokenize_count) → shuffle(by_key) → reduce(sum)`` over a text
    source fuses into the engine's one-sort-per-block fold
    (``MapReduceEngine``; ``DistributedMapReduce`` under ``mesh=True``),
    exactly the WordCount pipeline — so a plan-compiled run IS the
    hand-wired run, byte for byte, and checkpoint placement rides the
    fold-stage boundary (``run_checkpointed``/``run_stream``);
  * ``map(tokenize_pairs) → shuffle → reduce(sum)`` fuses into the
    composite-key tf fold (``apps.tfidf.term_doc_counts``);
  * ``map(tokenize_pairs) → shuffle → reduce(collect_docs)`` fuses into
    the inverted-index fold (``apps.inverted_index``, mesh variant under
    ``mesh=True``);
  * ``map(tfidf_score)`` over a tf table is the host-side rescore fold
    (df/n_docs over a table orders of magnitude smaller than the
    corpus — the ``build_tfidf`` stance);
  * ``iterate(pagerank)`` over an edge source lowers onto
    ``apps.pagerank`` (``ShardedPageRank`` under ``mesh=True``);
  * ``join(inner)`` merges two terminal tables on key — a host fold
    over device-built tables, like every other table-level finalize;
  * ``sink`` renders the terminal value to the EXACT bytes the
    hand-wired CLI drivers print (the byte-identity contract the tests
    pin).

A composition outside these signatures is a loud ``PlanError`` at
compile time, never a silently-wrong execution.  jax-free at import
(jax enters inside ``run``) so the serve control plane can compile-check
plans without a backend.
"""

from __future__ import annotations

import dataclasses

from locust_tpu import obs
from locust_tpu.plan.nodes import Node, Plan, PlanError
from locust_tpu.plan.optimize import (
    incremental_delta,
    optimize as optimize_plan,
    record_rewrite,
)

# Serve-side bound on the pagerank state size: ``num_nodes`` derives
# from the max node id in the CORPUS, so a 12-byte submit naming node
# 2e9 would otherwise allocate multi-GB dense rank/degree vectors inside
# the multi-tenant daemon (overload must reject, never OOM —
# serve/daemon.py).  2^24 nodes ≈ 67 MB per dense float32 vector.  The
# CLI path (``run()``) stays unbounded like the pre-plan driver: a
# single-tenant process may spend its own memory.
SERVE_MAX_PAGERANK_NODES = 1 << 24

# Lowered stage shapes (the compiler's internal vocabulary; every
# NODE_KINDS entry is matched somewhere below — analysis rule R014
# checks this file for exactly that).
_FOLDS = {
    ("tokenize_count", "sum"): "wordcount",
    ("tokenize_pairs", "sum"): "tf",
    ("tokenize_pairs", "collect_docs"): "index",
}


@dataclasses.dataclass
class PlanResult:
    """One executed plan: the workload-shaped ``value`` (pairs list /
    dict / ranks array), the sink-rendered ``output`` bytes (``None``
    when ``render=False``), and the loss/limit accounting the serve
    tier reports."""

    value: object
    output: bytes | None
    distinct: int
    truncated: bool
    overflow_tokens: int
    run_result: object | None = None  # engine RunResult (wordcount fold)


class CompiledPlan:
    """A plan lowered to an executable stage tree.

    Holds the underlying engine lazily and reuses it across ``run``
    calls, so a resident ``CompiledPlan`` (the serve tier's warm-
    executable cache holds these for plan jobs) keeps its jit caches
    warm exactly like a resident ``MapReduceEngine`` does.
    """

    def __init__(self, plan: Plan, cfg=None, mesh: bool = False,
                 optimize: bool = True):
        self.plan = plan  # the ORIGINAL plan: cache/WAL identity
        self.cfg = cfg
        self.mesh = mesh
        self._engine = None  # lazy MapReduceEngine (wordcount fold)
        # The rewrite pass (plan/optimize.py) runs between validation
        # and lowering; ``self.plan`` stays the original so every
        # fingerprint-keyed identity (warm/result caches, WAL replay,
        # batch keys) is untouched, and the LOWERED plan is the
        # optimizer's output — byte-identical by the rule contracts.
        self.optimized = (
            optimize_plan(plan, cfg=cfg, mesh=mesh) if optimize else None
        )
        self._lowered = (
            self.optimized.plan if self.optimized is not None else plan
        )
        with obs.span("plan.compile", plan=plan.fingerprint()):
            self._by_id = self._lowered.by_id()
            self._sink = self._lowered.sink()
            self._stages: dict[str, tuple] = {}
            self._root = self._lower(self._sink.id)
        if cfg is None and any(
            n.kind == "source" and n.op == "text" for n in plan.nodes
        ):
            raise PlanError(
                "a plan with a text source needs an EngineConfig"
            )
        if mesh and self._needs_mesh_guard():
            raise PlanError(
                "the tf fold has no mesh lowering (the pair table is "
                "device-bounded; use the index plan for the distributed "
                "path)"
            )

    def _needs_mesh_guard(self) -> bool:
        return any(
            s[0] == "fold" and s[1] == "tf" for s in self._stages.values()
        )

    # ------------------------------------------------------------ lowering

    def _lower(self, nid: str) -> str:
        """Classify node ``nid`` (and its producers) into a stage;
        returns the stage id (== node id).  Memoized so a multi-consumer
        node lowers (and later executes) once."""
        if nid in self._stages:
            return nid
        n = self._by_id[nid]
        if n.kind == "source":
            stage = ("source", n)
        elif n.kind == "reduce":
            shuf = self._by_id[n.inputs[0]]
            if shuf.kind != "shuffle":
                raise PlanError(
                    f"node {n.id!r}: reduce must consume a shuffle node "
                    "(the engine fuses group+combine into one sort)"
                )
            mapper = self._by_id[shuf.inputs[0]]
            if mapper.kind != "map":
                raise PlanError(
                    f"node {shuf.id!r}: shuffle must consume a map node"
                )
            fold = _FOLDS.get((mapper.op, n.op))
            if fold is None:
                raise PlanError(
                    f"node {n.id!r}: no fold lowering for map "
                    f"{mapper.op!r} + reduce {n.op!r}"
                )
            src_id = self._lower(mapper.inputs[0])
            stage = ("fold", fold, src_id)
        elif n.kind == "map" and n.op == "tfidf_score":
            tf_id = self._lower(n.inputs[0])
            tf_stage = self._stages[tf_id]
            if not (tf_stage[0] == "fold" and tf_stage[1] == "tf"):
                raise PlanError(
                    f"node {n.id!r}: tfidf_score must consume the tf fold"
                )
            composed = (
                self.optimized is not None
                and n.id in self.optimized.composed_scores
            )
            stage = ("score", tf_id, composed)
        elif n.kind == "map":
            # tokenize maps only exist fused under a shuffle+reduce; a
            # bare token stream has no materialization (the fixed-slot
            # emit tensor is an engine-internal shape).
            raise PlanError(
                f"node {n.id!r}: map {n.op!r} must feed a "
                "shuffle -> reduce chain"
            )
        elif n.kind == "shuffle":
            raise PlanError(
                f"node {n.id!r}: shuffle must feed a reduce node (the "
                "engine's one-sort fold groups and combines together)"
            )
        elif n.kind == "join":
            left = self._lower(n.inputs[0])
            right = self._lower(n.inputs[1])
            stage = ("join", left, right, n.param("combine", "sum"))
        elif n.kind == "iterate":
            src_id = self._lower(n.inputs[0])
            src = self._by_id[src_id]
            if not (src.kind == "source" and src.op == "edges"):
                raise PlanError(
                    f"node {n.id!r}: iterate(pagerank) must consume an "
                    "edges source"
                )
            stage = ("pagerank", src_id,
                     n.param("num_iters", 20), n.param("damping", 0.85))
        elif n.kind == "sink":
            stage = ("render", n.op, self._lower(n.inputs[0]))
        else:  # pragma: no cover - Plan validation owns kind closure
            raise PlanError(f"node {n.id!r}: unknown kind {n.kind!r}")
        self._stages[nid] = stage
        return nid

    # ----------------------------------------------------------- execution

    def run(
        self,
        data=None,
        *,
        num_nodes: int | None = None,
        max_nodes: int | None = None,
        timed: bool = False,
        render: bool = True,
        finalize: bool = True,
        checkpoint_dir: str | None = None,
        every: int = 8,
        sub_cache=None,
        corpus_sha: str | None = None,
        corpus_bytes: bytes | None = None,
    ) -> PlanResult:
        """Execute the compiled plan.

        ``data`` feeds the source node(s): a rows array / list of line
        bytes for a text source, an ``(src, dst)`` edge-array pair for
        an edges source, or a ``{input_name: data}`` dict when sources
        name distinct inputs (``source`` param ``input``; default
        ``"corpus"``).  ``timed`` routes the wordcount fold through
        ``timed_run`` (the reference's stage report); ``checkpoint_dir``
        places crash-resumable snapshots at the fold-stage boundary
        (``run_checkpointed``).  ``render=False`` skips the sink's
        output-bytes rendering (CLI drivers print from ``value``);
        ``finalize=False`` additionally skips the wordcount fold's
        host-pairs decode (``value`` comes back None, ``run_result``
        carries the device table) — for callers like the CLI's staged
        map node that only dump the raw table, where the full decode
        would be paid and discarded.  Only a plan whose sink consumes
        the wordcount fold directly may skip it.
        """
        stage = self._stages[self._stages[self._root][2]]
        if not finalize and not (
            stage[0] == "fold" and stage[1] == "wordcount"
        ):
            raise PlanError(
                "finalize=False is only meaningful for a sink fed by "
                "the wordcount fold (other stages need the decoded value)"
            )
        if not finalize and render:
            # There is no decoded value to render — a None would reach
            # _render as a raw TypeError instead of a loud PlanError.
            raise PlanError("finalize=False requires render=False")
        with obs.span("plan.run", plan=self.plan.fingerprint()):
            ctx = _RunCtx(self, data, num_nodes, timed,
                          checkpoint_dir, every, finalize=finalize,
                          max_nodes=max_nodes, sub_cache=sub_cache,
                          corpus_sha=corpus_sha,
                          corpus_bytes=corpus_bytes)
            value = ctx.eval(self._stages[self._root][2])
            render_op = self._stages[self._root][1]
            output = _render(render_op, value) if render else None
            distinct, truncated, overflow = ctx.accounting(
                self._stages[self._root][2], value
            )
            return PlanResult(
                value=value, output=output, distinct=distinct,
                truncated=truncated, overflow_tokens=overflow,
                run_result=ctx.run_result,
            )

    def run_stream(self, blocks, **kw):
        """Bounded-memory passthrough for a pure wordcount-fold plan:
        delegates to ``MapReduceEngine.run_stream`` (same checkpoint/
        resume contract) and returns the raw ``RunResult`` — the
        streaming CLI's existing stall/ckpt accounting rides it
        unchanged."""
        stage = self._stages[self._stages[self._root][2]]
        if not (stage[0] == "fold" and stage[1] == "wordcount"
                and not self.mesh):
            raise PlanError(
                "run_stream supports the single-device wordcount fold "
                "plan only"
            )
        return self._wordcount_engine().run_stream(blocks, **kw)

    def run_corpus(self, corpus: bytes, *, sub_cache=None,
                   corpus_sha: str | None = None) -> PlanResult:
        """The serve tier's entry: raw corpus bytes in, rendered result
        out.  Text sources split lines exactly like the daemon's batch
        stager (``serve/batch.split_lines``); edge sources parse the
        SNAP ``src dst`` format exactly like the CLI
        (``cli_apps.load_edges``).  ONE corpus only: a plan whose
        sources name distinct ``input``s would silently self-join the
        same bytes — loud instead (``parse_spec`` rejects it as
        ``bad_spec`` before admission; this is the dispatch-side
        defense)."""
        named = sorted({
            n.param("input", "corpus")
            for n in self.plan.nodes if n.kind == "source"
        } - {"corpus"})
        if named:
            raise PlanError(
                f"run_corpus feeds ONE corpus; this plan's sources name "
                f"distinct inputs {named} — submit it through run() "
                "with a data dict instead"
            )
        if any(n.kind == "source" and n.op == "edges"
               for n in self.plan.nodes):
            src, dst = edges_from_bytes(corpus)
            return self.run(
                (src, dst), max_nodes=SERVE_MAX_PAGERANK_NODES
            )
        if sub_cache is not None and corpus_sha is None:
            import hashlib

            corpus_sha = hashlib.sha256(corpus).hexdigest()
        return self.run(corpus.splitlines(), sub_cache=sub_cache,
                        corpus_sha=corpus_sha, corpus_bytes=corpus)

    def _wordcount_engine(self):
        if self._engine is None:
            from locust_tpu.engine import MapReduceEngine

            cfg = self.cfg
            if self.optimized is not None and self.optimized.fuse_kernel:
                # fuse_fold_kernel (plan/optimize.py): the wordcount
                # fold engages the Pallas megakernel — the engine's own
                # eligibility check stays the runtime authority and
                # degrades to plain hasht byte-identically off
                # supported shapes/backends.
                cfg = dataclasses.replace(cfg, sort_mode="fused")
            self._engine = MapReduceEngine(cfg)
        return self._engine


def compile_plan(plan: Plan, cfg=None, mesh: bool = False,
                 optimize: bool = True) -> CompiledPlan:
    """Lower ``plan`` onto the engine tier; raises ``PlanError`` on any
    composition outside the supported signatures (docs/PLAN.md).
    ``optimize=False`` skips the rewrite pass (plan/optimize.py) — the
    naive 1:1 lowering the optimizer's byte-identity contract is pinned
    against."""
    return CompiledPlan(plan, cfg=cfg, mesh=mesh, optimize=optimize)


class _RunCtx:
    """One plan execution: stage memo + source staging + accounting."""

    def __init__(self, cp: CompiledPlan, data, num_nodes, timed,
                 checkpoint_dir, every, finalize: bool = True,
                 max_nodes: int | None = None, sub_cache=None,
                 corpus_sha: str | None = None,
                 corpus_bytes: bytes | None = None):
        self.cp = cp
        self.data = data
        self.num_nodes = num_nodes
        self.max_nodes = max_nodes
        self.timed = timed
        self.checkpoint_dir = checkpoint_dir
        self.every = every
        self.finalize = finalize
        self.run_result = None
        self.sub_cache = sub_cache        # serve.cache.SubPlanCache
        self.corpus_sha = corpus_sha
        self.corpus_bytes = corpus_bytes
        self._memo: dict[str, object] = {}
        self._acct: dict[str, tuple] = {}  # stage id -> (dist, trunc, ovf)

    def _sub_engaged(self) -> bool:
        """Per-edge sub-result caching engages only on the serve path
        (run_corpus with a cache): plain host pairs in/out, no engine
        side effects — timed/checkpointed/unfinalized runs and mesh
        execution need the engine's own artifacts, so they stay naive."""
        return (
            self.sub_cache is not None
            and self.corpus_sha is not None
            and self.corpus_bytes is not None
            and self.finalize
            and not self.cp.mesh
            and not self.timed
            and not self.checkpoint_dir
        )

    # -------------------------------------------------------------- eval

    def eval(self, sid: str):
        if sid in self._memo:
            return self._memo[sid]
        stage = self.cp._stages[sid]
        kind = stage[0]
        if kind == "source":
            out = self._eval_source(stage[1])
        elif kind == "fold":
            out = self._eval_fold(sid, stage)
        elif kind == "score":
            out = self._eval_score(stage)
        elif kind == "join":
            out = self._eval_join(sid, stage)
        elif kind == "pagerank":
            out = self._eval_pagerank(sid, stage)
        else:  # pragma: no cover - render handled by run()
            raise PlanError(f"unexpected stage {kind!r}")
        self._memo[sid] = out
        return out

    def _source_data(self, n: Node):
        name = n.param("input", "corpus")
        data = self.data
        if isinstance(data, dict):
            if name not in data:
                raise PlanError(
                    f"source {n.id!r}: no input named {name!r} in the "
                    f"run data (have: {sorted(data)})"
                )
            data = data[name]
        if data is None:
            raise PlanError(f"source {n.id!r}: run() got no input data")
        return data

    def _eval_source(self, n: Node):
        import numpy as np

        data = self._source_data(n)
        if n.op == "edges":
            src, dst = data
            return np.asarray(src), np.asarray(dst)
        from locust_tpu.core import bytes_ops

        cfg = self.cp.cfg
        rows = (
            data
            if isinstance(data, np.ndarray)
            else bytes_ops.strings_to_rows(list(data), cfg.line_width)
        )
        k = n.param("lines_per_doc", 1)
        ids = (np.arange(rows.shape[0]) // k).astype(np.int32)
        return rows, ids

    def _eval_fold(self, sid: str, stage):
        """Fold-stage dispatch: sub-plan cache consult (exact hit ->
        skip even the source staging; verified append-only regrowth ->
        delta-only refold + merge) before the full fold.  Every path
        returns EXACTLY what the naive fold returns — cached values are
        the bytes a previous identical fold produced, and the
        incremental merge rides the mergeable-table property with
        bail-to-full guards wherever a full refold could differ
        (truncation, capacity) — docs/PLAN.md "Optimizer"."""
        if not self._sub_engaged():
            return self._eval_fold_full(sid, stage)
        sub = self.sub_cache
        key_fp = self.cp._lowered.node_fingerprint(sid)
        cfg_fp = self.cp.cfg.fingerprint()
        ent = sub.get(key_fp, cfg_fp, self.corpus_sha)
        if ent is not None:
            obs.metric_inc("plan.subcache_hits")
            return self._restore_fold_entry(sid, stage, ent)
        obs.metric_inc("plan.subcache_misses")
        ent = self._incremental_fold(sid, stage, sub, key_fp, cfg_fp)
        if ent is not None:
            return self._restore_fold_entry(sid, stage, ent)
        value = self._eval_fold_full(sid, stage)
        sub.put(key_fp, cfg_fp, self.corpus_sha,
                self._fold_entry(sid, stage, value))
        return value

    def _restore_fold_entry(self, sid: str, stage, ent: dict):
        fold = stage[1]
        self._acct[sid] = (
            int(ent["distinct"]), bool(ent["truncated"]),
            int(ent["overflow"]),
        )
        if fold == "tf":
            src_node = self.cp._stages[stage[2]][1]
            k = src_node.param("lines_per_doc", 1)
            n_lines = int(ent["n_lines"])
            # n_docs exactly as the full path derives it: distinct of
            # arange(n_lines) // k, i.e. ceil(n_lines / k), floor 1.
            self._memo[f"{sid}.n_docs"] = (
                -(-n_lines // k) if n_lines else 1
            )
        value = ent["value"]
        # Shallow copies out of the cache: entry values are shared
        # across runs and must never be mutated by a consumer.
        return list(value) if isinstance(value, list) else dict(value)

    def _fold_entry(self, sid: str, stage, value) -> dict:
        fold = stage[1]
        rows, _ids = self.eval(stage[2])
        dist, trunc, ovf = self._acct[sid]
        return {
            "fold": fold, "value": value,
            "distinct": int(dist), "truncated": bool(trunc),
            "overflow": int(ovf),
            "corpus_len": len(self.corpus_bytes),
            "corpus_sha": self.corpus_sha,
            "n_lines": int(rows.shape[0]),
            "bytes": _fold_value_bytes(fold, value),
        }

    def _incremental_fold(self, sid: str, stage, sub, key_fp, cfg_fp):
        """incremental_fold (plan/optimize.py): look for a cached entry
        over a hash-verified append-only PREFIX of this corpus, refold
        only the delta lines, merge.  Returns the merged entry (also
        stored under the new corpus sha, so future growth chains), or
        None -> full recompute."""
        fold = stage[1]
        if fold not in ("wordcount", "tf"):
            return None  # index postings: exact-hit reuse only
        for cand in sub.prefix_candidates(key_fp, cfg_fp):
            info = incremental_delta(cand, self.corpus_bytes)
            if info is None:
                continue
            merged = self._merge_delta(sid, stage, fold, cand, info)
            if merged is None:
                continue  # guard bailed: the full path owns this run
            sub.put(key_fp, cfg_fp, self.corpus_sha, merged)
            record_rewrite(info["rule"])
            return merged
        return None

    def _merge_delta(self, sid: str, stage, fold: str, ent: dict,
                     info: dict):
        cfg = self.cp.cfg
        rows, ids = self.eval(stage[2])
        n_old = int(info["old_n_lines"])
        n_total = int(rows.shape[0])
        if not 0 <= n_old < n_total:
            return None
        delta_rows = rows[n_old:]
        if fold == "wordcount":
            from locust_tpu.engine import merge_host_pairs

            eng = self.cp._wordcount_engine()
            res = eng.run(delta_rows)
            if res.truncated:
                return None
            pairs = merge_host_pairs(
                ent["value"], res.to_host_pairs(), combine=eng.combine
            )
            if len(pairs) > cfg.resolved_table_size:
                # A full refold would truncate, and only IT knows which
                # keys survive — bail to the naive path.
                return None
            dist = len(pairs)
            ovf = int(ent["overflow"]) + int(res.overflow_tokens)
            value = pairs
        else:  # tf
            from locust_tpu.apps.inverted_index import (
                default_pairs_capacity,
            )
            from locust_tpu.apps.tfidf import term_doc_counts
            from locust_tpu.engine import _wrap_i32

            try:
                tf_delta = term_doc_counts(delta_rows, ids[n_old:], cfg)
            except Exception:  # noqa: BLE001  # locust: noqa[R017] loss condition = documented bail to the naive recompute, which raises the canonical error for the full corpus — nothing is lost silently
                # The delta fold hit a loss condition (overflow /
                # capacity — term_doc_counts raises rather than
                # truncate).  Bail so the NAIVE path recomputes and
                # raises the canonical error for the full corpus.
                return None
            value = dict(ent["value"])
            for key, v in tf_delta.items():
                value[key] = _wrap_i32(int(value.get(key, 0)) + int(v))
            if len(value) > default_pairs_capacity(cfg):
                return None  # a full refold RAISES; let it
            dist, ovf = len(value), 0
        bl = cfg.block_lines
        sub = self.sub_cache
        sub.record_incremental(
            delta_blocks=-(-(n_total - n_old) // bl),
            total_blocks=max(1, -(-n_total // bl)),
        )
        return {
            "fold": fold, "value": value,
            "distinct": int(dist), "truncated": False,
            "overflow": int(ovf),
            "corpus_len": len(self.corpus_bytes),
            "corpus_sha": self.corpus_sha,
            "n_lines": n_total,
            "bytes": _fold_value_bytes(fold, value),
        }

    def _eval_fold_full(self, sid: str, stage):
        fold = stage[1]
        src_node = self.cp._stages[stage[2]][1]
        rows, ids = self.eval(stage[2])
        cfg, mesh = self.cp.cfg, self.cp.mesh
        if fold == "wordcount":
            if mesh:
                from locust_tpu.parallel.mesh import make_mesh
                from locust_tpu.parallel.shuffle import DistributedMapReduce

                opt = self.cp.optimized
                if opt is not None and opt.fuse_kernel:
                    # fuse_fold_kernel fires for mesh jobs too
                    # (megakernel v2): the mesh engine's own
                    # fused_mesh_eligible gate keeps runtime authority —
                    # off-TPU it demotes explicitly (fused_demoted) and
                    # folds exactly like hasht.
                    cfg = dataclasses.replace(cfg, sort_mode="fused")
                res = DistributedMapReduce(make_mesh(), cfg).run(rows)
                pairs = res.to_host_pairs() if self.finalize else None
                self._acct[sid] = (
                    res.distinct, res.truncated, res.emit_overflow
                )
            else:
                eng = self.cp._wordcount_engine()
                if self.checkpoint_dir:
                    res = eng.run_checkpointed(
                        rows, self.checkpoint_dir, every=self.every
                    )
                elif self.timed:
                    res = eng.timed_run(rows)
                else:
                    res = eng.run_fused(rows)
                self.run_result = res
                pairs = res.to_host_pairs() if self.finalize else None
                self._acct[sid] = (
                    res.num_segments, res.truncated, res.overflow_tokens
                )
            return pairs
        if fold == "tf":
            from locust_tpu.apps.tfidf import term_doc_counts

            tf = term_doc_counts(rows, ids, cfg)
            self._acct[sid] = (len(tf), False, 0)
            # The score stage needs n_docs exactly as build_tfidf
            # derives it: distinct ids over the INPUT, not the table
            # (a doc whose lines carry no tokens still counts).
            self._memo[f"{sid}.n_docs"] = (
                len(set(int(d) for d in ids)) or 1
            )
            return tf
        if fold == "index":
            if mesh:
                from locust_tpu.apps.inverted_index import (
                    build_inverted_index_mesh,
                )
                from locust_tpu.parallel.mesh import make_mesh

                index = build_inverted_index_mesh(
                    rows, ids, make_mesh(), cfg
                )
            else:
                from locust_tpu.apps.inverted_index import (
                    build_inverted_index,
                )

                index = build_inverted_index(rows, ids, cfg)
            self._acct[sid] = (len(index), False, 0)
            return index
        raise PlanError(  # pragma: no cover - _FOLDS is closed
            f"unknown fold {fold!r} (source {src_node.id!r})"
        )

    def _eval_score(self, stage):
        from locust_tpu.apps.tfidf import scores_from_tf

        tf_id = stage[1]
        composed = len(stage) > 2 and stage[2]
        if composed and tf_id not in self._memo:
            # compose_score (plan/optimize.py): fold + rescore as ONE
            # stage — the tf table is consumed inline and never
            # retained in the stage memo (the reduce has exactly one
            # consumer, so nothing else can ask for it).
            tf = self._eval_fold(tf_id, self.cp._stages[tf_id])
        else:
            tf = self.eval(tf_id)
        return scores_from_tf(tf, self._memo[f"{tf_id}.n_docs"])

    def _eval_join(self, sid: str, stage):
        _, left_id, right_id, combine = stage
        left = dict(self.eval(left_id))
        right = dict(self.eval(right_id))
        op = {
            "sum": lambda a, b: a + b,
            "mul": lambda a, b: a * b,
            "min": min,
        }[combine]
        pairs = sorted(
            (k, op(v, right[k])) for k, v in left.items() if k in right
        )
        self._acct[sid] = (len(pairs), False, 0)
        return pairs

    def _eval_pagerank(self, sid: str, stage):
        import numpy as np

        _, src_id, num_iters, damping = stage
        src, dst = self.eval(src_id)
        n = (
            self.num_nodes
            if self.num_nodes is not None
            else int(max(int(src.max()), int(dst.max()))) + 1
        )
        if self.max_nodes is not None and n > self.max_nodes:
            # Serve-side bound (SERVE_MAX_PAGERANK_NODES): the node
            # count derives from corpus CONTENT, so a tiny submit
            # naming a huge id must reject, not allocate.
            raise PlanError(
                f"pagerank needs {n} dense node slots, past this "
                f"endpoint's cap ({self.max_nodes}); renumber the "
                "graph or run it through the CLI"
            )
        if self.cp.mesh:
            from locust_tpu.apps.pagerank import ShardedPageRank
            from locust_tpu.parallel.mesh import make_mesh

            ranks = ShardedPageRank(make_mesh(), n, damping=damping).run(
                src, dst, num_iters=num_iters
            )
        else:
            from locust_tpu.apps.pagerank import pagerank

            ranks = np.asarray(pagerank(
                np.asarray(src, np.int32), np.asarray(dst, np.int32),
                num_nodes=n, num_iters=num_iters, damping=damping,
            ))
        self._acct[sid] = (n, False, 0)
        return ranks

    def accounting(self, sid: str, value) -> tuple:
        got = self._acct.get(sid)
        if got is not None:
            return got
        try:
            return len(value), False, 0
        except TypeError:
            return 0, False, 0


def _fold_value_bytes(fold: str, value) -> int:
    """Byte-size estimate of one cached fold value (the sub-plan
    cache's LRU accounting — the ``pairs_bytes`` stance: an estimate
    that tracks growth, not an exact RSS)."""
    if fold == "wordcount":
        return sum(len(k) + 8 for k, _v in value)
    if fold == "tf":
        return sum(len(w) + 16 for (w, _d) in value)
    return sum(len(w) + 8 * len(docs) for w, docs in value.items())


def rank_row(node: int, rank: float) -> bytes:
    """ONE spelling of a pagerank output row — the ``ranks`` sink and
    the driver's ``--top`` path (which reorders rows) both use it, so
    the formats cannot drift apart."""
    return f"{node}\t{rank:.8f}\n".encode()


def iter_rendered(op: str, value):
    """Per-row sink rendering, the ONE spelling of each workload's
    output format: ``_render`` joins it for plan results, and the
    hand-wired CLI drivers (``cli_apps``) iterate it directly (honoring
    ``--limit``) — byte-identity holds by construction, not by parallel
    maintenance."""
    if op == "table":
        for k, v in value:  # pairs are already host-finalized + sorted
            yield k + b"\t" + str(v).encode() + b"\n"
    elif op == "tfidf":
        for word, doc in sorted(value):
            yield (
                word + b"\t" + str(doc).encode()
                + b"\t" + f"{value[(word, doc)]:.6f}".encode() + b"\n"
            )
    elif op == "postings":
        for word in sorted(value):
            docs = b",".join(str(d).encode() for d in value[word])
            yield word + b"\t" + docs + b"\n"
    elif op == "ranks":
        for i in range(value.shape[0]):
            yield rank_row(i, value[i])
    else:  # pragma: no cover - NODE_OPS closes the sink set
        raise PlanError(f"unknown sink op {op!r}")


def _render(op: str, value) -> bytes:
    """Sink rendering: byte-for-byte the hand-wired drivers' stdout —
    the byte-identity contract serve plan results ride."""
    return b"".join(iter_rendered(op, value))


def edges_from_bytes(corpus: bytes):
    """SNAP-style ``src dst`` edge list from raw bytes.  The ONE parser
    (comment/2-field/int/negative-id rules): ``cli_apps.load_edges``
    delegates here, so a pagerank plan submitted to the daemon parses
    its corpus exactly like the CLI parses a file — by construction,
    not by parallel maintenance."""
    import numpy as np

    src, dst = [], []
    for ln_no, ln in enumerate(corpus.splitlines(), 1):
        ln = ln.strip()
        if not ln or ln.startswith(b"#"):
            continue
        parts = ln.split()
        if len(parts) != 2:
            raise PlanError(
                f"edge list line {ln_no}: expected 'src dst', got "
                f"{ln[:60]!r}"
            )
        try:
            src.append(int(parts[0]))
            dst.append(int(parts[1]))
        except ValueError:
            raise PlanError(
                f"edge list line {ln_no}: non-integer node id {ln[:60]!r}"
            )
    if not src:
        raise PlanError("edge list has no edges")
    s = np.asarray(src, np.int64)
    d = np.asarray(dst, np.int64)
    if s.min() < 0 or d.min() < 0:
        raise PlanError("edge list has a negative node id")
    return s, d
