"""Plan optimizer — a rewrite pass between validation and compilation.

``optimize(plan, cfg, mesh)`` runs over a VALIDATED plan and returns an
``Optimized`` bundle: a (possibly rewritten) plan that is still a valid
``Plan`` plus annotations the compiler consumes.  The hard contract is
byte-identity: every rewrite must leave the sink-rendered output
byte-for-byte what the naive lowering produces, across the whole ladder
(single-device, mesh, stream, crash-resume, distributed) — the rules
below only ever (a) rename work onto an implementation that is already
pinned bit-identical, (b) deduplicate work whose results are equal by
content-addressed construction, or (c) reuse results whose inputs are
verified by hash to be a prefix of the new input.  A plan no rule
matches passes through EXACTLY — same object, same fingerprint.

The rule registry is CLOSED and two-sided (the NODE_KINDS /
ERROR_CODES mold, enforced by analysis rule R015): every rule id is an
entry in ``REWRITE_RULES``, every entry is applied somewhere in this
module, exercised under ``tests/`` and documented in docs/PLAN.md
"Optimizer".  Rewrites are recorded through ``record_rewrite`` with a
LITERAL rule id — a typo'd rule fails loudly at the firing site.

jax-free at import (the plan package contract): the serve control plane
optimizes plans without a backend, and static eligibility here never
probes the device — the ENGINE keeps runtime authority (an ineligible
fused fold degrades inside the engine, byte-identically).

FlumeJava-style deferred fusion + Nectar-style sub-computation caching,
specialized to the closed plan vocabulary (docs/PLAN.md).
"""

from __future__ import annotations

import dataclasses
import hashlib

from locust_tpu import obs
from locust_tpu.plan.nodes import Plan, PlanError

# The closed rewrite-rule registry (analysis rule R015 keeps it
# two-sided: registered <-> applied/exercised/documented).
REWRITE_RULES = (
    "fuse_fold_kernel",   # wordcount fold spine -> sort_mode="fused"
    "compose_score",      # single-consumer reduce+tfidf_score: one stage
    "cse_subplan",        # duplicate upstream closures -> one node
    "incremental_fold",   # verified append-only regrowth -> delta refold
)


def record_rewrite(rule: str) -> None:
    """Count one applied rewrite; the rule id must be registered (the
    runtime half of R015 — a typo'd id fails at the firing site, not in
    a dashboard nobody reads)."""
    if rule not in REWRITE_RULES:
        raise PlanError(
            f"rewrite rule {rule!r} is not in REWRITE_RULES "
            "(locust_tpu/plan/optimize.py) — register it"
        )
    obs.metric_inc("plan.rewrites")


@dataclasses.dataclass(frozen=True)
class Optimized:
    """One optimizer pass result: the plan to LOWER (identity for
    caches stays the ORIGINAL plan — ``CompiledPlan`` keeps both) plus
    the annotations the compiler consumes."""

    plan: Plan
    applied: tuple = ()            # rule ids fired, in order
    fuse_kernel: bool = False      # wordcount folds build the fused engine
    composed_scores: frozenset = frozenset()  # score node ids folded inline


def optimize(plan: Plan, cfg=None, mesh: bool = False) -> Optimized:
    """Run every static rewrite over ``plan``.  Identity when nothing
    fires: the SAME ``Plan`` object comes back (same fingerprint), so a
    no-op optimization can never perturb cache keys or WAL replay."""
    with obs.span("plan.optimize", plan=plan.fingerprint()):
        applied: list = []
        plan = _cse_subplan(plan, applied)
        composed = _compose_score(plan, applied)
        fuse = _fuse_fold_kernel(plan, cfg, mesh, applied)
        return Optimized(
            plan=plan, applied=tuple(applied),
            fuse_kernel=fuse, composed_scores=composed,
        )


# ------------------------------------------------------------- rule (a)


def _fuse_fold_kernel(plan: Plan, cfg, mesh: bool, applied: list) -> bool:
    """Fusion onto the PR 13 megakernel: a ``map(tokenize_count) →
    shuffle(by_key) → reduce(sum)`` spine under ``sort_mode="hasht"``
    lowers its wordcount fold with ``sort_mode="fused"`` instead — the
    whole map→aggregate chain in ONE VMEM-resident kernel.  Safe by the
    pinned family identity (``HASHT_FAMILY`` tables are BIT-identical)
    and because the engine's own eligibility check stays the runtime
    authority: off supported shapes/backends it degrades to plain hasht,
    byte-identically.  Static only — this module never probes a backend
    (the jax-free contract).  Fires for mesh and streaming jobs too
    (megakernel v2): the mesh engines gate through
    ``fused_mesh_eligible`` at construction and demote EXPLICITLY
    (``fused_demoted``) when the kernel can't engage, and ``run_stream``
    under ``sort_mode="fused"`` takes the persistent streaming
    formulation — both still bit-identical to hasht."""
    if cfg is None or getattr(cfg, "sort_mode", None) != "hasht":
        return False
    by_id = plan.by_id()
    for n in plan.nodes:
        if n.kind != "reduce" or n.op != "sum":
            continue
        shuf = by_id[n.inputs[0]]
        if shuf.kind != "shuffle":
            continue
        mapper = by_id[shuf.inputs[0]]
        if mapper.kind == "map" and mapper.op == "tokenize_count":
            record_rewrite("fuse_fold_kernel")
            applied.append("fuse_fold_kernel")
            return True
    return False


# ------------------------------------------------------------- rule (b)


def _compose_score(plan: Plan, applied: list) -> frozenset:
    """Adjacent-map composition, tfidf spine: a ``map(tfidf_score)``
    whose input reduce has EXACTLY one consumer evaluates fold+rescore
    as one stage — the intermediate tf table is consumed inline and
    never retained in the stage memo (one dispatch, no materialized
    intermediate).  Annotation-only: the plan is unchanged, so the
    rendered bytes are trivially identical."""
    by_id = plan.by_id()
    consumers: dict = {}
    for n in plan.nodes:
        for ref in n.inputs:
            consumers[ref] = consumers.get(ref, 0) + 1
    composed = set()
    for n in plan.nodes:
        if n.kind == "map" and n.op == "tfidf_score":
            feed = by_id[n.inputs[0]]
            if feed.kind == "reduce" and consumers.get(feed.id) == 1:
                composed.add(n.id)
    if composed:
        record_rewrite("compose_score")
        applied.append("compose_score")
    return frozenset(composed)


def _cse_subplan(plan: Plan, applied: list) -> Plan:
    """Common-subplan elimination WITHIN a plan: nodes whose upstream
    closures share a content-addressed fingerprint
    (``Plan.node_fingerprint``) collapse onto the first in topo order,
    and every consumer re-points at the survivor — so a join of two
    identical chains folds the chain ONCE.  The rewritten node set goes
    back through full ``Plan`` validation (type-check, arity, topo,
    reachability); results are equal by content-addressed construction,
    so the sink bytes cannot change."""
    by_id = plan.by_id()
    keeper: dict = {}   # closure fp -> surviving node id
    remap: dict = {}    # dropped node id -> surviving node id
    for nid in plan.topo_order():
        if by_id[nid].kind == "sink":
            continue
        fp = plan.node_fingerprint(nid)
        if fp in keeper:
            remap[nid] = keeper[fp]
        else:
            keeper[fp] = nid
    if not remap:
        return plan
    record_rewrite("cse_subplan")
    applied.append("cse_subplan")
    survivors = []
    for n in plan.nodes:
        if n.id in remap:
            continue
        if any(ref in remap for ref in n.inputs):
            n = dataclasses.replace(
                n, inputs=tuple(remap.get(ref, ref) for ref in n.inputs)
            )
        survivors.append(n)
    return Plan(tuple(survivors), version=plan.version)


# ------------------------------------------------------------- rule (c)


def incremental_delta(entry: dict, corpus: bytes) -> dict | None:
    """Append-only regrowth check for one cached fold entry
    (``serve.cache.SubPlanCache``): the new ``corpus`` qualifies for an
    incremental delta refold iff the entry's corpus is a VERIFIED
    prefix — the sha256 is recomputed over ``corpus[:old_len]`` right
    here, server-side, never trusted from the client — that ends on a
    line boundary (otherwise the delta's first bytes would merge into
    the prefix's last line and re-tokenize it; a ``\\r\\n`` split
    across the cut is the same hazard), and the cached table is exact
    (a truncated table dropped keys nobody can re-derive).  Returns
    ``{"rule": "incremental_fold", "old_len", "old_n_lines"}`` on a
    match — the caller (``plan/compile._RunCtx``) folds ONLY the delta
    lines and merges via the mergeable-table property
    (``engine.merge_host_pairs``), recording the rewrite on success."""
    old_len = int(entry.get("corpus_len") or 0)
    old_sha = entry.get("corpus_sha") or ""
    if not (0 < old_len < len(corpus)):
        return None
    if entry.get("truncated"):
        return None
    if corpus[old_len - 1:old_len] != b"\n":
        return None
    if hashlib.sha256(corpus[:old_len]).hexdigest() != old_sha:
        return None
    return {
        "rule": "incremental_fold",
        "old_len": old_len,
        "old_n_lines": int(entry.get("n_lines") or 0),
    }
