"""locust_tpu.plan — composable dataflow plans over the engine.

A typed logical DAG (``nodes.py``) with JSON round-tripping and a
content-addressed fingerprint, canonical workload builders
(``builders.py``), and a compiler (``compile.py``) that lowers validated
plans onto the existing engine/mesh primitives — docs/PLAN.md.

jax-free at import (the serve control plane validates and fingerprints
plans before — or without — a backend); ``compile_plan`` resolves
lazily, and jax enters only when a compiled plan actually runs.
"""

from locust_tpu.plan.builders import (  # noqa: F401
    index_plan,
    pagerank_plan,
    tfidf_plan,
    wordcount_plan,
)
from locust_tpu.plan.nodes import (  # noqa: F401
    NODE_KINDS,
    NODE_OPS,
    PLAN_VERSION,
    Node,
    Plan,
    PlanError,
    from_doc,
    from_json,
    node,
)
from locust_tpu.plan.optimize import (  # noqa: F401
    REWRITE_RULES,
    Optimized,
    optimize,
)

_LAZY = ("compile_plan", "CompiledPlan", "PlanResult")


def __getattr__(name: str):
    # PEP 562 lazy re-export (the distributor/__init__ pattern): keeps
    # this package importable without numpy/engine modules loaded.
    if name in _LAZY:
        from locust_tpu.plan import compile as _compile

        return getattr(_compile, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
