"""Canonical plans for the workload ladder.

The ONE spelling of each workload as a logical DAG — the CLI drivers
(``cli_apps.py``, ``cli.py``), the serve smoke/tests and the bench
``plan`` sub-dict all construct these instead of re-wiring stage chains
by hand, so "the wordcount pipeline" has exactly one definition whose
``fingerprint()`` everything keys off (docs/PLAN.md).  jax-free.
"""

from __future__ import annotations

from locust_tpu.plan.nodes import Plan, node


def wordcount_plan() -> Plan:
    """source → tokenize → group → sum → table: the reference pipeline
    (main.cu:397-473) as a plan.  Compiles onto the engine's fused
    one-sort-per-block fold (plan/compile.py)."""
    return Plan((
        node("corpus", "source", "text"),
        node("tokenize", "map", "tokenize_count", ("corpus",)),
        node("group", "shuffle", "by_key", ("tokenize",)),
        node("counts", "reduce", "sum", ("group",)),
        node("out", "sink", "table", ("counts",)),
    ))


def tfidf_plan(lines_per_doc: int = 1) -> Plan:
    """The two-stage tf-idf pipeline: a (word, doc)-keyed count fold,
    then a table-level rescore — tf from the device, df/n_docs as host
    folds over the (tiny) pair table (apps/tfidf.py)."""
    return Plan((
        node("corpus", "source", "text", lines_per_doc=lines_per_doc),
        node("pairs", "map", "tokenize_pairs", ("corpus",)),
        node("group", "shuffle", "by_key", ("pairs",)),
        node("tf", "reduce", "sum", ("group",)),
        node("score", "map", "tfidf_score", ("tf",)),
        node("out", "sink", "tfidf", ("score",)),
    ))


def index_plan(lines_per_doc: int = 1) -> Plan:
    """Inverted index: (word, doc) pairs grouped by word, reduced to the
    distinct sorted posting list (apps/inverted_index.py)."""
    return Plan((
        node("corpus", "source", "text", lines_per_doc=lines_per_doc),
        node("pairs", "map", "tokenize_pairs", ("corpus",)),
        node("group", "shuffle", "by_key", ("pairs",)),
        node("postings", "reduce", "collect_docs", ("group",)),
        node("out", "sink", "postings", ("postings",)),
    ))


def pagerank_plan(num_iters: int = 20, damping: float = 0.85) -> Plan:
    """Iterative PageRank over an edge list: the iterate node wraps the
    damped power iteration the apps tier already lowers to a dense
    segment-sum + psum (apps/pagerank.py)."""
    return Plan((
        node("edges", "source", "edges"),
        node("ranks", "iterate", "pagerank", ("edges",),
             num_iters=num_iters, damping=damping),
        node("out", "sink", "ranks", ("ranks",)),
    ))
