"""Typed logical dataflow plans: the DAG layer over the engine.

The reference's whole pipeline is ONE hand-wired Map→Process→Reduce
sequence (reference MapReduce/src/main.cu:397-473) and until this layer
our reproduction mirrored it: pagerank/index/tfidf each hard-coded their
own stage chains.  A *plan* is the FlumeJava/Spark lesson applied to
that engine — a small, deferred, fingerprintable DAG of typed logical
nodes that ``plan/compile.py`` lowers onto the EXISTING engine and mesh
primitives (docs/PLAN.md).  The payoff is identity, not execution: a
``Plan`` is pure data with a content-addressed ``fingerprint()`` in the
same sha-of-canonical-repr mold as ``EngineConfig.fingerprint()``, so
the serve tier's warm-executable cache, result cache and write-ahead
journal can key and replay arbitrary pipelines instead of only named
workloads (docs/SERVING.md "Plan submits").

Closed registries (the ``faultplan.SITES`` / obs ``NAMES`` stance,
enforced three-sided by analysis rule R014 — registered, lowered +
tested + documented, and distribute-covered or SOLO_ONLY-exempt):

  * ``NODE_KINDS`` — the node kinds a plan may use; an unknown kind is a
    loud ``PlanError`` at construction, never a silently-ignored node;
  * ``NODE_OPS`` — the operations each kind admits;
  * ``_SIGNATURES`` — the dataflow TYPE each (kind, op) consumes and
    produces; validation type-checks the whole DAG in topological order,
    so a plan that wires a token stream into a ranks sink fails at
    submit time, not at dispatch.

jax-free at import (like the rest of the serve control plane): the thin
client validates and fingerprints plans without paying a jax init, which
can hang on a wedged axon tunnel (CLAUDE.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re

PLAN_VERSION = 1

# The closed node-kind registry.  Analysis rule R014 polices it from
# three sides: every kind literal constructed/matched under locust_tpu/
# must be an entry here; every entry must be lowered in plan/compile.py,
# exercised under tests/, and documented in docs/PLAN.md; and every
# entry must be matched by the distributed planner in plan/distribute.py
# OR registered in its SOLO_ONLY tuple — so a new kind cannot silently
# fall off the distributed surface (stale/unknown SOLO_ONLY entries are
# findings too).
NODE_KINDS = (
    "source",   # ingest: corpus text or an edge list
    "map",      # per-record transform / emit (or a table-level rescore)
    "shuffle",  # group records by key (the Process-stage sort)
    "reduce",   # combine each group into one row
    "join",     # inner-join two tables on key
    "iterate",  # a fixed-point loop over a static structure
    "sink",     # render the terminal table to output bytes
)

# Operations per kind — the second closed tier under the kind registry.
NODE_OPS = {
    "source": ("text", "edges"),
    "map": ("tokenize_count", "tokenize_pairs", "tfidf_score"),
    "shuffle": ("by_key",),
    "reduce": ("sum", "collect_docs"),
    "join": ("inner",),
    "iterate": ("pagerank",),
    "sink": ("table", "tfidf", "postings", "ranks"),
}

# Dataflow typing: (kind, op) -> [(input types, output type), ...].
# Polymorphic ops (shuffle/reduce over word emits vs (word, doc) pair
# emits) list one signature per accepted input row type; validation
# picks the matching one in topological order.
_SIGNATURES = {
    ("source", "text"): (((), "rows"),),
    ("source", "edges"): (((), "edges"),),
    ("map", "tokenize_count"): ((("rows",), "emits"),),
    ("map", "tokenize_pairs"): ((("rows",), "pair_emits"),),
    ("map", "tfidf_score"): ((("pair_table",), "scores"),),
    ("shuffle", "by_key"): (
        (("emits",), "grouped"),
        (("pair_emits",), "grouped_pairs"),
    ),
    ("reduce", "sum"): (
        (("grouped",), "table"),
        (("grouped_pairs",), "pair_table"),
    ),
    ("reduce", "collect_docs"): ((("grouped_pairs",), "postings"),),
    ("join", "inner"): ((("table", "table"), "table"),),
    ("iterate", "pagerank"): ((("edges",), "ranks"),),
    ("sink", "table"): ((("table",), "output"),),
    ("sink", "tfidf"): ((("scores",), "output"),),
    ("sink", "postings"): ((("postings",), "output"),),
    ("sink", "ranks"): ((("ranks",), "output"),),
}

# Per-(kind, op) parameter schema: name -> validator returning the
# normalized value or raising ValueError.  A key outside the schema is a
# loud PlanError (the SPEC_CONFIG_KEYS stance: typos never silently
# no-op).  Every value must be a JSON scalar so plans round-trip.
JOIN_COMBINES = ("sum", "mul", "min")


def _pos_int(v):
    if isinstance(v, bool) or not isinstance(v, int) or v < 1:
        raise ValueError(f"must be an integer >= 1, got {v!r}")
    return v


# Iteration budget cap: a plan is multi-tenant input on the serve tier,
# and an unbounded num_iters would hold the daemon's one engine lock for
# hours on a validated submit.  Far above any convergent power-iteration
# use (the reference default is 20).
MAX_ITERS = 10_000


def _iters(v):
    v = _pos_int(v)
    if v > MAX_ITERS:
        raise ValueError(f"must be <= {MAX_ITERS}, got {v}")
    return v


def _damping(v):
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        raise ValueError(f"must be a number, got {v!r}")
    v = float(v)
    if not 0.0 < v < 1.0:
        raise ValueError(f"must be in (0, 1), got {v}")
    return v


def _input_name(v):
    if not isinstance(v, str) or not _ID_RE.match(v):
        raise ValueError(f"must be a short identifier, got {v!r}")
    return v


def _join_combine(v):
    if v not in JOIN_COMBINES:
        raise ValueError(f"must be one of {JOIN_COMBINES}, got {v!r}")
    return v


_PARAM_SCHEMA = {
    ("source", "text"): {"lines_per_doc": _pos_int, "input": _input_name},
    ("source", "edges"): {"input": _input_name},
    ("join", "inner"): {"combine": _join_combine},
    ("iterate", "pagerank"): {"num_iters": _iters, "damping": _damping},
}

_ID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")

# Arity per kind (join is the one two-input node).
_ARITY = {
    "source": 0, "map": 1, "shuffle": 1, "reduce": 1, "join": 2,
    "iterate": 1, "sink": 1,
}


class PlanError(ValueError):
    """Structured plan validation failure.  ``parse_spec`` maps it onto
    the serve tier's ``bad_spec`` reason code (docs/SERVING.md)."""


@dataclasses.dataclass(frozen=True)
class Node:
    """One typed plan node.  ``params`` is a sorted key/value tuple so
    the dataclass stays frozen + hashable; build through ``node()``."""

    id: str
    kind: str
    op: str
    inputs: tuple = ()
    params: tuple = ()

    def param(self, name: str, default=None):
        for k, v in self.params:
            if k == name:
                return v
        return default


def node(node_id: str, kind: str, op: str, inputs=(), **params) -> Node:
    """Node constructor: the canonical spelling R014 recognizes — the
    kind is always a literal second argument here (or a ``kind=``
    keyword), never a runtime-built string."""
    return Node(
        id=str(node_id), kind=kind, op=op,
        inputs=tuple(str(i) for i in inputs),
        params=tuple(sorted(params.items())),
    )


@dataclasses.dataclass(frozen=True)
class Plan:
    """A validated logical dataflow DAG.

    Validation runs in ``__post_init__`` (the ``EngineConfig`` stance):
    every ``Plan`` instance is structurally valid by construction —
    unique ids, registered kinds/ops, arity, acyclicity, full dataflow
    type-check, exactly one sink, no orphan nodes.  ``fingerprint()`` is
    content-addressed over the canonical JSON, so "same plan" is ONE
    well-defined predicate shared by the warm-executable cache, the
    result cache and journal replay.
    """

    nodes: tuple = ()
    version: int = PLAN_VERSION

    def __post_init__(self):
        object.__setattr__(self, "nodes", tuple(self.nodes))
        _validate(self)

    # ------------------------------------------------------------ identity

    def to_doc(self) -> dict:
        return {
            "plan_version": self.version,
            "nodes": [
                {
                    "id": n.id, "kind": n.kind, "op": n.op,
                    "inputs": list(n.inputs),
                    "params": dict(n.params),
                }
                for n in self.nodes
            ],
        }

    def canonical_json(self) -> str:
        """The ONE serialized spelling: sorted keys, no whitespace.
        ``fingerprint()`` hashes exactly this text, and the serve tier
        stores exactly this text in ``JobSpec.plan`` and the journal —
        so 'same plan' can never depend on dict ordering."""
        return json.dumps(self.to_doc(), sort_keys=True,
                          separators=(",", ":"))

    def fingerprint(self) -> str:
        """sha1 of the canonical JSON, truncated like
        ``EngineConfig.fingerprint()`` — the plan half of the serve
        tier's executable identity.  Memoized: the scheduler keys
        pending jobs by it every poll tick."""
        fp = self.__dict__.get("_fingerprint")
        if fp is None:
            fp = hashlib.sha1(
                self.canonical_json().encode()
            ).hexdigest()[:12]
            object.__setattr__(self, "_fingerprint", fp)
        return fp

    def node_fingerprint(self, nid: str) -> str:
        """Content-addressed fingerprint of ``nid``'s upstream CLOSURE —
        a Merkle hash over (kind, op, params, input closure fps), so it
        is independent of node IDS and of unrelated siblings: two
        tenants' plans that spell the same tokenize→fold prefix under
        different names share the fingerprint (alpha-equivalence).  The
        optimizer's CSE rewrite and the serve tier's sub-plan result
        cache key on exactly this identity (docs/PLAN.md "Optimizer").
        Memoized like ``fingerprint()`` — one topo sweep per plan."""
        fps = self.__dict__.get("_node_fps")
        if fps is None:
            fps = {}
            by_id = self.by_id()
            for oid in self.topo_order():
                n = by_id[oid]
                payload = json.dumps(
                    [n.kind, n.op, list(n.params),
                     [fps[ref] for ref in n.inputs]],
                    sort_keys=True, separators=(",", ":"),
                )
                fps[oid] = hashlib.sha1(
                    payload.encode()
                ).hexdigest()[:12]
            object.__setattr__(self, "_node_fps", fps)
        if nid not in fps:
            raise PlanError(f"no node {nid!r} in this plan")
        return fps[nid]

    # ---------------------------------------------------------- structure

    def by_id(self) -> dict:
        return {n.id: n for n in self.nodes}

    def sink(self) -> Node:
        return next(n for n in self.nodes if n.kind == "sink")

    def topo_order(self) -> tuple:
        """Node ids in a deterministic topological order (validation
        proved one exists)."""
        return self.__dict__["_topo"]

    def node_types(self) -> dict:
        """{node id: inferred dataflow type} from validation."""
        return dict(self.__dict__["_types"])


def from_doc(doc) -> Plan:
    """Parse + validate a plan document (the JSON dict shape
    ``to_doc()`` emits).  Every malformation is a ``PlanError`` whose
    message is safe to relay to a client."""
    if not isinstance(doc, dict):
        raise PlanError(f"plan must be a JSON object, got {type(doc).__name__}")
    version = doc.get("plan_version")
    if version != PLAN_VERSION:
        raise PlanError(
            f"unsupported plan_version {version!r} (this build speaks "
            f"{PLAN_VERSION})"
        )
    raw_nodes = doc.get("nodes")
    if not isinstance(raw_nodes, list) or not raw_nodes:
        raise PlanError("plan needs a non-empty 'nodes' list")
    nodes = []
    for i, rn in enumerate(raw_nodes):
        if not isinstance(rn, dict):
            raise PlanError(f"nodes[{i}] must be an object")
        unknown = set(rn) - {"id", "kind", "op", "inputs", "params"}
        if unknown:
            raise PlanError(f"nodes[{i}] has unknown keys {sorted(unknown)}")
        inputs = rn.get("inputs", [])
        if not isinstance(inputs, list):
            raise PlanError(f"nodes[{i}].inputs must be a list")
        params = rn.get("params", {})
        if not isinstance(params, dict):
            raise PlanError(f"nodes[{i}].params must be an object")
        # Param keys collide with node()'s own arguments ("kind", "op",
        # ...) as a raw TypeError through **params — every malformation
        # must surface as a structured PlanError (the serve bad_spec
        # contract), so screen them here; real schema validation still
        # happens in _validate.
        bad = [k for k in params if not isinstance(k, str)
               or k in ("node_id", "kind", "op", "inputs")]
        if bad:
            raise PlanError(
                f"nodes[{i}].params has reserved/invalid keys {bad}"
            )
        nodes.append(node(
            str(rn.get("id", "")), str(rn.get("kind", "")),
            str(rn.get("op", "")), inputs, **params,
        ))
    return Plan(tuple(nodes))


def from_json(text: str) -> Plan:
    try:
        doc = json.loads(text)
    except (TypeError, ValueError) as e:
        raise PlanError(f"plan JSON does not parse: {e}")
    return from_doc(doc)


# ------------------------------------------------------------- validation


def _validate(plan: Plan) -> None:
    nodes = plan.nodes
    if plan.version != PLAN_VERSION:
        raise PlanError(
            f"unsupported plan_version {plan.version!r} (this build "
            f"speaks {PLAN_VERSION})"
        )
    if not nodes:
        raise PlanError("plan needs at least one node")
    seen: dict[str, Node] = {}
    for n in nodes:
        if not isinstance(n, Node):
            raise PlanError(f"plan nodes must be Node instances, got {n!r}")
        if not _ID_RE.match(n.id):
            raise PlanError(f"node id {n.id!r} is not a short identifier")
        if n.id in seen:
            raise PlanError(f"duplicate node id {n.id!r}")
        if n.kind not in NODE_KINDS:
            raise PlanError(
                f"node {n.id!r}: unknown kind {n.kind!r} "
                f"(kinds: {NODE_KINDS})"
            )
        if n.op not in NODE_OPS[n.kind]:
            raise PlanError(
                f"node {n.id!r}: unknown op {n.op!r} for kind {n.kind!r} "
                f"(ops: {NODE_OPS[n.kind]})"
            )
        if len(n.inputs) != _ARITY[n.kind]:
            raise PlanError(
                f"node {n.id!r}: kind {n.kind!r} takes {_ARITY[n.kind]} "
                f"input(s), got {len(n.inputs)}"
            )
        schema = _PARAM_SCHEMA.get((n.kind, n.op), {})
        for k, v in n.params:
            if k not in schema:
                raise PlanError(
                    f"node {n.id!r}: unknown param {k!r} for "
                    f"({n.kind}, {n.op}) (allowed: {sorted(schema) or 'none'})"
                )
            try:
                schema[k](v)
            except ValueError as e:
                raise PlanError(f"node {n.id!r}: param {k!r} {e}")
        seen[n.id] = n
    for n in nodes:
        for ref in n.inputs:
            if ref not in seen:
                raise PlanError(
                    f"node {n.id!r}: input {ref!r} names no node"
                )
            if ref == n.id:
                raise PlanError(f"node {n.id!r}: self-referential input")

    # Kahn topological order — a leftover node means a cycle.
    indeg = {n.id: len(n.inputs) for n in nodes}
    consumers: dict[str, list[str]] = {n.id: [] for n in nodes}
    for n in nodes:
        for ref in n.inputs:
            consumers[ref].append(n.id)
    ready = sorted(nid for nid, d in indeg.items() if d == 0)
    topo: list[str] = []
    while ready:
        nid = ready.pop(0)
        topo.append(nid)
        for c in consumers[nid]:
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
        ready.sort()
    if len(topo) != len(nodes):
        cyc = sorted(nid for nid, d in indeg.items() if d > 0)
        raise PlanError(f"plan has a cycle through {cyc}")

    # Dataflow type-check in topo order (the "typed" in typed plans).
    types: dict[str, str] = {}
    for nid in topo:
        n = seen[nid]
        in_types = tuple(types[ref] for ref in n.inputs)
        for want, out in _SIGNATURES[(n.kind, n.op)]:
            if in_types == want:
                types[nid] = out
                break
        else:
            raise PlanError(
                f"node {n.id!r}: ({n.kind}, {n.op}) cannot consume "
                f"{in_types} (accepts: "
                f"{[w for w, _ in _SIGNATURES[(n.kind, n.op)]]})"
            )

    sinks = [n for n in nodes if n.kind == "sink"]
    if len(sinks) != 1:
        raise PlanError(f"plan needs exactly one sink node, got {len(sinks)}")

    # Reachability: every node must feed the sink (an orphan subgraph
    # would silently compute nothing — loud instead).
    live = {sinks[0].id}
    frontier = [sinks[0].id]
    while frontier:
        nid = frontier.pop()
        for ref in seen[nid].inputs:
            if ref not in live:
                live.add(ref)
                frontier.append(ref)
    orphans = sorted(set(seen) - live)
    if orphans:
        raise PlanError(f"nodes {orphans} do not feed the sink")

    object.__setattr__(plan, "_topo", tuple(topo))
    object.__setattr__(plan, "_types", types)
