"""Distributed plan execution: stage programs + shuffle partitions.

The serve tier's production skeleton left one seam open (ROADMAP item
2): pool dispatch shards *wordcount* batches across workers while *plan*
jobs — the general analytics surface — ran solo on the daemon's local
engine, so a plan got none of the pool's retry/quarantine machinery and
none of the scale-out.  This module is the Dean & Ghemawat answer
applied to the plan layer (docs/PLAN.md "Distributed execution"):

  * ``plan_shape()`` recognizes every distributable plan shape and
    returns ``(shape, reason)``: the map->shuffle->reduce[->score]->sink
    fold spine (``StageShape``, the same closed ``_FOLDS`` table
    ``plan/compile.py`` lowers), join trees of wordcount spines
    (``JoinShape`` — the distributed hash-join: co-partitioned bins,
    per-worker tree evaluation), and the pagerank ``iterate`` loop
    (``IterateShape`` — epoch-synchronized rank-shard sweeps).
    Anything else stays on the solo path, byte-identical by refusal,
    and ``reason`` names exactly why (the demotion log / counter and
    the tests read it; ``None`` shape always carries a reason).  Kinds
    that are distribution-exempt BY DESIGN live in the ``SOLO_ONLY``
    registry — analysis rule R014 enforces two-sided that every
    ``NODE_KINDS`` entry is either matched here or listed there, so a
    new kind can never silently stay undistributed;
  * **stage programs**: source splits ride the content-addressed corpus
    spill, each map split folds on a worker's warm executables, and the
    shuffle edge moves keyed partitions worker-to-worker over the
    distributor's binary HMAC'd data plane as packed LKVB files
    (io/serde.py) instead of folding through one merge on the daemon;
  * **deterministic re-execution**: a stage attempt's outputs publish
    ATOMICALLY (tmp + rename into the spill dir, content-addressed by
    sha256 and keyed by (plan fp, split, partition, attempt)), so a
    dead worker's lost shuffle partitions recompute from their durable
    upstream inputs — never a wrong answer, never a full-plan restart;
  * ``finalize()`` folds the reduced partitions back into the EXACT
    bytes the solo path renders (``compile.iter_rendered`` is the one
    spelling of every sink format) — byte-identity is the contract
    throughout, pinned by tests and the check.py smoke.

Chaos: the ``plan.partition`` site fires between the map and reduce
waves on every published partition file ("drop" unlinks it — the reduce
worker's sha/parse check fails structured and the coordinator recomputes
the split; "corrupt" flips bytes — same recovery, the checksum is the
tripwire).  ``plan.stage`` (hooked in distributor/worker.py) models the
stage RPC itself dying.  Telemetry: ``plan.partition_bytes`` counts
published shuffle bytes (closed obs registry, R009).

jax-free at import like the rest of the plan/serve control plane: the
fold/render imports are lazy, so validating shapes and reading
partitions never pays a jax init (CLAUDE.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

import numpy as np

from locust_tpu import obs
from locust_tpu.io import serde
from locust_tpu.utils import faultplan

from .compile import _FOLDS
from .nodes import Plan

# Node kinds that are distribution-exempt BY DESIGN (R014's two-sided
# distributed-coverage check: every NODE_KINDS entry must either appear
# in a ``.kind`` match below or be listed here with a reason).  Empty
# today: every kind participates in at least one distributed shape —
# source/map/shuffle/reduce as the fold spine, join as the hash-join
# tree, iterate as the epoch sweep, sink as the terminal render.
SOLO_ONLY: tuple = ()

# Doc-id suffix budget for composite (word, doc) partition keys: the doc
# id rides a uint32 key lane (apps/tfidf.py), so <= 10 decimal digits
# plus the NUL separator.
_DOC_SUFFIX = 11

# The one key/doc separator for composite shuffle keys.  Safe by
# construction: NUL is a tokenizer delimiter (config.DELIMITERS), so no
# word ever contains it, and the decimal doc-id suffix keeps read_kvbin's
# trailing-NUL strip away from the separator.
PAIR_SEP = b"\x00"


@dataclasses.dataclass(frozen=True)
class StageShape:
    """The distributable fold spine of a validated plan: which engine
    fold the map+reduce pair lowers to, how source lines map to doc
    ids, whether a tfidf_score stage follows the fold, and the sink op
    that renders the final table."""

    fold: str           # "wordcount" | "tf" | "index" (compile._FOLDS)
    lines_per_doc: int  # source param (doc ids are GLOBAL line//k)
    score: bool         # a map/tfidf_score stage between reduce and sink
    sink_op: str        # "table" | "tfidf" | "postings"
    node_fp: str = ""   # closure fp of the reduce node (warm-cache key)


@dataclasses.dataclass(frozen=True)
class FoldLeaf:
    """One wordcount fold spine feeding a join tree (the only leaf type
    the ``join`` signature admits: its inputs are "table"s, and only
    (tokenize_count, sum) over corpus text produces one)."""

    lines_per_doc: int
    node_fp: str    # closure fp of the leaf's reduce node
    reduce_id: str


@dataclasses.dataclass(frozen=True)
class JoinTree:
    """One ``join`` node in a recognized tree: combine op + children
    (each a FoldLeaf or a deeper JoinTree — depth is unbounded, the
    whole tree evaluates per-bin on one worker without returning to
    the master)."""

    combine: str            # "sum" | "mul" | "min" (nodes.JOIN_COMBINES)
    left: object            # FoldLeaf | JoinTree
    right: object           # FoldLeaf | JoinTree


@dataclasses.dataclass(frozen=True)
class JoinShape:
    """A distributable join plan: a tree of inner-joins over wordcount
    fold leaves.  Executes as ONE shared map wave (every leaf is the
    same corpus wordcount fold, so alpha-equivalent leaves share their
    shuffle partitions) plus one join wave that co-partitions by key
    hash and evaluates the whole tree per bin."""

    tree: JoinTree
    leaves: tuple           # distinct FoldLeafs, deterministic order
    sink_op: str            # "table" (the join signature's output)
    depth: int              # join nodes on the longest root->leaf path


@dataclasses.dataclass(frozen=True)
class IterateShape:
    """A distributable pagerank plan: epoch-synchronized sweeps over
    per-worker rank shards, one rank shuffle per iteration."""

    num_iters: int
    damping: float          # traced f32 on device (bit-parity w/ solo)
    node_fp: str            # closure fp of the iterate node
    sink_op: str            # "ranks"


def _fold_spine(plan, by_id, reducer, seen: set):
    """Recognize reduce<-shuffle<-map<-source(text, corpus) ending at
    ``reducer``; returns (fold name, source node, None) or
    (None, None, reason).  ``seen`` collects the spine's node ids for
    the caller's whole-plan coverage check."""
    shuffle = by_id[reducer.inputs[0]]
    if shuffle.kind != "shuffle":
        return None, None, "fold_feed_not_shuffle"
    mapper = by_id[shuffle.inputs[0]]
    if mapper.kind != "map":
        return None, None, "shuffle_feed_not_map"
    src = by_id[mapper.inputs[0]]
    if src.kind != "source" or src.op != "text":
        return None, None, "source_not_corpus_text"
    if src.param("input", "corpus") != "corpus":
        return None, None, "source_named_input"
    fold = _FOLDS.get((mapper.op, reducer.op))
    if fold is None:
        return None, None, "unlowered_fold"
    seen.update((reducer.id, shuffle.id, mapper.id, src.id))
    return fold, src, None


def _join_tree(plan, by_id, nid: str, seen: set, memo: dict):
    """Walk a join tree rooted at ``nid``: every internal node an
    inner-join, every leaf a wordcount fold spine.  Shared sub-trees
    (CSE'd plans) memoize by node id.  Returns (tree, None) or
    (None, reason)."""
    if nid in memo:
        return memo[nid], None
    node = by_id[nid]
    if node.kind == "join":
        left, reason = _join_tree(plan, by_id, node.inputs[0], seen, memo)
        if left is None:
            return None, reason
        right, reason = _join_tree(plan, by_id, node.inputs[1], seen, memo)
        if right is None:
            return None, reason
        seen.add(node.id)
        out = JoinTree(
            combine=node.param("combine", "sum"), left=left, right=right
        )
    elif node.kind == "reduce":
        fold, src, reason = _fold_spine(plan, by_id, node, seen)
        if fold is None:
            return None, reason
        if fold != "wordcount":
            # Typing already forces this (join inputs are "table"s and
            # only the wordcount fold makes one) — belt for the check.
            return None, "join_leaf_not_wordcount"
        out = FoldLeaf(
            lines_per_doc=int(src.param("lines_per_doc", 1)),
            node_fp=plan.node_fingerprint(node.id),
            reduce_id=node.id,
        )
    else:
        return None, "join_input_not_fold_or_join"
    memo[nid] = out
    return out, None


def _tree_depth(tree) -> int:
    if isinstance(tree, FoldLeaf):
        return 0
    return 1 + max(_tree_depth(tree.left), _tree_depth(tree.right))


def _tree_leaves(tree, out: list) -> list:
    if isinstance(tree, FoldLeaf):
        if tree not in out:
            out.append(tree)
    else:
        _tree_leaves(tree.left, out)
        _tree_leaves(tree.right, out)
    return out


def plan_shape(plan: Plan):
    """Recognize a plan's distributable shape.

    Returns ``(shape, reason)``: shape is a StageShape / JoinShape /
    IterateShape and reason is None, or shape is None and reason is a
    short stable string naming WHY the plan stays on the solo engine
    (multi-consumer DAGs, named inputs, unlowered folds...).  The solo
    path is the correctness floor and refusal here can never change an
    answer — but it is never silent: the daemon logs the reason once
    per shape and counts it (``plan_solo_fallbacks``).
    """
    by_id = plan.by_id()
    try:
        sink = next(n for n in plan.nodes if n.kind == "sink")
    except StopIteration:  # pragma: no cover - validation owns this
        return None, "no_sink"
    child = by_id[sink.inputs[0]]

    if child.kind == "iterate":
        if child.op != "pagerank":  # pragma: no cover - closed NODE_OPS
            return None, "iterate_op_uncovered"
        src = by_id[child.inputs[0]]
        if src.kind != "source" or src.op != "edges":
            return None, "iterate_source_not_edges"
        if src.param("input", "corpus") != "corpus":
            return None, "source_named_input"
        if sink.op != "ranks":  # pragma: no cover - typing owns this
            return None, "iterate_sink_not_ranks"
        if len(plan.nodes) != 3:
            return None, "extra_nodes"
        return IterateShape(
            num_iters=int(child.param("num_iters", 20)),
            damping=float(child.param("damping", 0.85)),
            node_fp=plan.node_fingerprint(child.id),
            sink_op=sink.op,
        ), None

    if child.kind == "join":
        if sink.op != "table":  # pragma: no cover - typing owns this
            return None, "join_sink_not_table"
        seen: set = {sink.id}
        tree, reason = _join_tree(plan, by_id, child.id, seen, {})
        if tree is None:
            return None, reason
        if seen != set(by_id):
            # Extra consumers hanging off the tree (a tee re-reading a
            # leaf table) would change what the join wave must produce.
            return None, "extra_nodes"
        return JoinShape(
            tree=tree,
            leaves=tuple(_tree_leaves(tree, [])),
            sink_op=sink.op,
            depth=_tree_depth(tree),
        ), None

    n_expected = 5
    score = False
    if child.kind == "map" and child.op == "tfidf_score":
        score = True
        n_expected += 1
        child = by_id[child.inputs[0]]
    if child.kind != "reduce":
        return None, "sink_feed_not_reduce"
    reducer = child
    seen = set()
    fold, src, reason = _fold_spine(plan, by_id, reducer, seen)
    if fold is None:
        return None, reason
    # Exact node count rejects extra consumers hanging off the spine
    # (a second sink is impossible, but a join/tee re-reading the table
    # would change what the distributed fold must produce).
    if len(plan.nodes) != n_expected:
        return None, "extra_nodes"
    if (fold, score, sink.op) not in (
        ("wordcount", False, "table"),
        ("tf", True, "tfidf"),
        ("index", False, "postings"),
    ):
        return None, "uncovered_sink_combo"
    return StageShape(
        fold=fold,
        lines_per_doc=int(src.param("lines_per_doc", 1)),
        score=score,
        sink_op=sink.op,
        node_fp=plan.node_fingerprint(reducer.id),
    ), None


# ------------------------------------------------------- shuffle keying


def partition_of(key: bytes, n_parts: int) -> int:
    """Deterministic shuffle partitioner: sha256-derived so replays and
    recomputes route every key to the same partition on every host (the
    stable_shard_id stance — chaos plans and re-executions agree)."""
    h = hashlib.sha256(key).digest()
    return int.from_bytes(h[:8], "big") % n_parts


def encode_key(fold: str, key) -> bytes:
    """One wire spelling of a shuffle key: raw word bytes for the
    wordcount fold, ``word NUL decimal-doc-id`` for the composite
    (word, doc) folds."""
    if fold == "wordcount":
        return key
    word, doc = key
    return word + PAIR_SEP + str(int(doc)).encode()


def decode_key(fold: str, raw: bytes):
    if fold == "wordcount":
        return raw
    word, _, doc = raw.rpartition(PAIR_SEP)
    return word, int(doc)


def partition_key_width(cfg, fold: str) -> int:
    """LKVB row width for a fold's encoded keys: engine words are
    already truncated to ``cfg.key_width``; composite keys append the
    NUL + doc-id suffix."""
    if fold == "wordcount":
        return int(cfg.key_width)
    return int(cfg.key_width) + _DOC_SUFFIX


# -------------------------------------------------- partition publish/read


def partition_path(
    out_dir: str, plan_fp: str, split: int, part: int, attempt: int
) -> str:
    """The content-addressed spill name for one stage attempt's output
    partition — (plan fp, split, partition, attempt) is the identity, so
    a speculative backup attempt can never clobber the primary's file."""
    return os.path.join(
        out_dir, f"plan_{plan_fp}_s{split}_p{part}_a{attempt}.kvb"
    )


def publish_partition(path: str, pairs: list) -> dict:
    """Atomically publish one partition file (tmp + rename, the corpus
    spill's own discipline) and return its durable reference: path,
    sha256 over the serialized bytes, sizes.  ``pairs`` are
    (encoded key bytes, int count) tuples."""
    tmp = f"{path}.tmp.{os.getpid()}"
    serde.write_kvbin(pairs, tmp)
    with open(tmp, "rb") as f:
        data = f.read()
    os.replace(tmp, path)
    obs.metric_inc("plan.partition_bytes", len(data))
    return {
        "path": path,
        "sha256": hashlib.sha256(data).hexdigest(),
        "bytes": len(data),
        "pairs": len(pairs),
    }


def publish_split(
    out_dir: str, plan_fp: str, split: int, attempt: int,
    pairs: list, n_parts: int,
) -> list[dict]:
    """Bucket one map split's encoded pairs by partition and publish all
    ``n_parts`` partition files (empty ones included: a missing file and
    an empty partition must stay distinguishable — absence means LOSS)."""
    buckets: list[list] = [[] for _ in range(n_parts)]
    for key, value in pairs:
        buckets[partition_of(key, n_parts)].append((key, int(value)))
    out = []
    for part, bucket in enumerate(buckets):
        ref = publish_partition(
            partition_path(out_dir, plan_fp, split, part, attempt), bucket
        )
        ref["part"] = part
        out.append(ref)
    return out


def read_partition(path: str, expect_sha: str, key_width: int) -> list:
    """Read + verify one published partition: sha256 gate first (a
    corrupt or torn file is a structured loss, never a silent wrong
    answer), then the LKVB decode.  Raises ``ValueError`` on ANY
    damage — the coordinator's recompute path owns recovery."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise ValueError(f"partition {path} unreadable: {e}")
    got = hashlib.sha256(data).hexdigest()
    if got != expect_sha:
        raise ValueError(
            f"partition {path} sha mismatch (got {got[:12]}, want "
            f"{expect_sha[:12]})"
        )
    rows, values = serde.read_kvbin(path, key_width)
    return [
        (rows[i].tobytes().rstrip(b"\x00"), int(values[i]))
        for i in range(len(values))
    ]


def merge_pairs(acc: dict, pairs) -> dict:
    """The reduce stage's combine: sum counts per encoded key (the
    engine's "sum" fold over disjoint splits of the same corpus)."""
    for key, value in pairs:
        acc[key] = acc.get(key, 0) + int(value)
    return acc


def chaos_partition(path: str, split: int, part: int) -> None:
    """The shuffle-partition chaos window (docs/FAULTS.md): fires
    between the map and reduce waves on every published partition.
    "drop" models the spill vanishing mid-plan (GC race, disk loss),
    "corrupt" a torn/flipped file — both must surface as a recompute,
    never a wrong answer."""
    rule = faultplan.fire("plan.partition", path=path, split=split,
                          part=part)
    if rule is None:
        return
    if rule.action == "drop":
        try:
            os.unlink(path)
        except OSError:
            pass
    elif rule.action == "corrupt":
        try:
            with open(path, "rb") as f:
                data = f.read()
            mangled = faultplan.active().mutate(rule, data)
            with open(path, "wb") as f:
                f.write(mangled)
        except OSError:
            pass


# ------------------------------------------------------------- finalize


def finalize(
    shape: StageShape, cfg, n_lines: int, partition_pairs: list[list],
    truncated: bool, overflow: int,
) -> tuple[bytes, int, bool, int]:
    """Fold the reduced shuffle partitions into the solo path's exact
    result: (rendered output bytes, distinct, truncated, overflow).

    Wordcount partitions re-merge through the engine's own
    sort+segment-reduce (``batching.merge_shard_results``, the sharded
    wordcount path's proven-identical merge) so pair ORDER matches the
    solo fold; the composite folds decode into the same host tables the
    solo evaluator builds and render through ``compile.iter_rendered``
    — the one spelling of every sink format.  Device work: the caller
    holds the engine lock.
    """
    from locust_tpu.serve import batch as batching

    from .compile import _render

    if shape.fold == "wordcount":
        shard_results = [
            {"pairs": pairs, "truncated": False, "overflow_tokens": 0}
            for pairs in partition_pairs
        ]
        shard_results.append({
            "pairs": [], "truncated": bool(truncated),
            "overflow_tokens": int(overflow),
        })
        pairs, distinct, trunc, ovf = batching.merge_shard_results(
            shard_results, cfg, "sum"
        )
        return _render("table", pairs), distinct, trunc, ovf
    table: dict = {}
    for pairs in partition_pairs:
        for raw, count in pairs:
            key = decode_key(shape.fold, raw)
            table[key] = table.get(key, 0) + int(count)
    if shape.fold == "tf":
        from locust_tpu.apps.tfidf import scores_from_tf

        # n_docs exactly as the solo evaluator derives it: distinct
        # GLOBAL doc ids over the input (arange(n) // lines_per_doc).
        n_docs = -(-int(n_lines) // shape.lines_per_doc) or 1
        scores = scores_from_tf(table, n_docs)
        return _render("tfidf", scores), len(scores), False, 0
    # index: postings = {word: sorted unique doc ids} (the counts only
    # carried the shuffle; the inverted index keeps membership).
    postings: dict = {}
    for word, doc in table:
        postings.setdefault(word, set()).add(int(doc))
    postings = {w: sorted(d) for w, d in postings.items()}
    return _render("postings", postings), len(postings), False, 0


# ------------------------------------------------------------ join trees

# The one spelling of the inner-join combine ops — MUST mirror
# compile._eval_join exactly: host Python ints, so a "mul" join's
# products never wrap int32 the way a device merge would.
JOIN_OPS = {
    "sum": lambda a, b: a + b,
    "mul": lambda a, b: a * b,
    "min": min,
}


def tree_doc(tree) -> list:
    """Serialize a JoinTree for the stage RPC wire: nested JSON lists
    ``["join", combine, left, right]`` with ``["leaf"]`` terminals.
    Every leaf of a covered join tree is the SAME corpus wordcount
    table (the join signature only admits wordcount folds over the one
    corpus), so the wire form needs no per-leaf identity."""
    if isinstance(tree, FoldLeaf):
        return ["leaf"]
    return ["join", tree.combine, tree_doc(tree.left), tree_doc(tree.right)]


def eval_tree_doc(doc: list, table: dict) -> dict:
    """Evaluate a serialized join tree over one co-partitioned bin's
    wordcount table: inner-join semantics exactly as the solo
    ``compile._eval_join`` (key in both sides, ``op(left, right)``).
    Restricting to one hash bin is exact because ``partition_of``
    routes every key of every leaf to the same bin."""
    if doc[0] == "leaf":
        return table
    _, combine, left_doc, right_doc = doc
    left = eval_tree_doc(left_doc, table)
    right = eval_tree_doc(right_doc, table)
    op = JOIN_OPS[combine]
    return {k: op(v, right[k]) for k, v in left.items() if k in right}


def finalize_join(bin_pairs: list[list]) -> tuple[bytes, int, bool, int]:
    """Merge the join wave's per-bin results into the solo bytes: the
    bins are key-disjoint, so one host sort of the concatenation IS the
    solo evaluator's ``sorted(...)`` over the whole join.  Host-side on
    purpose — join values are unbounded Python ints (mul combines), so
    a device sort_and_compact merge would wrap; disjointness makes the
    compaction a no-op anyway.  Accounting mirrors solo ``_eval_join``:
    (distinct, False, 0)."""
    from .compile import _render

    pairs = sorted(p for chunk in bin_pairs for p in chunk)
    return _render("table", pairs), len(pairs), False, 0


# ----------------------------------------------------------- rank shards

# Rank-shuffle key lane: node ids as zero-padded decimal, one width for
# every epoch partition (ties the LKVB row width down without a cfg).
RANK_KEY_WIDTH = 10


def shard_ranges(num_nodes: int, n_shards: int) -> list[tuple[int, int]]:
    """Contiguous per-worker rank shards [lo, hi): the same balanced
    split on every host/attempt so recomputes and WAL resumes agree."""
    return [
        (i * num_nodes // n_shards, (i + 1) * num_nodes // n_shards)
        for i in range(n_shards)
    ]


def encode_rank_pairs(lo: int, ranks) -> list:
    """One epoch shard's ranks as LKVB pairs: key = zero-padded node
    id, value = the float32 BIT PATTERN as int32 (the kvbin value lane
    is int32; a bit-cast round-trips exactly, a decimal rendering would
    not)."""
    bits = np.ascontiguousarray(np.asarray(ranks, np.float32)).view(
        np.int32
    )
    return [(b"%010d" % (lo + i), int(bits[i])) for i in range(len(bits))]


def decode_rank_values(pairs: list):
    """Invert encode_rank_pairs for one partition read in row order."""
    return np.array(
        [v for _, v in pairs], dtype=np.int32
    ).view(np.float32)


def finalize_ranks(rank_slices: list) -> tuple[bytes, int, bool, int]:
    """Concatenate the final epoch's shard slices (shard order == node
    order) into the solo render: ``_render("ranks", ...)`` is the one
    spelling, accounting mirrors solo ``_eval_pagerank`` (n, False, 0)."""
    from .compile import _render

    ranks = np.concatenate(
        [np.asarray(s, np.float32) for s in rank_slices]
    )
    return _render("ranks", ranks), len(ranks), False, 0
