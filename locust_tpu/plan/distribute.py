"""Distributed plan execution: stage programs + shuffle partitions.

The serve tier's production skeleton left one seam open (ROADMAP item
2): pool dispatch shards *wordcount* batches across workers while *plan*
jobs — the general analytics surface — ran solo on the daemon's local
engine, so a plan got none of the pool's retry/quarantine machinery and
none of the scale-out.  This module is the Dean & Ghemawat answer
applied to the plan layer (docs/PLAN.md "Distributed execution"):

  * ``plan_shape()`` recognizes the map->shuffle->reduce[->score]->sink
    spine the engine's folds cover (the same closed ``_FOLDS`` table
    ``plan/compile.py`` lowers) and returns its distributable shape —
    anything else stays on the solo path, byte-identical by refusal;
  * **stage programs**: source splits ride the content-addressed corpus
    spill, each map split folds on a worker's warm executables, and the
    shuffle edge moves keyed partitions worker-to-worker over the
    distributor's binary HMAC'd data plane as packed LKVB files
    (io/serde.py) instead of folding through one merge on the daemon;
  * **deterministic re-execution**: a stage attempt's outputs publish
    ATOMICALLY (tmp + rename into the spill dir, content-addressed by
    sha256 and keyed by (plan fp, split, partition, attempt)), so a
    dead worker's lost shuffle partitions recompute from their durable
    upstream inputs — never a wrong answer, never a full-plan restart;
  * ``finalize()`` folds the reduced partitions back into the EXACT
    bytes the solo path renders (``compile.iter_rendered`` is the one
    spelling of every sink format) — byte-identity is the contract
    throughout, pinned by tests and the check.py smoke.

Chaos: the ``plan.partition`` site fires between the map and reduce
waves on every published partition file ("drop" unlinks it — the reduce
worker's sha/parse check fails structured and the coordinator recomputes
the split; "corrupt" flips bytes — same recovery, the checksum is the
tripwire).  ``plan.stage`` (hooked in distributor/worker.py) models the
stage RPC itself dying.  Telemetry: ``plan.partition_bytes`` counts
published shuffle bytes (closed obs registry, R009).

jax-free at import like the rest of the plan/serve control plane: the
fold/render imports are lazy, so validating shapes and reading
partitions never pays a jax init (CLAUDE.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

from locust_tpu import obs
from locust_tpu.io import serde
from locust_tpu.utils import faultplan

from .compile import _FOLDS
from .nodes import Plan

# Doc-id suffix budget for composite (word, doc) partition keys: the doc
# id rides a uint32 key lane (apps/tfidf.py), so <= 10 decimal digits
# plus the NUL separator.
_DOC_SUFFIX = 11

# The one key/doc separator for composite shuffle keys.  Safe by
# construction: NUL is a tokenizer delimiter (config.DELIMITERS), so no
# word ever contains it, and the decimal doc-id suffix keeps read_kvbin's
# trailing-NUL strip away from the separator.
PAIR_SEP = b"\x00"


@dataclasses.dataclass(frozen=True)
class StageShape:
    """The distributable spine of a validated plan: which engine fold
    the map+reduce pair lowers to, how source lines map to doc ids,
    whether a tfidf_score stage follows the fold, and the sink op that
    renders the final table."""

    fold: str           # "wordcount" | "tf" | "index" (compile._FOLDS)
    lines_per_doc: int  # source param (doc ids are GLOBAL line//k)
    score: bool         # a map/tfidf_score stage between reduce and sink
    sink_op: str        # "table" | "tfidf" | "postings"


def plan_shape(plan: Plan) -> StageShape | None:
    """Recognize the map->shuffle->reduce[->score]->sink spine, or None.

    None means the plan stays on the solo engine (pagerank iteration,
    joins, multi-consumer DAGs, named inputs): the solo path is the
    correctness floor and refusal here can never change an answer.
    """
    by_id = plan.by_id()
    try:
        sink = plan.sink()
    except StopIteration:  # pragma: no cover - validation owns this
        return None
    n_expected = 5
    child = by_id[sink.inputs[0]]
    score = False
    if child.kind == "map" and child.op == "tfidf_score":
        score = True
        n_expected += 1
        child = by_id[child.inputs[0]]
    if child.kind != "reduce":
        return None
    reducer = child
    shuffle = by_id[reducer.inputs[0]]
    if shuffle.kind != "shuffle":
        return None
    mapper = by_id[shuffle.inputs[0]]
    if mapper.kind != "map":
        return None
    src = by_id[mapper.inputs[0]]
    if src.kind != "source" or src.op != "text":
        return None
    if src.param("input", "corpus") != "corpus":
        return None
    fold = _FOLDS.get((mapper.op, reducer.op))
    if fold is None:
        return None
    # Exact node count rejects extra consumers hanging off the spine
    # (a second sink is impossible, but a join/tee re-reading the table
    # would change what the distributed fold must produce).
    if len(plan.nodes) != n_expected:
        return None
    if (fold, score, sink.op) not in (
        ("wordcount", False, "table"),
        ("tf", True, "tfidf"),
        ("index", False, "postings"),
    ):
        return None
    return StageShape(
        fold=fold,
        lines_per_doc=int(src.param("lines_per_doc", 1)),
        score=score,
        sink_op=sink.op,
    )


# ------------------------------------------------------- shuffle keying


def partition_of(key: bytes, n_parts: int) -> int:
    """Deterministic shuffle partitioner: sha256-derived so replays and
    recomputes route every key to the same partition on every host (the
    stable_shard_id stance — chaos plans and re-executions agree)."""
    h = hashlib.sha256(key).digest()
    return int.from_bytes(h[:8], "big") % n_parts


def encode_key(fold: str, key) -> bytes:
    """One wire spelling of a shuffle key: raw word bytes for the
    wordcount fold, ``word NUL decimal-doc-id`` for the composite
    (word, doc) folds."""
    if fold == "wordcount":
        return key
    word, doc = key
    return word + PAIR_SEP + str(int(doc)).encode()


def decode_key(fold: str, raw: bytes):
    if fold == "wordcount":
        return raw
    word, _, doc = raw.rpartition(PAIR_SEP)
    return word, int(doc)


def partition_key_width(cfg, fold: str) -> int:
    """LKVB row width for a fold's encoded keys: engine words are
    already truncated to ``cfg.key_width``; composite keys append the
    NUL + doc-id suffix."""
    if fold == "wordcount":
        return int(cfg.key_width)
    return int(cfg.key_width) + _DOC_SUFFIX


# -------------------------------------------------- partition publish/read


def partition_path(
    out_dir: str, plan_fp: str, split: int, part: int, attempt: int
) -> str:
    """The content-addressed spill name for one stage attempt's output
    partition — (plan fp, split, partition, attempt) is the identity, so
    a speculative backup attempt can never clobber the primary's file."""
    return os.path.join(
        out_dir, f"plan_{plan_fp}_s{split}_p{part}_a{attempt}.kvb"
    )


def publish_partition(path: str, pairs: list) -> dict:
    """Atomically publish one partition file (tmp + rename, the corpus
    spill's own discipline) and return its durable reference: path,
    sha256 over the serialized bytes, sizes.  ``pairs`` are
    (encoded key bytes, int count) tuples."""
    tmp = f"{path}.tmp.{os.getpid()}"
    serde.write_kvbin(pairs, tmp)
    with open(tmp, "rb") as f:
        data = f.read()
    os.replace(tmp, path)
    obs.metric_inc("plan.partition_bytes", len(data))
    return {
        "path": path,
        "sha256": hashlib.sha256(data).hexdigest(),
        "bytes": len(data),
        "pairs": len(pairs),
    }


def publish_split(
    out_dir: str, plan_fp: str, split: int, attempt: int,
    pairs: list, n_parts: int,
) -> list[dict]:
    """Bucket one map split's encoded pairs by partition and publish all
    ``n_parts`` partition files (empty ones included: a missing file and
    an empty partition must stay distinguishable — absence means LOSS)."""
    buckets: list[list] = [[] for _ in range(n_parts)]
    for key, value in pairs:
        buckets[partition_of(key, n_parts)].append((key, int(value)))
    out = []
    for part, bucket in enumerate(buckets):
        ref = publish_partition(
            partition_path(out_dir, plan_fp, split, part, attempt), bucket
        )
        ref["part"] = part
        out.append(ref)
    return out


def read_partition(path: str, expect_sha: str, key_width: int) -> list:
    """Read + verify one published partition: sha256 gate first (a
    corrupt or torn file is a structured loss, never a silent wrong
    answer), then the LKVB decode.  Raises ``ValueError`` on ANY
    damage — the coordinator's recompute path owns recovery."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise ValueError(f"partition {path} unreadable: {e}")
    got = hashlib.sha256(data).hexdigest()
    if got != expect_sha:
        raise ValueError(
            f"partition {path} sha mismatch (got {got[:12]}, want "
            f"{expect_sha[:12]})"
        )
    rows, values = serde.read_kvbin(path, key_width)
    return [
        (rows[i].tobytes().rstrip(b"\x00"), int(values[i]))
        for i in range(len(values))
    ]


def merge_pairs(acc: dict, pairs) -> dict:
    """The reduce stage's combine: sum counts per encoded key (the
    engine's "sum" fold over disjoint splits of the same corpus)."""
    for key, value in pairs:
        acc[key] = acc.get(key, 0) + int(value)
    return acc


def chaos_partition(path: str, split: int, part: int) -> None:
    """The shuffle-partition chaos window (docs/FAULTS.md): fires
    between the map and reduce waves on every published partition.
    "drop" models the spill vanishing mid-plan (GC race, disk loss),
    "corrupt" a torn/flipped file — both must surface as a recompute,
    never a wrong answer."""
    rule = faultplan.fire("plan.partition", path=path, split=split,
                          part=part)
    if rule is None:
        return
    if rule.action == "drop":
        try:
            os.unlink(path)
        except OSError:
            pass
    elif rule.action == "corrupt":
        try:
            with open(path, "rb") as f:
                data = f.read()
            mangled = faultplan.active().mutate(rule, data)
            with open(path, "wb") as f:
                f.write(mangled)
        except OSError:
            pass


# ------------------------------------------------------------- finalize


def finalize(
    shape: StageShape, cfg, n_lines: int, partition_pairs: list[list],
    truncated: bool, overflow: int,
) -> tuple[bytes, int, bool, int]:
    """Fold the reduced shuffle partitions into the solo path's exact
    result: (rendered output bytes, distinct, truncated, overflow).

    Wordcount partitions re-merge through the engine's own
    sort+segment-reduce (``batching.merge_shard_results``, the sharded
    wordcount path's proven-identical merge) so pair ORDER matches the
    solo fold; the composite folds decode into the same host tables the
    solo evaluator builds and render through ``compile.iter_rendered``
    — the one spelling of every sink format.  Device work: the caller
    holds the engine lock.
    """
    from locust_tpu.serve import batch as batching

    from .compile import _render

    if shape.fold == "wordcount":
        shard_results = [
            {"pairs": pairs, "truncated": False, "overflow_tokens": 0}
            for pairs in partition_pairs
        ]
        shard_results.append({
            "pairs": [], "truncated": bool(truncated),
            "overflow_tokens": int(overflow),
        })
        pairs, distinct, trunc, ovf = batching.merge_shard_results(
            shard_results, cfg, "sum"
        )
        return _render("table", pairs), distinct, trunc, ovf
    table: dict = {}
    for pairs in partition_pairs:
        for raw, count in pairs:
            key = decode_key(shape.fold, raw)
            table[key] = table.get(key, 0) + int(count)
    if shape.fold == "tf":
        from locust_tpu.apps.tfidf import scores_from_tf

        # n_docs exactly as the solo evaluator derives it: distinct
        # GLOBAL doc ids over the input (arange(n) // lines_per_doc).
        n_docs = -(-int(n_lines) // shape.lines_per_doc) or 1
        scores = scores_from_tf(table, n_docs)
        return _render("tfidf", scores), len(scores), False, 0
    # index: postings = {word: sorted unique doc ids} (the counts only
    # carried the shuffle; the inverted index keeps membership).
    postings: dict = {}
    for word, doc in table:
        postings.setdefault(word, set()).add(int(doc))
    postings = {w: sorted(d) for w, d in postings.items()}
    return _render("postings", postings), len(postings), False, 0
