"""Key packing: NUL-padded key bytes <-> big-endian uint32 lanes.

The reference sorts 30-byte keys with a byte-wise comparator loop
(KIVComparator, reference MapReduce/src/KeyValue.h:20-33).  TPUs sort
integers far faster than data-dependent byte loops, and byte-wise
lexicographic order on NUL-padded equal-width keys is *exactly* elementwise
tuple order on big-endian-packed uint32 lanes — so a key_width-byte key
becomes key_width/4 uint32 sort operands and ``jax.lax.sort`` with
``num_keys=key_lanes`` reproduces the comparator's ordering with no
comparator at all.

Ordering note: we compare bytes as *unsigned* (0..255).  The reference
compares ``char`` (signed on its platforms), which differs only for
non-ASCII bytes >= 0x80; documented deliberate divergence (SURVEY.md §7.3).
NUL-padding means a proper prefix sorts before its extensions, matching
strcmp semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pack_keys(keys: jax.Array) -> jax.Array:
    """uint8 ``[..., K]`` -> big-endian uint32 lanes ``[..., K//4]``."""
    k = keys.shape[-1]
    if k % 4 != 0:
        raise ValueError(f"key width {k} not a multiple of 4")
    r = keys.reshape(*keys.shape[:-1], k // 4, 4).astype(jnp.uint32)
    return (r[..., 0] << 24) | (r[..., 1] << 16) | (r[..., 2] << 8) | r[..., 3]


def unpack_keys(lanes: jax.Array) -> jax.Array:
    """Big-endian uint32 lanes ``[..., L]`` -> uint8 bytes ``[..., 4L]``."""
    parts = jnp.stack(
        [
            (lanes >> 24) & 0xFF,
            (lanes >> 16) & 0xFF,
            (lanes >> 8) & 0xFF,
            lanes & 0xFF,
        ],
        axis=-1,
    ).astype(jnp.uint8)
    return parts.reshape(*lanes.shape[:-1], lanes.shape[-1] * 4)


def lanes_equal(a: jax.Array, b: jax.Array) -> jax.Array:
    """Row-wise key equality over the lane dim: ``my_strcmp(...) == 0``."""
    return jnp.all(a == b, axis=-1)


def lanes_less(a: jax.Array, b: jax.Array) -> jax.Array:
    """Row-wise lexicographic ``a < b`` over big-endian lanes.

    Equivalent to KIVComparator (KeyValue.h:20-33) on the unpacked bytes —
    without its walk-past-NUL out-of-bounds read on equal keys (SURVEY.md Q3).
    """
    # First lane where they differ decides; scan from most significant.
    neq = a != b
    first_diff = jnp.argmax(neq, axis=-1)
    a_at = jnp.take_along_axis(a, first_diff[..., None], axis=-1)[..., 0]
    b_at = jnp.take_along_axis(b, first_diff[..., None], axis=-1)[..., 0]
    any_diff = jnp.any(neq, axis=-1)
    return jnp.where(any_diff, a_at < b_at, False)


def fold_hash(lanes: jax.Array) -> jax.Array:
    """uint32 mixing hash of packed key lanes (for shuffle bucketing).

    FNV-1a-style lane fold followed by a murmur3 finalizer — used by the
    distributed shuffle to hash-partition keys across mesh devices
    (SURVEY.md §2.3 "TPU-native plan" for the shuffle).
    """
    h = jnp.full(lanes.shape[:-1], 0x811C9DC5, dtype=jnp.uint32)
    for i in range(lanes.shape[-1]):
        h = (h ^ lanes[..., i]) * jnp.uint32(0x01000193)
    # murmur3 fmix32
    h ^= h >> 16
    h = h * jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h = h * jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h
