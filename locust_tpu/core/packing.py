"""Key packing: NUL-padded key bytes <-> big-endian uint32 lanes.

The reference sorts 30-byte keys with a byte-wise comparator loop
(KIVComparator, reference MapReduce/src/KeyValue.h:20-33).  TPUs sort
integers far faster than data-dependent byte loops, and byte-wise
lexicographic order on NUL-padded equal-width keys is *exactly* elementwise
tuple order on big-endian-packed uint32 lanes — so a key_width-byte key
becomes key_width/4 uint32 sort operands and ``jax.lax.sort`` with
``num_keys=key_lanes`` reproduces the comparator's ordering with no
comparator at all.

Ordering note: we compare bytes as *unsigned* (0..255).  The reference
compares ``char`` (signed on its platforms), which differs only for
non-ASCII bytes >= 0x80; documented deliberate divergence (SURVEY.md §7.3).
NUL-padding means a proper prefix sorts before its extensions, matching
strcmp semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pack_keys(keys: jax.Array) -> jax.Array:
    """uint8 ``[..., K]`` -> big-endian uint32 lanes ``[..., K//4]``."""
    k = keys.shape[-1]
    if k % 4 != 0:
        raise ValueError(f"key width {k} not a multiple of 4")
    r = keys.reshape(*keys.shape[:-1], k // 4, 4).astype(jnp.uint32)
    return (r[..., 0] << 24) | (r[..., 1] << 16) | (r[..., 2] << 8) | r[..., 3]


def unpack_keys(lanes: jax.Array) -> jax.Array:
    """Big-endian uint32 lanes ``[..., L]`` -> uint8 bytes ``[..., 4L]``."""
    parts = jnp.stack(
        [
            (lanes >> 24) & 0xFF,
            (lanes >> 16) & 0xFF,
            (lanes >> 8) & 0xFF,
            lanes & 0xFF,
        ],
        axis=-1,
    ).astype(jnp.uint8)
    return parts.reshape(*lanes.shape[:-1], lanes.shape[-1] * 4)


def lanes_equal(a: jax.Array, b: jax.Array) -> jax.Array:
    """Row-wise key equality over the lane dim: ``my_strcmp(...) == 0``."""
    return jnp.all(a == b, axis=-1)


def _first_diff_lanes(a: jax.Array, b: jax.Array):
    """Broadcast-compare lane tuples; return (any_diff, a_at, b_at) where
    ``*_at`` are the values at the first (most significant) differing lane.

    The shared core of every lexicographic comparator here: big-endian lane
    tuple order == byte order, so the first differing lane decides.
    """
    a, b = jnp.broadcast_arrays(a, b)
    neq = a != b
    first_diff = jnp.argmax(neq, axis=-1)
    a_at = jnp.take_along_axis(a, first_diff[..., None], axis=-1)[..., 0]
    b_at = jnp.take_along_axis(b, first_diff[..., None], axis=-1)[..., 0]
    return jnp.any(neq, axis=-1), a_at, b_at


def lanes_less(a: jax.Array, b: jax.Array) -> jax.Array:
    """Row-wise lexicographic ``a < b`` over big-endian lanes.

    Equivalent to KIVComparator (KeyValue.h:20-33) on the unpacked bytes —
    without its walk-past-NUL out-of-bounds read on equal keys (SURVEY.md Q3).
    """
    any_diff, a_at, b_at = _first_diff_lanes(a, b)
    return jnp.where(any_diff, a_at < b_at, False)


def lanes_geq_table(keys: jax.Array, splitters: jax.Array) -> jax.Array:
    """Pairwise lexicographic ``keys[n] >= splitters[s]`` -> bool ``[N, S]``.

    Vectorized comparator for range partitioning (sample sort).  S (number
    of splitters, ~mesh size) is small, so the [N, S, L] broadcast is cheap.
    """
    any_diff, a_at, b_at = _first_diff_lanes(
        keys[:, None, :], splitters[None, :, :]
    )
    return jnp.where(any_diff, a_at > b_at, True)           # equal => >=


def _fmix32(h: jax.Array) -> jax.Array:
    """murmur3 finalizer: a full-avalanche bijection on uint32."""
    h ^= h >> 16
    h = h * jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    h = h * jnp.uint32(0xC2B2AE35)
    h ^= h >> 16
    return h


def _salted_fold(lanes: jax.Array, salt_prime: int, pre_mul: int | None) -> jax.Array:
    """fmix32(sum_i fmix32(lane_i ^ salt_i)): one vectorized pass over lanes.

    Deliberately NOT a sequential per-lane fold (h = (h^lane)*prime):
    column-at-a-time reads of a fused producer make XLA recompute the whole
    upstream tokenize chain once per read — measured ~12x the cost of the
    entire map stage on TPU v5e.  The commutative salted-sum form reads the
    ``[N, L]`` lane array in one elementwise pass + one lane-axis reduction;
    position sensitivity comes from per-lane salts, avalanche from fmix32.
    Non-cryptographic, same grade as murmur/xxHash.
    """
    n_lanes = lanes.shape[-1]
    i = jnp.arange(n_lanes, dtype=jnp.uint32)
    salts = (i + 1) * jnp.uint32(salt_prime)
    x = lanes if pre_mul is None else lanes * jnp.uint32(pre_mul)
    per_lane = _fmix32(x ^ salts)  # trailing-dim broadcast: any leading rank
    return _fmix32(jnp.sum(per_lane, axis=-1, dtype=jnp.uint32))


def hash_pair(lanes: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Two independent uint32 mixing hashes of packed key lanes.

    Together they act as a 64-bit grouping hash for the "hash" sort mode
    (ops/process_stage.py): sorting by (h1, h2) groups equal keys adjacently
    with 3 sort operands instead of key_lanes+1.  Distinct keys colliding in
    all 64 bits (~n^2/2^64 per block) could interleave within their hash run;
    downstream segment boundaries compare FULL key lanes, so the failure mode
    is a duplicated table row, which the host-side finalize re-merges.
    """
    h1 = _salted_fold(lanes, 0x9E3779B9, None)
    h2 = _salted_fold(lanes, 0xC2B2AE3D, 0x01000193)
    return h1, h2


def fold_hash(lanes: jax.Array) -> jax.Array:
    """uint32 mixing hash of packed key lanes (for shuffle bucketing).

    Used by the distributed shuffle to hash-partition keys across mesh
    devices (SURVEY.md §2.3 "TPU-native plan" for the shuffle).  Uses a
    salt distinct from both hash_pair streams so shuffle bucketing is
    uncorrelated with sort order.
    """
    return _salted_fold(lanes, 0x85EBCA77, None)
