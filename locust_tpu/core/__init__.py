from locust_tpu.core import bytes_ops, kv, packing  # noqa: F401
from locust_tpu.core.kv import KVBatch  # noqa: F401
