"""KV data model: fixed-width key/value batches as JAX pytrees.

Replaces the reference's POD structs-of-char-arrays —
``KeyValuePair{char key[100]; char value[100]; int ind}`` and
``KeyIntValuePair{char key[30]; int value; int count}``
(reference MapReduce/src/KeyValue.h:6-18) — with structure-of-arrays
tensors: keys live as packed big-endian uint32 lanes (see core/packing.py),
values as int32, and validity as an explicit bool mask instead of the
empty-string sentinel that the reference's compaction predicates test
(KeyIntValueNotEmpty, KeyValue.h:79-84).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.core import bytes_ops, packing


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVBatch:
    """A batch of (key, value) emits.

    Attributes:
      key_lanes: uint32 ``[N, L]`` — big-endian packed key bytes.
      values: int32 ``[N]``.
      valid: bool ``[N]`` — live entries; replaces empty-key sentinels.
    """

    key_lanes: jax.Array
    values: jax.Array
    valid: jax.Array

    @property
    def size(self) -> int:
        return self.key_lanes.shape[0]

    @property
    def num_lanes(self) -> int:
        return self.key_lanes.shape[-1]

    def num_valid(self) -> jax.Array:
        return jnp.sum(self.valid.astype(jnp.int32))

    def keys_bytes(self) -> jax.Array:
        """uint8 ``[N, 4L]`` NUL-padded key bytes."""
        return packing.unpack_keys(self.key_lanes)

    @classmethod
    def from_bytes(cls, keys: jax.Array, values: jax.Array, valid: jax.Array) -> "KVBatch":
        return cls(
            key_lanes=packing.pack_keys(keys),
            values=values.astype(jnp.int32),
            valid=valid.astype(bool),
        )

    @classmethod
    def concat(cls, *batches: "KVBatch") -> "KVBatch":
        return cls(
            key_lanes=jnp.concatenate([b.key_lanes for b in batches]),
            values=jnp.concatenate([b.values for b in batches]),
            valid=jnp.concatenate([b.valid for b in batches]),
        )

    @classmethod
    def empty(cls, n: int, key_lanes: int) -> "KVBatch":
        return cls(
            key_lanes=jnp.zeros((n, key_lanes), dtype=jnp.uint32),
            values=jnp.zeros((n,), dtype=jnp.int32),
            valid=jnp.zeros((n,), dtype=bool),
        )

    def to_host_pairs(self) -> list[tuple[bytes, int]]:
        """Host-side: decode live entries to (key bytes, value) pairs.

        ONE device_get for the whole batch (a single round trip — on remote
        TPU links per-array fetches each pay full latency), lane unpacking
        in numpy (big-endian reinterpret), and a Python decode loop that is
        O(live entries), not O(table capacity).
        """
        lanes, values, valid = jax.device_get(
            (self.key_lanes, self.values, self.valid)
        )
        valid = np.asarray(valid)
        live_lanes = np.asarray(lanes)[valid]
        live_values = np.asarray(values)[valid]
        # big-endian uint32 lanes -> the original NUL-padded key bytes
        n_live, n_lanes = live_lanes.shape
        keys = live_lanes.astype(">u4").view(np.uint8).reshape(n_live, n_lanes * 4)
        return [
            (k, int(v))
            for k, v in zip(bytes_ops.rows_to_strings(keys), live_values)
        ]
