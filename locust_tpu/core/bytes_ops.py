"""Byte-tensor string primitives — the TPU-native device string library.

The reference hand-rolls a device libc (my_strlen/my_strcmp/my_strcpy/
my_strtok_r/my_reverse/my_itoa, reference MapReduce/src/util.cu:3-140) because
CUDA kernels have no libc.  On TPU the idiomatic formulation is data-parallel
ops over fixed-width ``uint8`` tensors: a "string" is a NUL-padded row, and
every libc routine becomes a vectorized mask/scan/gather:

  my_strlen   -> byte_length          (argmax of the NUL mask)
  my_strcmp   -> packed-lane compare  (see core/packing.py; big-endian uint32
                                       lane order == lexicographic byte order)
  my_strcpy   -> array slicing / take_along_axis gathers
  my_strtok_r -> token_starts/token_ids (delimiter mask + prefix-sum segment
                 ids, replacing the inherently sequential strtok_r loop at
                 util.cu:54-89 with one parallel pass)
  my_itoa     -> itoa_bytes           (vectorized decimal digit extraction,
                 replacing util.cu:106-140 + my_reverse at util.cu:91-104)

All functions are shape-polymorphic over leading batch dims and jit-safe
(static shapes, no data-dependent control flow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.config import DELIMITERS


def byte_length(x: jax.Array) -> jax.Array:
    """Length of each NUL-padded byte row: ``my_strlen`` (util.cu:3-9).

    Args:
      x: uint8 array ``[..., W]``, rows padded with 0 after the content.
    Returns:
      int32 array ``[...]`` — index of the first zero byte, or W if none.
    """
    w = x.shape[-1]
    is_nul = x == 0
    first = jnp.argmax(is_nul, axis=-1).astype(jnp.int32)
    return jnp.where(jnp.any(is_nul, axis=-1), first, w)


def delimiter_mask(x: jax.Array, delimiters: bytes = DELIMITERS) -> jax.Array:
    """Boolean mask of bytes that terminate tokens.

    Matches the reference's strtok delimiter set (main.cu:138) plus the NUL
    pad byte and newline/carriage-return, which in the reference never reach
    strtok because tokenization is per-getline-line.
    """
    from locust_tpu.config import TOKEN_BOUNDARY_EXTRA

    delims = np.frombuffer(delimiters + TOKEN_BOUNDARY_EXTRA, dtype=np.uint8)
    # Small membership test: [..., W, D] compare then any-reduce. D is ~13 so
    # this stays cheap and fuses into one VPU pass.
    return jnp.any(x[..., None] == jnp.asarray(delims), axis=-1)


def token_starts(in_token: jax.Array) -> jax.Array:
    """Mask of token first-bytes given an in-token (non-delimiter) mask.

    A byte starts a token iff it is in-token and its left neighbor is not
    (position 0 counts as having a delimiter neighbor) — the parallel
    equivalent of strtok_r's "skip leading delimiters" phase (util.cu:63-70).
    """
    prev = jnp.pad(in_token[..., :-1], [(0, 0)] * (in_token.ndim - 1) + [(1, 0)])
    return in_token & ~prev


def token_ends(in_token: jax.Array) -> jax.Array:
    """Mask of token last-bytes (right neighbor is a delimiter or row end)."""
    nxt = jnp.pad(in_token[..., 1:], [(0, 0)] * (in_token.ndim - 1) + [(0, 1)])
    return in_token & ~nxt


def token_ids(starts: jax.Array) -> jax.Array:
    """0-based token index at every byte position (valid where in-token).

    ``cumsum(starts) - 1`` — the prefix-sum segment-id trick that replaces
    the sequential token loop of strtok_r (util.cu:54-89).
    """
    return jnp.cumsum(starts.astype(jnp.int32), axis=-1) - 1


def count_tokens(lines: jax.Array, delimiters: bytes = DELIMITERS) -> jax.Array:
    """Number of tokens per row."""
    starts = token_starts(~delimiter_mask(lines, delimiters))
    return jnp.sum(starts.astype(jnp.int32), axis=-1)


def itoa_bytes(values: jax.Array, width: int = 12) -> jax.Array:
    """Non-negative int32 -> left-aligned ASCII decimal, NUL-padded.

    Vectorized ``my_itoa`` (util.cu:106-140): digit extraction by repeated
    division; the reference then reverses in place (my_reverse, util.cu:91-104)
    — here we extract most-significant-first and left-shift by the digit
    count instead, with a take_along_axis gather.

    Args:
      values: int32 ``[...]`` of non-negative integers (negatives clamp to 0).
      width: output byte width; >= 10 so any int32 fits.
    Returns:
      uint8 ``[..., width]``.
    """
    if width < 10:
        raise ValueError(f"width {width} cannot hold all int32 values (need >= 10)")
    v = jnp.maximum(values.astype(jnp.int32), 0)
    # Right-aligned digits, most significant first.  int32 holds <= 10 digits,
    # so powers beyond 10^9 are materialized as 10^9 and masked to digit 0.
    p_exp = list(range(width - 1, -1, -1))
    pows = jnp.asarray([10 ** min(p, 9) for p in p_exp], dtype=jnp.int32)
    in_range = jnp.asarray([p <= 9 for p in p_exp])
    digits = jnp.where(in_range, (v[..., None] // pows) % 10, 0)  # [..., width]
    ndig = jnp.maximum(
        jnp.sum((in_range & (v[..., None] >= pows)).astype(jnp.int32), axis=-1), 1
    )  # number of significant digits; v=0 -> 1
    # Left-align: output position k reads right-aligned position k+(width-ndig).
    k = jnp.arange(width, dtype=jnp.int32)
    src = k + (width - ndig)[..., None]
    gathered = jnp.take_along_axis(digits, jnp.clip(src, 0, width - 1), axis=-1)
    ascii_digits = (gathered + ord("0")).astype(jnp.uint8)
    return jnp.where(k < ndig[..., None], ascii_digits, jnp.uint8(0))


def rows_to_strings(rows: np.ndarray) -> list[bytes]:
    """Host-side: NUL-padded uint8 rows -> Python bytes (up to first NUL)."""
    out = []
    for row in np.asarray(rows):
        b = row.tobytes()
        i = b.find(b"\x00")
        out.append(b if i < 0 else b[:i])
    return out


def strings_to_rows(strings: list[bytes], width: int) -> np.ndarray:
    """Host-side: byte strings -> NUL-padded uint8 rows, truncated to width."""
    out = np.zeros((len(strings), width), dtype=np.uint8)
    for i, s in enumerate(strings):
        s = s[:width]
        out[i, : len(s)] = np.frombuffer(s, dtype=np.uint8)
    return out
