"""Locust-TPU: a TPU-native distributed MapReduce framework.

A brand-new JAX/XLA/Pallas implementation of the capability surface of
wuyan33/Locust (a CUDA + TCP MapReduce engine): fixed-width KV
map -> shuffle -> reduce with device-side string processing, a staged CLI,
and a multi-host distributed mode where the shuffle is an ICI all-to-all
over a ``jax.sharding.Mesh`` and the final combine is a ``psum``.

See SURVEY.md for the structural analysis of the reference this framework
rebuilds, layer by layer.
"""

__version__ = "0.1.0"

# Deliberately light — and jax-free: entrypoints must be able to read
# config (e.g. config.machine_cache_dir for JAX_COMPILATION_CACHE_DIR)
# BEFORE their first `import jax`, since jax snapshots env vars at import.
# The two jax-heavy re-exports resolve lazily (PEP 562).
from locust_tpu.config import (  # noqa: F401
    DEFAULT_CONFIG,
    DELIMITERS,
    SORT_MODES,
    EngineConfig,
)

_LAZY = {
    "KVBatch": ("locust_tpu.core.kv", "KVBatch"),
    "StreamingCorpus": ("locust_tpu.io.loader", "StreamingCorpus"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'locust_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
