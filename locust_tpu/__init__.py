"""Locust-TPU: a TPU-native distributed MapReduce framework.

A brand-new JAX/XLA/Pallas implementation of the capability surface of
wuyan33/Locust (a CUDA + TCP MapReduce engine): fixed-width KV
map -> shuffle -> reduce with device-side string processing, a staged CLI,
and a multi-host distributed mode where the shuffle is an ICI all-to-all
over a ``jax.sharding.Mesh`` and the final combine is a ``psum``.

See SURVEY.md for the structural analysis of the reference this framework
rebuilds, layer by layer.
"""

__version__ = "0.1.0"

# Deliberately light: heavy modules (engine, apps, parallel) import
# lazily from their own paths so `python -m locust_tpu --help` stays fast.
from locust_tpu.config import (  # noqa: F401
    DEFAULT_CONFIG,
    DELIMITERS,
    SORT_MODES,
    EngineConfig,
)
from locust_tpu.core.kv import KVBatch  # noqa: F401
from locust_tpu.io.loader import StreamingCorpus  # noqa: F401
