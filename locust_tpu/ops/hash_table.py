"""Sort-free Process+Reduce: multi-probe hash-table aggregation.

The reference's Process stage exists to group equal keys so a segment
pass can total them (thrust sort at reference MapReduce/src/main.cu:414-415,
94% of its GPU runtime) — but per-key totals do not inherently need a
sort.  This module aggregates an emit batch directly into a fixed-size
open-addressed hash table with XLA scatters:

  per probe round (double hashing, ``slot_p = (h1 + p*(h2|1)) % T``):
    1. rows COMPETE for their slot by scatter-min over a 31-bit folded
       hash (the winner per slot is deterministic: smallest folded);
    2. winners whose slot is EMPTY write their full key lanes
       (same-key writers write identical bytes, so duplicate-index
       write order cannot matter; two DISTINCT keys can both "win" only
       on a 31-bit folded-hash collision, and XLA does not promise the
       duplicate-index row write is atomic — the slot could then hold an
       interleaved chimera matching neither writer, so step 3's matched
       flag is what ultimately marks a slot used);
    3. every unresolved row gathers its slot's stored lanes and compares
       ALL lanes — a row is resolved only by an exact full-key match, so
       hash collisions can never merge distinct keys (same invariant as
       the sort modes' boundary compare, process_stage.py);
    4. resolved rows scatter-combine their values into the slot
       (sum/min/max — the same normalized combiners as segment_reduce).

  Rows still unresolved after all rounds (probe exhaustion under high
  load, or a pathological folded-hash fight) are returned as a mask; the
  engine routes them through the EXACT stock sort+segment-reduce
  fallback (engine.py fold path), so the mode degrades to today's
  behavior rather than to a wrong answer.

Traffic: ~4 rounds x ~11 row-sized gather/scatter sweeps vs the
incumbent sort's ~21 passes x 6 operands x read+write — roughly 6x less
HBM movement at the bench shape, IF the backend's duplicate-index
scatter is not serialized (scripts/bench_sort_variants.py variant J
measures exactly that primitive; CPU: 19x).

Empty-slot sentinel: lane 0 == 0.  A valid emit's key starts with a
non-delimiter, non-NUL byte packed big-endian into lane 0, so lane 0 of
any real key is >= 0x01000000; rows violating this (impossible via the
tokenizer, but cheap to guard) are simply left to the exact fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from locust_tpu.config import HASHT_PROBES as DEFAULT_PROBES
from locust_tpu.core import packing
from locust_tpu.core.kv import KVBatch

# DEFAULT_PROBES (config.HASHT_PROBES, default 4): at the bench load
# factor (~5.6k distinct in 65,536 slots ≈ 0.09) the expected unresolved
# fraction after 4 rounds is ~0.09^4 ≈ 7e-5 of KEYS — in practice zero,
# so the engine's fallback `lax.cond` almost never fires.

# Associative combiners only: "count" is rejected at the aggregate_exact
# gate (it is not a monoid over its own outputs — a mixed batch of raw
# emits and pre-aggregated table rows has no correct single-pass count);
# normalize_combine lowers it to emit-1 + "sum" before any fold.
_COMBINE_INIT = {"sum": 0, "min": 2**31 - 1, "max": -(2**31)}


def hash_aggregate(
    batch: KVBatch,
    out_size: int,
    combine: str = "sum",
    probes: int = DEFAULT_PROBES,
    table: KVBatch | None = None,
) -> tuple[KVBatch, jax.Array, jax.Array]:
    """Aggregate ``batch`` into an ``out_size``-slot table without sorting.

    With ``table`` (a KVBatch of capacity ``out_size`` produced by a
    previous hasht fold), aggregation is INCREMENTAL: prior keys keep
    their slots and batch rows combine into them, so a fold's scatter
    traffic scales with the BLOCK, not table+block — the concat +
    full-table re-aggregation the sort modes pay per fold disappears.
    Slot stability across folds follows from the probe invariant: a key
    resolved at round r found every earlier slot of its sequence
    occupied, and slots never empty out, so later rows of that key walk
    the same sequence to the same slot.

    EXCEPTION — keys that entered the table via the exactness ladder's
    residual/full branches sit at slots OFF their probe sequence; later
    batch rows of such a key cannot match there and may claim a second
    slot (or re-residual).  That SPLITS the key's total across rows —
    still exact, because every consumer merges duplicate key rows with
    the combine op (``finalize_host_pairs``; the ladder's own ``full``
    branch and the sort-mode merges consolidate them too) — but the
    ``used``/distinct count then OVERCOUNTS, so capacity truncation
    stays conservative (may flag early, never silently drops).

    Returns ``(table, used_count, unresolved_mask)``:

    * ``table`` — KVBatch of capacity ``out_size``; used slots hold one
      distinct key each with its combined value (device order is slot
      order, like the sort modes' hash order — host finalize re-sorts);
    * ``used_count`` — number of occupied slots == distinct keys
      resolved (every resolved key occupies exactly ONE slot: all rows
      of a key share (h1, h2), hence the same probe sequence and the
      same resolution round);
    * ``unresolved_mask`` — [N] bool, rows the caller must still fold in
      exactly (engine.py routes them through sort+segment-reduce).
    """
    if combine not in _COMBINE_INIT:
        raise ValueError(f"combine must be one of {sorted(_COMBINE_INIT)}")
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n_lanes = lanes.shape[-1]
    T = out_size

    h1, h2 = packing.hash_pair(lanes)
    folded = h1 >> 1                       # < 0x7FFFFFFF < the empty sentinel
    step = h2 | jnp.uint32(1)              # odd: full cycle when T is 2^k
    sentinel = jnp.uint32(0xFFFFFFFF)

    # Belt-and-braces: a "valid" row whose lane0 is 0 would alias the
    # empty-slot sentinel; leave such rows to the exact fallback.
    unresolved = valid & (lanes[:, 0] != 0)

    if table is None:
        stored_lanes = jnp.zeros((T + 1, n_lanes), jnp.uint32)  # T = dump
        acc = jnp.full((T + 1,), _COMBINE_INIT[combine], jnp.int32)
    else:
        if table.size != T:
            raise ValueError(
                f"incremental table capacity {table.size} != out_size {T}"
            )
        # Existing slots keep their keys/values; EMPTY slots must hold
        # the combine identity (table.values stores 0 there), and a
        # stored key in an invalid slot must not block claims — masked
        # to the empty sentinel pattern.
        stored_lanes = jnp.concatenate(
            [
                jnp.where(table.valid[:, None], table.key_lanes, 0),
                jnp.zeros((1, n_lanes), jnp.uint32),
            ]
        )
        acc = jnp.concatenate(
            [
                jnp.where(
                    table.valid, table.values,
                    jnp.int32(_COMBINE_INIT[combine]),
                ),
                jnp.full((1,), _COMBINE_INIT[combine], jnp.int32),
            ]
        )
    # A slot counts as used only once some row has FULL-KEY-matched it.
    # Written-but-never-matched slots are possible in exactly one case:
    # two distinct keys collide on the 31-bit folded hash, both win the
    # same empty slot in the same round, and the duplicate-index row
    # write interleaves per element (XLA leaves this unspecified) — the
    # stored bytes then match neither writer.  Without this flag such a
    # slot would surface as a phantom output row holding the combine
    # init; with it, the slot is excluded and both writers resolve via
    # later probes or the exact fallback ladder.  Slots carried in from
    # a previous incremental fold were matched when first inserted.
    if table is None:
        matched_slot = jnp.zeros((T + 1,), bool)
    else:
        matched_slot = jnp.concatenate(
            [table.valid, jnp.zeros((1,), bool)]
        )

    for p in range(probes):
        slot = ((h1 + jnp.uint32(p) * step) % jnp.uint32(T)).astype(jnp.int32)
        # 1. Compete: smallest folded hash wins the slot this round.
        claim = jnp.full((T,), sentinel).at[slot].min(
            jnp.where(unresolved, folded, sentinel), mode="drop"
        )
        won = unresolved & (claim[slot] == folded)
        # 2. Winners write their key into EMPTY slots (dump row for the
        #    rest keeps the scatter shape static).
        empty = stored_lanes[:T, 0] == 0
        writer = won & empty[slot]
        stored_lanes = stored_lanes.at[
            jnp.where(writer, slot, T)
        ].set(lanes, mode="drop")
        # 3. Resolve by FULL-key equality with whatever the slot holds
        #    (this round's winner, or an earlier round's occupant).
        match = unresolved & jnp.all(
            stored_lanes[slot] == lanes, axis=-1
        )
        # 4. Combine resolved values into the slot (dump row otherwise).
        vslot = jnp.where(match, slot, T)
        matched_slot = matched_slot.at[vslot].set(True, mode="drop")
        if combine == "sum":
            acc = acc.at[vslot].add(values, mode="drop")
        elif combine == "min":
            acc = acc.at[vslot].min(values, mode="drop")
        else:
            acc = acc.at[vslot].max(values, mode="drop")
        unresolved = unresolved & ~match

    used = (stored_lanes[:T, 0] != 0) & matched_slot[:T]
    table = KVBatch(
        key_lanes=stored_lanes[:T],
        values=jnp.where(used, acc[:T], 0),
        valid=used,
    )
    # Rows guarded out of the probe rounds (lane0 == 0, sentinel alias)
    # re-enter the returned mask: the CONTRACT is that everything not in
    # the table comes back as unresolved, so no caller path can lose
    # them silently.
    unresolved = unresolved | (valid & (lanes[:, 0] == 0))
    return table, jnp.sum(used.astype(jnp.int32)), unresolved


# Residual-buffer capacity for ``place_residual``: unresolved rows are
# compacted into this many slots and sorted there (a 4096-row sort is
# milliseconds).  More unresolved rows than this sends the engine to the
# full-sort fallback instead — with 4 probes at sane load factors that is
# astronomically rare, but the bound is what keeps the mode EXACT.
RESIDUAL_CAP = 4096


def place_residual(
    table: KVBatch,
    used: jax.Array,
    batch: KVBatch,
    unresolved: jax.Array,
    combine: str = "sum",
) -> tuple[KVBatch, jax.Array]:
    """Exactly fold ``unresolved`` rows of ``batch`` into ``table``.

    The cheap middle path between "all rows resolved" and the full-sort
    fallback: probe exhaustion strands only a handful of rows (a key that
    deterministically loses every probe round re-fails every fold, so
    this path is on the steady-state fold of real corpora), and sorting
    a RESIDUAL_CAP-row buffer costs milliseconds where re-sorting the
    whole (table + emits) batch would cost more than the sort mode this
    mode exists to beat.

    Caller guarantees ``sum(unresolved) <= RESIDUAL_CAP``.  Steps:

      1. cumsum-compact the unresolved rows into a RESIDUAL_CAP buffer;
      2. group+total the buffer with the stock sort + segment reduce.
         A residual key failed the full-lane match at every PROBE slot,
         so its total is disjoint from any probe-resolved slot; with
         incremental folds it may still duplicate a row placed off its
         probe sequence by an EARLIER ladder descent — exact regardless,
         because all consumers merge duplicate key rows (see
         hash_aggregate's incremental exception note);
      3. place the k-th residual key into the k-th empty slot (rank maps
         built with one cumsum each).  Keys beyond the empty-slot count
         are dropped but still counted in the returned distinct total,
         so capacity truncation stays observable exactly like the sort
         path's head-slice (reduce_stage.segment_reduce_into).

    Returns ``(merged_table, distinct_total)``.
    """
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    T = table.size
    n_lanes = table.key_lanes.shape[-1]
    cap = RESIDUAL_CAP

    # 1. Compact unresolved rows into the small buffer (dump row = cap).
    pos = jnp.cumsum(unresolved.astype(jnp.int32)) - 1
    idx = jnp.where(unresolved & (pos < cap), pos, cap)
    rlanes = jnp.zeros((cap + 1, n_lanes), jnp.uint32).at[idx].set(
        batch.key_lanes, mode="drop"
    )
    rvals = jnp.zeros((cap + 1,), jnp.int32).at[idx].set(
        batch.values, mode="drop"
    )
    rvalid = jnp.zeros((cap + 1,), bool).at[idx].set(
        unresolved, mode="drop"
    )
    rbatch = KVBatch(rlanes[:cap], rvals[:cap], rvalid[:cap])

    # 2. Group + total the residual keys (tiny sort).
    rtab, rdist = segment_reduce_into(
        sort_and_compact(rbatch, "hashp1"), cap, combine
    )

    # 3. k-th residual key -> k-th empty slot.
    empty = ~table.valid
    erank = jnp.cumsum(empty.astype(jnp.int32)) - 1
    slot_by_rank = jnp.zeros((cap + 1,), jnp.int32).at[
        jnp.where(empty & (erank < cap), erank, cap)
    ].set(jnp.arange(T, dtype=jnp.int32), mode="drop")[:cap]
    n_empty = T - used
    placeable = rtab.valid & (
        jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(n_empty, cap)
    )
    target = jnp.where(placeable, slot_by_rank, T)  # dump row = T

    lanes_pad = jnp.concatenate(
        [table.key_lanes, jnp.zeros((1, n_lanes), jnp.uint32)]
    ).at[target].set(rtab.key_lanes, mode="drop")
    vals_pad = jnp.concatenate(
        [table.values, jnp.zeros((1,), jnp.int32)]
    ).at[target].set(rtab.values, mode="drop")
    valid_pad = jnp.concatenate(
        [table.valid, jnp.zeros((1,), bool)]
    ).at[target].set(placeable, mode="drop")

    merged = KVBatch(lanes_pad[:T], vals_pad[:T], valid_pad[:T])
    return merged, used + rdist


def combine_or_passthrough(
    batch: KVBatch, combine: str, probes: int = 2
) -> KVBatch:
    """Opportunistic pre-aggregation with an O(n) worst case — no sort.

    For the mesh LOCAL COMBINER (shuffle.local_step): aggregation there
    is an optimization, not a contract — ungrouped rows ship fine
    (partition is order-agnostic and every destination re-reduces), so
    when probing fails the right fallback is not a sort but a cheap
    compaction: resolved table rows and still-raw unresolved rows are
    cumsum-packed into one batch-sized output (used + n_unres <= valid
    rows <= batch.size, so nothing can be dropped).  Worst case =
    ``probes`` scatter sweeps + one O(n) compaction, the bound the
    probes=2 choice at the call site is justified by.

    Same associativity gate as aggregate_exact ("count" must be lowered
    first — resolved slots hold partial sums that ship as single rows).
    """
    if combine == "count":
        raise ValueError(
            "combine_or_passthrough cannot take combine='count'; lower it "
            "via reduce_stage.normalize_combine to emit-1 + 'sum' first"
        )
    N = batch.size
    n_lanes = batch.key_lanes.shape[-1]
    table, used, unresolved = hash_aggregate(batch, N, combine, probes=probes)

    def fast(_):
        return table

    def passthrough(_):
        rank_t = jnp.cumsum(table.valid.astype(jnp.int32)) - 1
        dest_t = jnp.where(table.valid, rank_t, N)
        lanes = jnp.zeros((N + 1, n_lanes), jnp.uint32).at[dest_t].set(
            table.key_lanes, mode="drop"
        )
        vals = jnp.zeros((N + 1,), jnp.int32).at[dest_t].set(
            table.values, mode="drop"
        )
        valid = jnp.zeros((N + 1,), bool).at[dest_t].set(
            table.valid, mode="drop"
        )
        rank_u = jnp.cumsum(unresolved.astype(jnp.int32)) - 1 + used
        dest_u = jnp.where(unresolved, rank_u, N)
        lanes = lanes.at[dest_u].set(batch.key_lanes, mode="drop")
        vals = vals.at[dest_u].set(batch.values, mode="drop")
        valid = valid.at[dest_u].set(unresolved, mode="drop")
        return KVBatch(lanes[:N], vals[:N], valid[:N])

    return jax.lax.cond(
        jnp.sum(unresolved.astype(jnp.int32)) == 0,
        fast,
        passthrough,
        operand=None,
    )


def reduce_into(
    batch: KVBatch,
    out_size: int,
    combine: str,
    sort_mode: str,
) -> tuple[KVBatch, jax.Array]:
    """THE fold-level reduce dispatch: one place decides sort vs hasht.

    Every bounded-table fold site (engine block fold, mesh per-shard
    merge, hierarchical cross-slice combine) calls this instead of
    hand-rolling the ``if sort_mode == "hasht"`` branch — a new
    fold-level strategy lands here once, not in four files.  (The mesh
    LOCAL COMBINER is the one deliberate exception: aggregation there is
    optional, so it uses ``combine_or_passthrough``.)
    """
    if sort_mode == "hasht":
        return aggregate_exact(batch, out_size, combine)
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    return segment_reduce_into(
        sort_and_compact(batch, sort_mode), out_size, combine
    )


def fold_into(
    acc: KVBatch,
    batch: KVBatch,
    out_size: int,
    combine: str,
    sort_mode: str,
) -> tuple[KVBatch, jax.Array]:
    """Fold a batch of NEW rows into an existing bounded table.

    The accumulator-merge counterpart of :func:`reduce_into` — call
    this when ``acc`` is itself the output of a previous fold at the
    same ``(out_size, combine, sort_mode)``:

    * sort modes: ``concat(acc, batch)`` then one sort + segment reduce
      — the table IS sorted back in with the emits (one fused sort does
      grouping and merge);
    * "hasht": ``aggregate_exact`` over the same concat — a per-fold
      REBUILD, deliberately NOT the incremental
      ``hash_aggregate(table=acc)`` mode.  Measured round 5 (CPU bench,
      hamlet-repeated 8MB): incremental wiring LOST — 8.1 -> 6.5 MB/s
      and distinct drifted 5608 -> 5631, because a key the probe rounds
      strand (all its slots taken; ~2 keys on hamlet) is placed OFF its
      probe sequence by the residual branch and then accumulates one
      duplicate row EVERY subsequent fold (linear growth; rebuild keeps
      exactly one row).  The distinct drift would additionally poison
      bench's lossless-side A/B guard (max-distinct anchor).  Wiring
      incremental for real needs a slot-stable STASH side-table for
      stranded keys — future work; the capability + its exactness
      contract stay tested at the hash_aggregate level.
    """
    if sort_mode == "hasht":
        return aggregate_exact(KVBatch.concat(acc, batch), out_size, combine)
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    return segment_reduce_into(
        sort_and_compact(KVBatch.concat(acc, batch), sort_mode),
        out_size,
        combine,
    )


def aggregate_exact(
    batch: KVBatch,
    out_size: int,
    combine: str = "sum",
    probes: int | None = None,
    into: KVBatch | None = None,
) -> tuple[KVBatch, jax.Array]:
    """The full sort-free fold with its exactness ladder, as one call.

    ``into`` (a table from a previous hasht fold at the same shape)
    switches :func:`hash_aggregate` to its incremental mode; the ladder
    below is unchanged — its ``small``/``full`` branches already merge
    residual rows into an arbitrary existing table.

    ``hash_aggregate`` + the three-way unresolved-row ladder the engine's
    "hasht" fold documents (engine.fold_block_hasht): 0 unresolved → the
    table is the answer; <= RESIDUAL_CAP → ``place_residual``'s small
    compact-sort-place path; more → the full stock sort fallback.  The
    single shared implementation for every fold-level consumer (the
    single-device engine and the mesh shuffle's per-shard merge) — no
    collectives inside, so it traces under ``shard_map`` with per-shard
    branch selection.

    Returns ``(table[out_size], distinct)`` with the pre-capacity
    distinct count (truncation observable, like segment_reduce_into).
    """
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    if combine == "count":
        # Refuse, don't corrupt: "count" is not a monoid over its own
        # outputs (normalize_combine, reduce_stage.py), and this ladder's
        # fallback branches re-reduce batches that may contain
        # PRE-AGGREGATED table rows — a second "count" over those counts
        # rows, not occurrences (verified: wrong totals at >RESIDUAL_CAP
        # unresolved).  Callers must lower count -> emit-1 + "sum" at the
        # leaves first; every engine/mesh fold site already does.
        raise ValueError(
            "aggregate_exact cannot take combine='count' (not associative "
            "over partial tables); lower it via "
            "reduce_stage.normalize_combine to emit-1 + 'sum' first"
        )
    table, used, unresolved = hash_aggregate(
        batch, out_size, combine,
        probes=DEFAULT_PROBES if probes is None else probes,
        table=into,
    )
    n_unres = jnp.sum(unresolved.astype(jnp.int32))

    def fast(_):
        return table, used

    def small(_):
        return place_residual(table, used, batch, unresolved, combine)

    def full(_):
        resid = KVBatch(batch.key_lanes, batch.values, unresolved)
        return segment_reduce_into(
            sort_and_compact(KVBatch.concat(table, resid), "hashp1"),
            out_size,
            combine,
        )

    return jax.lax.cond(
        n_unres == 0,
        fast,
        lambda op: jax.lax.cond(n_unres <= RESIDUAL_CAP, small, full, op),
        operand=None,
    )
