"""Sort-free Process+Reduce: multi-probe hash-table aggregation.

The reference's Process stage exists to group equal keys so a segment
pass can total them (thrust sort at reference MapReduce/src/main.cu:414-415,
94% of its GPU runtime) — but per-key totals do not inherently need a
sort.  This module aggregates an emit batch directly into a fixed-size
open-addressed hash table with XLA scatters:

  per probe round (double hashing, ``slot_p = (h1 + p*(h2|1)) % T``):
    1. rows COMPETE for their slot by scatter-min over a 31-bit folded
       hash (the winner per slot is deterministic: smallest folded);
    2. winners whose slot is EMPTY write their full key lanes
       (same-key writers write identical bytes, so duplicate-index
       write order cannot matter; two DISTINCT keys can both "win" only
       on a 31-bit folded-hash collision, and XLA does not promise the
       duplicate-index row write is atomic — the slot could then hold an
       interleaved chimera matching neither writer, so step 3's matched
       flag is what ultimately marks a slot used);
    3. every unresolved row gathers its slot's stored lanes and compares
       ALL lanes — a row is resolved only by an exact full-key match, so
       hash collisions can never merge distinct keys (same invariant as
       the sort modes' boundary compare, process_stage.py);
    4. resolved rows scatter-combine their values into the slot
       (sum/min/max — the same normalized combiners as segment_reduce).

  Rows still unresolved after all rounds (probe exhaustion under high
  load, or a pathological folded-hash fight) are returned as a mask; the
  engine routes them through the EXACT stock sort+segment-reduce
  fallback (engine.py fold path), so the mode degrades to today's
  behavior rather than to a wrong answer.

Traffic: ~4 rounds x ~11 row-sized gather/scatter sweeps vs the
incumbent sort's ~21 passes x 6 operands x read+write — roughly 6x less
HBM movement at the bench shape, IF the backend's duplicate-index
scatter is not serialized (scripts/bench_sort_variants.py variant J
measures exactly that primitive; CPU: 19x).  On TPU v5e the scatter runs
but costs ~2.2x the sort-family primitive (J 107.6 ms, ledger ts
1785523898), so the value combine has a second spelling: a one-hot bf16
contraction on the systolic MXU (``mxu_scatter_add``, the productized
K_mxu_hist probe — 52.0 ms / 1.6 s compile at the same shape), selected
per fold by ``scatter_impl`` / engine sort mode "hasht-mxu"
(config.HASHT_FAMILY).  Both spellings produce BIT-identical tables;
roofline treatment in utils/roofline.py (one-hot bytes vs scatter bytes).

Empty-slot sentinel: lane 0 == 0.  A valid emit's key starts with a
non-delimiter, non-NUL byte packed big-endian into lane 0, so lane 0 of
any real key is >= 0x01000000; rows violating this (impossible via the
tokenizer, but cheap to guard) are simply left to the exact fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from locust_tpu.config import (
    HASHT_FAMILY,
    HASHT_MXU_CHUNK,
    HASHT_PROBES as DEFAULT_PROBES,
    hasht_mxu_grid,
)
from locust_tpu.core import packing
from locust_tpu.core.kv import KVBatch

# How the value-combine scatter of the probe loop is spelled, keyed by the
# sort mode that selected this fold (config.HASHT_FAMILY):
#   "xla" — ``.at[slot].add`` duplicate-index scatter (the incumbent;
#           measured ~2.2x the sort-family primitive on v5e, ledger
#           J_scatter 107.6 ms vs I 50.7 at the fold shape);
#   "mxu" — the same sum as one-hot bf16 contractions on the systolic MXU
#           (``mxu_scatter_add``; the K_mxu_hist probe measured 52.0 ms
#           with a 1.6 s compile at the identical shape).
# The claim (scatter-min over folded hashes) and key-lane writes stay XLA
# scatters under BOTH impls — the MXU speaks only +, and those steps are
# what make the fold exact, not what prices it.
SCATTER_IMPLS = ("xla", "mxu")


def scatter_impl_for(sort_mode: str) -> str:
    """The fold family's mode -> combine-scatter spelling map (the one
    place "hasht-mxu" is interpreted; engines pass sort_mode strings)."""
    return "mxu" if sort_mode == "hasht-mxu" else "xla"


def mxu_scatter_add(
    slot: jax.Array,
    values: jax.Array,
    mask: jax.Array,
    out_size: int,
    chunk: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Duplicate-index scatter-add spelled as one-hot MXU contractions.

    Returns ``(sums, hit)``: ``sums[t]`` is the int32 sum (mod 2^32 —
    BIT-identical to XLA's wrapping ``.at[t].add``) of ``values`` over
    masked rows with ``slot == t``, and ``hit[t]`` is True iff any masked
    row landed on ``t``.  Rows with ``mask`` False (or an out-of-grid
    slot) contribute nothing.

    Formulation (productized from scripts/bench_sort_variants.py
    ``variant_k``): decompose ``slot = hi * t_lo + lo`` on the
    ``config.hasht_mxu_grid`` and accumulate
    ``hist[w, hi, lo] = sum_n W[n, w] * onehot_hi[n, hi] * onehot_lo[n, lo]``
    as ONE ``[t_hi * 5, n_chunk] x [n_chunk, t_lo]`` bf16 contraction per
    chunk.  Exactness, unlike the probe's bf16-cast of raw values, is
    unconditional: the 5 weight planes are the value's four unsigned
    8-bit limbs plus the hit count — every operand entry is <= 255 and
    hence bf16-exact, per-chunk partials accumulate in fp32 where a
    slot's limb sum stays < 255 * chunk <= 2^24 (config.HASHT_MXU_CHUNK's
    validated ceiling), partials then convert to uint32 and accumulate
    with wraparound, and the final limb recombination is mod-2^32
    arithmetic — the same ring int32 scatter-add lives in.

    The n axis is chunked (``lax.scan``) so the materialized one-hot
    operands stay ~``chunk * (5 * t_hi + t_lo) * 2`` bytes regardless of
    the fold's row count.
    """
    t_hi, t_lo = hasht_mxu_grid(out_size)
    n = slot.shape[0]
    chunk = HASHT_MXU_CHUNK if chunk is None else chunk
    if not 1 <= chunk <= 65536:
        # The SAME exactness ceiling config validates for the env knob:
        # a slot's per-chunk limb partial must stay < 255 * chunk <= 2^24
        # or the fp32 einsum accumulation rounds and the bit-identity
        # contract silently breaks for direct callers.
        raise ValueError(
            f"chunk must be in [1, 65536] (fp32 partial-sum exactness "
            f"bound 2^24/255), got {chunk}"
        )

    # 5 weight planes, all bf16-exact: value limbs 0..3 (unsigned view of
    # the int32 — the limb recombination below restores wrapping-sum
    # semantics for negative values too) + the hit count.
    w_u = jax.lax.bitcast_convert_type(
        values.astype(jnp.int32), jnp.uint32
    )
    w_u = jnp.where(mask, w_u, jnp.uint32(0))
    planes = [(w_u >> jnp.uint32(8 * b)) & jnp.uint32(0xFF) for b in range(4)]
    planes.append(mask.astype(jnp.uint32))
    weights = jnp.stack(planes, axis=-1).astype(jnp.bfloat16)   # [n, 5]
    s32 = slot.astype(jnp.int32)
    hi = s32 // t_lo
    lo = s32 % t_lo

    def hist_chunk(hi_c, lo_c, w_c):
        # One-hot rows land in their grid cell; a masked or out-of-grid
        # row produces an all-zero one-hot / zero weight either way.
        oh_hi = (
            hi_c[:, None] == jnp.arange(t_hi, dtype=jnp.int32)[None, :]
        ).astype(jnp.bfloat16)
        oh_lo = (
            lo_c[:, None] == jnp.arange(t_lo, dtype=jnp.int32)[None, :]
        ).astype(jnp.bfloat16)
        lhs = (oh_hi[:, :, None] * w_c[:, None, :]).reshape(
            hi_c.shape[0], t_hi * 5
        )
        part = jnp.einsum(
            "nm,nl->ml", lhs, oh_lo, preferred_element_type=jnp.float32
        ).reshape(t_hi, 5, t_lo)
        # fp32 partials are exact integers < 2^24 here; uint32 conversion
        # is therefore exact, and uint32 accumulation wraps mod 2^32.
        return part.astype(jnp.uint32)

    if n <= chunk:
        acc = hist_chunk(hi, lo, weights)
    else:
        n_chunks = -(-n // chunk)
        pad = n_chunks * chunk - n
        hi_p = jnp.pad(hi, (0, pad), constant_values=-1)  # off-grid: no-op
        lo_p = jnp.pad(lo, (0, pad), constant_values=-1)
        w_p = jnp.pad(weights, ((0, pad), (0, 0)))

        def body(carry, xs):
            h, l, w = xs
            return carry + hist_chunk(h, l, w), None

        acc, _ = jax.lax.scan(
            body,
            jnp.zeros((t_hi, 5, t_lo), jnp.uint32),
            (
                hi_p.reshape(n_chunks, chunk),
                lo_p.reshape(n_chunks, chunk),
                w_p.reshape(n_chunks, chunk, 5),
            ),
        )

    sums_u = (
        acc[:, 0]
        + (acc[:, 1] << jnp.uint32(8))
        + (acc[:, 2] << jnp.uint32(16))
        + (acc[:, 3] << jnp.uint32(24))
    )
    sums = jax.lax.bitcast_convert_type(
        sums_u.reshape(-1)[:out_size], jnp.int32
    )
    hit = acc[:, 4].reshape(-1)[:out_size] > 0
    return sums, hit

# DEFAULT_PROBES (config.HASHT_PROBES, default 4): at the bench load
# factor (~5.6k distinct in 65,536 slots ≈ 0.09) the expected unresolved
# fraction after 4 rounds is ~0.09^4 ≈ 7e-5 of KEYS — in practice zero,
# so the engine's fallback `lax.cond` almost never fires.

# Associative combiners only: "count" is rejected at the aggregate_exact
# gate (it is not a monoid over its own outputs — a mixed batch of raw
# emits and pre-aggregated table rows has no correct single-pass count);
# normalize_combine lowers it to emit-1 + "sum" before any fold.
_COMBINE_INIT = {"sum": 0, "min": 2**31 - 1, "max": -(2**31)}


def hash_aggregate(
    batch: KVBatch,
    out_size: int,
    combine: str = "sum",
    probes: int = DEFAULT_PROBES,
    table: KVBatch | None = None,
    scatter_impl: str = "xla",
) -> tuple[KVBatch, jax.Array, jax.Array]:
    """Aggregate ``batch`` into an ``out_size``-slot table without sorting.

    ``scatter_impl`` selects how step 4's value combine is spelled (see
    ``SCATTER_IMPLS``): "xla" is the duplicate-index scatter, "mxu" the
    one-hot contraction — tables are BIT-identical either way (the "mxu"
    sum is exact mod 2^32, the ring int32 scatter-add lives in).  "mxu"
    applies to combine="sum" only; min/max have no matmul spelling and
    keep the XLA scatter (trivially identical).  Steps 1-3 (claim, key
    write, full-lane verify) are unchanged under both impls.

    With ``table`` (a KVBatch of capacity ``out_size`` produced by a
    previous hasht fold), aggregation is INCREMENTAL: prior keys keep
    their slots and batch rows combine into them, so a fold's scatter
    traffic scales with the BLOCK, not table+block — the concat +
    full-table re-aggregation the sort modes pay per fold disappears.
    Slot stability across folds follows from the probe invariant: a key
    resolved at round r found every earlier slot of its sequence
    occupied, and slots never empty out, so later rows of that key walk
    the same sequence to the same slot.

    EXCEPTION — keys that entered the table via the exactness ladder's
    residual/full branches sit at slots OFF their probe sequence; later
    batch rows of such a key cannot match there and may claim a second
    slot (or re-residual).  That SPLITS the key's total across rows —
    still exact, because every consumer merges duplicate key rows with
    the combine op (``finalize_host_pairs``; the ladder's own ``full``
    branch and the sort-mode merges consolidate them too) — but the
    ``used``/distinct count then OVERCOUNTS, so capacity truncation
    stays conservative (may flag early, never silently drops).

    Returns ``(table, used_count, unresolved_mask)``:

    * ``table`` — KVBatch of capacity ``out_size``; used slots hold one
      distinct key each with its combined value (device order is slot
      order, like the sort modes' hash order — host finalize re-sorts);
    * ``used_count`` — number of occupied slots == distinct keys
      resolved (every resolved key occupies exactly ONE slot: all rows
      of a key share (h1, h2), hence the same probe sequence and the
      same resolution round);
    * ``unresolved_mask`` — [N] bool, rows the caller must still fold in
      exactly (engine.py routes them through sort+segment-reduce).
    """
    if combine not in _COMBINE_INIT:
        raise ValueError(f"combine must be one of {sorted(_COMBINE_INIT)}")
    if scatter_impl not in SCATTER_IMPLS:
        raise ValueError(
            f"scatter_impl must be one of {SCATTER_IMPLS}, got {scatter_impl!r}"
        )
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n_lanes = lanes.shape[-1]
    T = out_size

    h1, h2 = packing.hash_pair(lanes)
    folded = h1 >> 1                       # < 0x7FFFFFFF < the empty sentinel
    step = h2 | jnp.uint32(1)              # odd: full cycle when T is 2^k
    sentinel = jnp.uint32(0xFFFFFFFF)

    # Belt-and-braces: a "valid" row whose lane0 is 0 would alias the
    # empty-slot sentinel; leave such rows to the exact fallback.
    unresolved = valid & (lanes[:, 0] != 0)

    if table is None:
        stored_lanes = jnp.zeros((T + 1, n_lanes), jnp.uint32)  # T = dump
        acc = jnp.full((T + 1,), _COMBINE_INIT[combine], jnp.int32)
    else:
        if table.size != T:
            raise ValueError(
                f"incremental table capacity {table.size} != out_size {T}"
            )
        # Existing slots keep their keys/values; EMPTY slots must hold
        # the combine identity (table.values stores 0 there), and a
        # stored key in an invalid slot must not block claims — masked
        # to the empty sentinel pattern.
        stored_lanes = jnp.concatenate(
            [
                jnp.where(table.valid[:, None], table.key_lanes, 0),
                jnp.zeros((1, n_lanes), jnp.uint32),
            ]
        )
        acc = jnp.concatenate(
            [
                jnp.where(
                    table.valid, table.values,
                    jnp.int32(_COMBINE_INIT[combine]),
                ),
                jnp.full((1,), _COMBINE_INIT[combine], jnp.int32),
            ]
        )
    # A slot counts as used only once some row has FULL-KEY-matched it.
    # Written-but-never-matched slots are possible in exactly one case:
    # two distinct keys collide on the 31-bit folded hash, both win the
    # same empty slot in the same round, and the duplicate-index row
    # write interleaves per element (XLA leaves this unspecified) — the
    # stored bytes then match neither writer.  Without this flag such a
    # slot would surface as a phantom output row holding the combine
    # init; with it, the slot is excluded and both writers resolve via
    # later probes or the exact fallback ladder.  Slots carried in from
    # a previous incremental fold were matched when first inserted.
    if table is None:
        matched_slot = jnp.zeros((T + 1,), bool)
    else:
        matched_slot = jnp.concatenate(
            [table.valid, jnp.zeros((1,), bool)]
        )

    for p in range(probes):
        slot = ((h1 + jnp.uint32(p) * step) % jnp.uint32(T)).astype(jnp.int32)
        # 1. Compete: smallest folded hash wins the slot this round.
        claim = jnp.full((T,), sentinel).at[slot].min(
            jnp.where(unresolved, folded, sentinel), mode="drop"
        )
        won = unresolved & (claim[slot] == folded)
        # 2. Winners write their key into EMPTY slots (dump row for the
        #    rest keeps the scatter shape static).
        empty = stored_lanes[:T, 0] == 0
        writer = won & empty[slot]
        stored_lanes = stored_lanes.at[
            jnp.where(writer, slot, T)
        ].set(lanes, mode="drop")
        # 3. Resolve by FULL-key equality with whatever the slot holds
        #    (this round's winner, or an earlier round's occupant).
        match = unresolved & jnp.all(
            stored_lanes[slot] == lanes, axis=-1
        )
        # 4. Combine resolved values into the slot.  "mxu" + sum: the
        #    scatter-add and the matched-slot flag both come out of one
        #    one-hot contraction (mxu_scatter_add's value limbs + hit
        #    plane); otherwise the duplicate-index scatter with a dump
        #    row.  Identical tables by construction either way.
        if scatter_impl == "mxu" and combine == "sum":
            sums, hit = mxu_scatter_add(slot, values, match, T)
            acc = acc.at[:T].add(sums)
            matched_slot = matched_slot.at[:T].set(matched_slot[:T] | hit)
        else:
            vslot = jnp.where(match, slot, T)
            matched_slot = matched_slot.at[vslot].set(True, mode="drop")
            if combine == "sum":
                acc = acc.at[vslot].add(values, mode="drop")
            elif combine == "min":
                acc = acc.at[vslot].min(values, mode="drop")
            else:
                acc = acc.at[vslot].max(values, mode="drop")
        unresolved = unresolved & ~match

    used = (stored_lanes[:T, 0] != 0) & matched_slot[:T]
    table = KVBatch(
        key_lanes=stored_lanes[:T],
        values=jnp.where(used, acc[:T], 0),
        valid=used,
    )
    # Rows guarded out of the probe rounds (lane0 == 0, sentinel alias)
    # re-enter the returned mask: the CONTRACT is that everything not in
    # the table comes back as unresolved, so no caller path can lose
    # them silently.
    unresolved = unresolved | (valid & (lanes[:, 0] == 0))
    return table, jnp.sum(used.astype(jnp.int32)), unresolved


# Residual-buffer capacity for ``place_residual``: unresolved rows are
# compacted into this many slots and sorted there (a 4096-row sort is
# milliseconds).  More unresolved rows than this sends the engine to the
# full-sort fallback instead — with 4 probes at sane load factors that is
# astronomically rare, but the bound is what keeps the mode EXACT.
RESIDUAL_CAP = 4096


def place_residual(
    table: KVBatch,
    used: jax.Array,
    batch: KVBatch,
    unresolved: jax.Array,
    combine: str = "sum",
) -> tuple[KVBatch, jax.Array]:
    """Exactly fold ``unresolved`` rows of ``batch`` into ``table``.

    The cheap middle path between "all rows resolved" and the full-sort
    fallback: probe exhaustion strands only a handful of rows (a key that
    deterministically loses every probe round re-fails every fold, so
    this path is on the steady-state fold of real corpora), and sorting
    a RESIDUAL_CAP-row buffer costs milliseconds where re-sorting the
    whole (table + emits) batch would cost more than the sort mode this
    mode exists to beat.

    Caller guarantees ``sum(unresolved) <= RESIDUAL_CAP``.  Steps:

      1. cumsum-compact the unresolved rows into a RESIDUAL_CAP buffer;
      2. group+total the buffer with the stock sort + segment reduce.
         A residual key failed the full-lane match at every PROBE slot,
         so its total is disjoint from any probe-resolved slot; with
         incremental folds it may still duplicate a row placed off its
         probe sequence by an EARLIER ladder descent — exact regardless,
         because all consumers merge duplicate key rows (see
         hash_aggregate's incremental exception note);
      3. place the k-th residual key into the k-th empty slot (rank maps
         built with one cumsum each).  Keys beyond the empty-slot count
         are dropped but still counted in the returned distinct total,
         so capacity truncation stays observable exactly like the sort
         path's head-slice (reduce_stage.segment_reduce_into).

    Returns ``(merged_table, distinct_total)``.
    """
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    T = table.size
    n_lanes = table.key_lanes.shape[-1]
    cap = RESIDUAL_CAP

    # 1. Compact unresolved rows into the small buffer (dump row = cap).
    pos = jnp.cumsum(unresolved.astype(jnp.int32)) - 1
    idx = jnp.where(unresolved & (pos < cap), pos, cap)
    rlanes = jnp.zeros((cap + 1, n_lanes), jnp.uint32).at[idx].set(
        batch.key_lanes, mode="drop"
    )
    rvals = jnp.zeros((cap + 1,), jnp.int32).at[idx].set(
        batch.values, mode="drop"
    )
    rvalid = jnp.zeros((cap + 1,), bool).at[idx].set(
        unresolved, mode="drop"
    )
    rbatch = KVBatch(rlanes[:cap], rvals[:cap], rvalid[:cap])

    # 2. Group + total the residual keys (tiny sort).
    rtab, rdist = segment_reduce_into(
        sort_and_compact(rbatch, "hashp1"), cap, combine
    )

    # 3. k-th residual key -> k-th empty slot.
    empty = ~table.valid
    erank = jnp.cumsum(empty.astype(jnp.int32)) - 1
    slot_by_rank = jnp.zeros((cap + 1,), jnp.int32).at[
        jnp.where(empty & (erank < cap), erank, cap)
    ].set(jnp.arange(T, dtype=jnp.int32), mode="drop")[:cap]
    n_empty = T - used
    placeable = rtab.valid & (
        jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(n_empty, cap)
    )
    target = jnp.where(placeable, slot_by_rank, T)  # dump row = T

    lanes_pad = jnp.concatenate(
        [table.key_lanes, jnp.zeros((1, n_lanes), jnp.uint32)]
    ).at[target].set(rtab.key_lanes, mode="drop")
    vals_pad = jnp.concatenate(
        [table.values, jnp.zeros((1,), jnp.int32)]
    ).at[target].set(rtab.values, mode="drop")
    valid_pad = jnp.concatenate(
        [table.valid, jnp.zeros((1,), bool)]
    ).at[target].set(placeable, mode="drop")

    merged = KVBatch(lanes_pad[:T], vals_pad[:T], valid_pad[:T])
    return merged, used + rdist


def combine_or_passthrough(
    batch: KVBatch, combine: str, probes: int = 2,
    scatter_impl: str = "xla",
) -> KVBatch:
    """Opportunistic pre-aggregation with an O(n) worst case — no sort.

    For the mesh LOCAL COMBINER (shuffle.local_step): aggregation there
    is an optimization, not a contract — ungrouped rows ship fine
    (partition is order-agnostic and every destination re-reduces), so
    when probing fails the right fallback is not a sort but a cheap
    compaction: resolved table rows and still-raw unresolved rows are
    cumsum-packed into one batch-sized output (used + n_unres <= valid
    rows <= batch.size, so nothing can be dropped).  Worst case =
    ``probes`` scatter sweeps + one O(n) compaction, the bound the
    probes=2 choice at the call site is justified by.

    Same associativity gate as aggregate_exact ("count" must be lowered
    first — resolved slots hold partial sums that ship as single rows).
    """
    if combine == "count":
        raise ValueError(
            "combine_or_passthrough cannot take combine='count'; lower it "
            "via reduce_stage.normalize_combine to emit-1 + 'sum' first"
        )
    N = batch.size
    n_lanes = batch.key_lanes.shape[-1]
    table, used, unresolved = hash_aggregate(
        batch, N, combine, probes=probes, scatter_impl=scatter_impl
    )

    def fast(_):
        return table

    def passthrough(_):
        rank_t = jnp.cumsum(table.valid.astype(jnp.int32)) - 1
        dest_t = jnp.where(table.valid, rank_t, N)
        lanes = jnp.zeros((N + 1, n_lanes), jnp.uint32).at[dest_t].set(
            table.key_lanes, mode="drop"
        )
        vals = jnp.zeros((N + 1,), jnp.int32).at[dest_t].set(
            table.values, mode="drop"
        )
        valid = jnp.zeros((N + 1,), bool).at[dest_t].set(
            table.valid, mode="drop"
        )
        rank_u = jnp.cumsum(unresolved.astype(jnp.int32)) - 1 + used
        dest_u = jnp.where(unresolved, rank_u, N)
        lanes = lanes.at[dest_u].set(batch.key_lanes, mode="drop")
        vals = vals.at[dest_u].set(batch.values, mode="drop")
        valid = valid.at[dest_u].set(unresolved, mode="drop")
        return KVBatch(lanes[:N], vals[:N], valid[:N])

    return jax.lax.cond(
        jnp.sum(unresolved.astype(jnp.int32)) == 0,
        fast,
        passthrough,
        operand=None,
    )


def reduce_into(
    batch: KVBatch,
    out_size: int,
    combine: str,
    sort_mode: str,
) -> tuple[KVBatch, jax.Array]:
    """THE fold-level reduce dispatch: one place decides sort vs hasht.

    Every bounded-table fold site (engine block fold, mesh per-shard
    merge, hierarchical cross-slice combine) calls this instead of
    hand-rolling the ``if sort_mode in HASHT_FAMILY`` branch — a new
    fold-level strategy lands here once, not in four files.  (The mesh
    LOCAL COMBINER is the one deliberate exception: aggregation there is
    optional, so it uses ``combine_or_passthrough``.)
    """
    if sort_mode in HASHT_FAMILY:
        return aggregate_exact(
            batch, out_size, combine,
            scatter_impl=scatter_impl_for(sort_mode),
        )
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    return segment_reduce_into(
        sort_and_compact(batch, sort_mode), out_size, combine
    )


def fold_into(
    acc: KVBatch,
    batch: KVBatch,
    out_size: int,
    combine: str,
    sort_mode: str,
) -> tuple[KVBatch, jax.Array]:
    """Fold a batch of NEW rows into an existing bounded table.

    The accumulator-merge counterpart of :func:`reduce_into` — call
    this when ``acc`` is itself the output of a previous fold at the
    same ``(out_size, combine, sort_mode)``:

    * sort modes: ``concat(acc, batch)`` then one sort + segment reduce
      — the table IS sorted back in with the emits (one fused sort does
      grouping and merge);
    * the hasht family ("hasht" / "hasht-mxu", differing only in the
      combine-scatter spelling): ``aggregate_exact`` over the same
      concat — a per-fold REBUILD, deliberately NOT the incremental
      ``hash_aggregate(table=acc)`` mode.  Measured round 5 (CPU bench,
      hamlet-repeated 8MB): incremental wiring LOST — 8.1 -> 6.5 MB/s
      and distinct drifted 5608 -> 5631, because a key the probe rounds
      strand (all its slots taken; ~2 keys on hamlet) is placed OFF its
      probe sequence by the residual branch and then accumulates one
      duplicate row EVERY subsequent fold (linear growth; rebuild keeps
      exactly one row).  The distinct drift would additionally poison
      bench's lossless-side A/B guard (max-distinct anchor).  Wiring
      incremental for real needs a slot-stable STASH side-table for
      stranded keys — future work; the capability + its exactness
      contract stay tested at the hash_aggregate level.
    """
    if sort_mode in HASHT_FAMILY:
        return aggregate_exact(
            KVBatch.concat(acc, batch), out_size, combine,
            scatter_impl=scatter_impl_for(sort_mode),
        )
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    return segment_reduce_into(
        sort_and_compact(KVBatch.concat(acc, batch), sort_mode),
        out_size,
        combine,
    )


def aggregate_exact(
    batch: KVBatch,
    out_size: int,
    combine: str = "sum",
    probes: int | None = None,
    into: KVBatch | None = None,
    scatter_impl: str = "xla",
) -> tuple[KVBatch, jax.Array]:
    """The full sort-free fold with its exactness ladder, as one call.

    ``into`` (a table from a previous hasht fold at the same shape)
    switches :func:`hash_aggregate` to its incremental mode; the ladder
    below is unchanged — its ``small``/``full`` branches already merge
    residual rows into an arbitrary existing table.

    ``scatter_impl`` reaches only the probe loop's value combine
    (:func:`hash_aggregate`).  The residual/overflow branches stay
    sort-based under BOTH impls: they exist to be exact on the handful of
    rows the probes strand, their sorts are capacity-bounded
    (RESIDUAL_CAP), and — because the probe loop's table is bit-identical
    across impls — the branch a given batch takes, and the rows it sees,
    are identical too.

    ``hash_aggregate`` + the three-way unresolved-row ladder the engine's
    "hasht" fold documents (engine.fold_block_hasht): 0 unresolved → the
    table is the answer; <= RESIDUAL_CAP → ``place_residual``'s small
    compact-sort-place path; more → the full stock sort fallback.  The
    single shared implementation for every fold-level consumer (the
    single-device engine and the mesh shuffle's per-shard merge) — no
    collectives inside, so it traces under ``shard_map`` with per-shard
    branch selection.

    Returns ``(table[out_size], distinct)`` with the pre-capacity
    distinct count (truncation observable, like segment_reduce_into).
    """
    from locust_tpu.ops.process_stage import sort_and_compact
    from locust_tpu.ops.reduce_stage import segment_reduce_into

    if combine == "count":
        # Refuse, don't corrupt: "count" is not a monoid over its own
        # outputs (normalize_combine, reduce_stage.py), and this ladder's
        # fallback branches re-reduce batches that may contain
        # PRE-AGGREGATED table rows — a second "count" over those counts
        # rows, not occurrences (verified: wrong totals at >RESIDUAL_CAP
        # unresolved).  Callers must lower count -> emit-1 + "sum" at the
        # leaves first; every engine/mesh fold site already does.
        raise ValueError(
            "aggregate_exact cannot take combine='count' (not associative "
            "over partial tables); lower it via "
            "reduce_stage.normalize_combine to emit-1 + 'sum' first"
        )
    table, used, unresolved = hash_aggregate(
        batch, out_size, combine,
        probes=DEFAULT_PROBES if probes is None else probes,
        table=into,
        scatter_impl=scatter_impl,
    )
    n_unres = jnp.sum(unresolved.astype(jnp.int32))

    def fast(_):
        return table, used

    def small(_):
        return place_residual(table, used, batch, unresolved, combine)

    def full(_):
        resid = KVBatch(batch.key_lanes, batch.values, unresolved)
        return segment_reduce_into(
            sort_and_compact(KVBatch.concat(table, resid), "hashp1"),
            out_size,
            combine,
        )

    return jax.lax.cond(
        n_unres == 0,
        fast,
        lambda op: jax.lax.cond(n_unres <= RESIDUAL_CAP, small, full, op),
        operand=None,
    )
