from locust_tpu.ops.map_stage import tokenize_block, wordcount_map  # noqa: F401
from locust_tpu.ops.process_stage import sort_and_compact  # noqa: F401
from locust_tpu.ops.reduce_stage import segment_reduce  # noqa: F401
