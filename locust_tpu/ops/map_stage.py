"""Map stage: data-parallel tokenization into fixed-slot KV emits.

TPU-native replacement for the reference's map kernel (``map()``/``kernMap``,
reference MapReduce/src/main.cu:136-159), which runs one CUDA thread per line
looping ``my_strtok_r`` sequentially and emitting ``(word, 1)`` into fixed
slot ``line*EMITS_PER_LINE + count`` with a cap of EMITS_PER_LINE=20
(main.cu:19,145-147).

Here the whole block tokenizes in one fused pass of vectorized ops:
delimiter masks -> token-start/end masks -> prefix-sum token ids -> a
one-hot reduction that turns "the e-th token of line l starts at byte w"
into a dense ``[lines, emits]`` index table -> a single gather of key bytes.
No sequential loop, no thread divergence, static shapes throughout.

The fixed-slot emit contract is preserved (same capacity semantics as
main.cu:145): each line owns ``emits_per_line`` slots; excess tokens are
dropped and counted (the reference printf-warns and drops, main.cu:141-144).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from locust_tpu.config import EngineConfig
from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch


class TokenizeResult(NamedTuple):
    keys: jax.Array      # uint8 [lines, emits_per_line, key_width]
    valid: jax.Array     # bool  [lines, emits_per_line]
    overflow: jax.Array  # int32 [] — tokens dropped beyond the per-line cap


def tokenize_block(lines: jax.Array, cfg: EngineConfig) -> TokenizeResult:
    """Tokenize a ``[block_lines, line_width]`` uint8 block.

    Pure-jnp formulation (the Pallas variant lives in ops/pallas/); XLA fuses
    the mask/compare chain into a couple of VPU passes plus one gather.
    """
    num_lines, width = lines.shape
    emits, key_w = cfg.emits_per_line, cfg.key_width

    in_token = ~bytes_ops.delimiter_mask(lines)            # [L, W]
    starts = bytes_ops.token_starts(in_token)              # [L, W]
    ends = bytes_ops.token_ends(in_token)                  # [L, W]
    tid = bytes_ops.token_ids(starts)                      # [L, W]

    # Dense slot index tables: start/end byte of the e-th token of each line.
    slot = jnp.arange(emits, dtype=jnp.int32)              # [E]
    pos = jnp.arange(width, dtype=jnp.int32)               # [W]
    start_oh = (starts[..., None] & (tid[..., None] == slot)).astype(jnp.int32)
    end_oh = (ends[..., None] & (tid[..., None] == slot)).astype(jnp.int32)
    start_idx = jnp.einsum("lwe,w->le", start_oh, pos)     # [L, E]
    end_idx = jnp.einsum("lwe,w->le", end_oh, pos)         # [L, E]

    ntok = jnp.sum(starts.astype(jnp.int32), axis=-1)      # [L]
    valid = slot[None, :] < jnp.minimum(ntok, emits)[:, None]
    # Token byte length, truncated to the key width (reference truncates via
    # its 30-byte key field, KeyValue.h:15).
    tok_len = jnp.clip(end_idx - start_idx + 1, 0, key_w)

    k = jnp.arange(key_w, dtype=jnp.int32)                 # [K]
    byte_idx = jnp.clip(start_idx[..., None] + k, 0, width - 1)  # [L, E, K]
    gathered = jnp.take_along_axis(lines[:, None, :], byte_idx, axis=-1)
    keys = jnp.where(
        (k < tok_len[..., None]) & valid[..., None], gathered, jnp.uint8(0)
    )

    overflow = jnp.sum(jnp.maximum(ntok - emits, 0))
    return TokenizeResult(keys=keys, valid=valid, overflow=overflow)


def wordcount_map(lines: jax.Array, cfg: EngineConfig) -> tuple[KVBatch, jax.Array]:
    """The WordCount map_fn: emit ``(token, 1)`` per token.

    Returns the flat emit batch ``[block_lines * emits_per_line]`` and the
    overflow counter — the analog of the reference's per-line fixed-slot emit
    table ``dev_map_kvs[MAX_EMITS]`` (main.cu:20,392).

    ``cfg.use_pallas`` selects the hand-written VMEM-resident kernel
    (ops/pallas/tokenize.py); interpret mode engages automatically off-TPU.
    """
    if cfg.use_pallas:
        from locust_tpu.ops.pallas.tokenize import tokenize_block_pallas

        interpret = jax.default_backend() != "tpu"
        keys, valid, overflow = tokenize_block_pallas(lines, cfg, interpret)
    else:
        res = tokenize_block(lines, cfg)
        keys, valid, overflow = res.keys, res.valid, res.overflow
    flat_keys = keys.reshape(-1, cfg.key_width)
    flat_valid = valid.reshape(-1)
    values = jnp.ones(flat_keys.shape[0], dtype=jnp.int32)
    return KVBatch.from_bytes(flat_keys, values, flat_valid), overflow
