"""Map stage: data-parallel tokenization into fixed-slot KV emits.

TPU-native replacement for the reference's map kernel (``map()``/``kernMap``,
reference MapReduce/src/main.cu:136-159), which runs one CUDA thread per line
looping ``my_strtok_r`` sequentially and emitting ``(word, 1)`` into fixed
slot ``line*EMITS_PER_LINE + count`` with a cap of EMITS_PER_LINE=20
(main.cu:19,145-147).

Here the whole block tokenizes in one fused pass of vectorized ops:
delimiter masks -> token-start/end masks -> prefix-sum token ids -> a
one-hot reduction that turns "the e-th token of line l starts at byte w"
into a dense ``[lines, emits]`` index table -> key-byte extraction as an
MXU matmul.  No sequential loop, no thread divergence, static shapes.

Key-byte extraction rides the MXU: an element gather
(``keys[l,e,k] = lines[l, start[l,e]+k]``) lowers to a scalar gather that
is ~12x slower than the rest of the stage combined on TPU v5e; instead the
one-hot start mask contracts against ``key_width`` shifted copies of the
line bytes — ``einsum('lwe,lwk->lek', onehot, shifted)`` in bfloat16
(bytes 0..255 and 0/1 indicators are exact in bf16; accumulation in f32).
That is the standard TPU gather-as-matmul trick: the systolic array does
scattered reads as dense FLOPs.

The fixed-slot emit contract is preserved (same capacity semantics as
main.cu:145): each line owns ``emits_per_line`` slots; excess tokens are
dropped and counted (the reference printf-warns and drops, main.cu:141-144).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from locust_tpu.config import EngineConfig
from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch


class TokenizeResult(NamedTuple):
    keys: jax.Array      # uint8 [lines, emits_per_line, key_width]
    valid: jax.Array     # bool  [lines, emits_per_line]
    overflow: jax.Array  # int32 [] — tokens dropped beyond the per-line cap


def tokenize_block(lines: jax.Array, cfg: EngineConfig) -> TokenizeResult:
    """Tokenize a ``[block_lines, line_width]`` uint8 block.

    Pure-jnp formulation (the Pallas variant lives in ops/pallas/); XLA fuses
    the mask/compare chain into a couple of VPU passes plus one gather.
    """
    num_lines, width = lines.shape
    emits, key_w = cfg.emits_per_line, cfg.key_width

    in_token = ~bytes_ops.delimiter_mask(lines)            # [L, W]
    starts = bytes_ops.token_starts(in_token)              # [L, W]
    tid = bytes_ops.token_ids(starts)                      # [L, W]

    slot = jnp.arange(emits, dtype=jnp.int32)              # [E]
    ntok = jnp.sum(starts.astype(jnp.int32), axis=-1)      # [L]
    valid = slot[None, :] < jnp.minimum(ntok, emits)[:, None]

    # keys[l,e,k] = lines[l, start[l,e]+k], formulated per backend
    # (cfg.map_impl; VERDICT r3 weak #4).
    padded = jnp.pad(lines, ((0, 0), (0, key_w)))
    impl = cfg.map_impl
    if impl == "auto":
        impl = "einsum" if jax.default_backend() == "tpu" else "gather"
    if impl == "einsum":
        # MXU contraction (see module docstring): one-hot "token e of
        # line l starts at byte w" x key_width shifted byte planes.
        start_oh = starts[..., None] & (tid[..., None] == slot)  # [L, W, E]
        shifted = jnp.stack(
            [padded[:, k : k + width] for k in range(key_w)], axis=-1
        )                                                   # [L, W, K] uint8
        gathered = jnp.einsum(
            "lwe,lwk->lek",
            start_oh.astype(jnp.bfloat16),
            shifted.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(jnp.uint8)                                 # exact: bytes<256
    else:
        # Plain gather: scatter each token's start column into its emit
        # slot (each live (line, slot) written at most once — token ids
        # are unique per start), then one take_along_axis over the
        # NUL-padded row.  O(L*W + L*E*K) scalar work instead of the
        # einsum's L*W*E*K multiply-adds — the right trade everywhere
        # EXCEPT the MXU.  Non-starts and overflow tokens land in an
        # explicit dump slot (index ``emits``, sliced off) so every write
        # is in-bounds — a mode="drop" OOB write would trip the checkify
        # index guard the debug pipeline runs under.  Invalid slots
        # gather from column 0; `valid` masks them below.
        w_col = jnp.broadcast_to(
            jnp.arange(width, dtype=jnp.int32)[None, :], lines.shape
        )
        slot_of_col = jnp.where(
            starts, jnp.minimum(tid, emits), emits
        )                                                   # [L, W] in [0,E]
        start_idx = (
            jnp.zeros((num_lines, emits + 1), dtype=jnp.int32)
            .at[jnp.arange(num_lines, dtype=jnp.int32)[:, None], slot_of_col]
            .set(w_col)[:, :emits]
        )                                                   # [L, E]
        idx = start_idx[:, :, None] + jnp.arange(key_w, dtype=jnp.int32)
        gathered = jnp.take_along_axis(
            padded, idx.reshape(num_lines, -1), axis=1
        ).reshape(num_lines, emits, key_w)                  # [L, E, K] uint8

    # Token end masking needs no end-index table: a token's bytes run until
    # its first delimiter (NUL pad included in the delimiter set), so the
    # running all-non-delimiter AND over the gathered window IS the key
    # mask.  Tokens longer than key_w truncate, matching the reference's
    # 30-byte key field (KeyValue.h:15).  The prefix-AND runs as log2(K)
    # shifted ANDs rather than a cumprod: XLA lowers cumprod to a serial
    # scan that costs ~2x the whole rest of the tail on CPU (measured
    # 8.9ms vs 5.0ms at [8192, 17, 16]), while K is a tiny static width.
    live = ~bytes_ops.delimiter_mask(gathered)              # [L, E, K]
    shift = 1
    while shift < key_w:
        live = live & jnp.concatenate(
            [jnp.ones_like(live[..., :shift]), live[..., :-shift]], axis=-1
        )
        shift *= 2
    keys = jnp.where(live & valid[..., None], gathered, jnp.uint8(0))

    overflow = jnp.sum(jnp.maximum(ntok - emits, 0))
    return TokenizeResult(keys=keys, valid=valid, overflow=overflow)


def wordcount_map(lines: jax.Array, cfg: EngineConfig) -> tuple[KVBatch, jax.Array]:
    """The WordCount map_fn: emit ``(token, 1)`` per token.

    Returns the flat emit batch ``[block_lines * emits_per_line]`` and the
    overflow counter — the analog of the reference's per-line fixed-slot emit
    table ``dev_map_kvs[MAX_EMITS]`` (main.cu:20,392).

    ``cfg.use_pallas`` selects the hand-written VMEM-resident kernel
    (ops/pallas/tokenize.py); interpret mode engages automatically off-TPU.
    """
    if cfg.use_pallas:
        from locust_tpu.ops.pallas.tokenize import tokenize_block_pallas

        interpret = jax.default_backend() != "tpu"
        keys, valid, overflow = tokenize_block_pallas(lines, cfg, interpret)
    else:
        res = tokenize_block(lines, cfg)
        keys, valid, overflow = res.keys, res.valid, res.overflow
    flat_keys = keys.reshape(-1, cfg.key_width)
    flat_valid = valid.reshape(-1)
    values = jnp.ones(flat_keys.shape[0], dtype=jnp.int32)
    return KVBatch.from_bytes(flat_keys, values, flat_valid), overflow
