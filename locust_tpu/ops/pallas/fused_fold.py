"""Fused map->aggregate Pallas megakernel: tokenize + hash + table-update
in one VMEM-resident kernel.

The hot path's largest remaining HBM round-trip (ROADMAP item 5) is the
``[lines, emits, key_width]`` token tensor materialized between the map
stage (ops/map_stage.py) and the hash-table fold (ops/hash_table.py) —
the same global-memory staging the reference does between ``kernMap`` and
its Process sort (reference MapReduce/src/main.cu:392-415).  This kernel
DELETES that intermediate rather than accelerating it (the FlashAttention
keep-it-resident argument applied to the map->process boundary): per
line-tile grid step it

  1. tokenizes the ``[FUSED_TILE_LINES, line_width]`` uint8 tile in VMEM,
     reusing the mask / prefix-sum / masked-reduction formulation of
     ops/pallas/tokenize.py byte for byte (same key bytes, same validity,
     same overflow count);
  2. collapses the tile's duplicate keys EXACTLY with a Gram-matrix
     equality (``d2 = |a|^2 + |b|^2 - 2 a.b == 0`` over the key byte
     planes — one [n, K] x [K, n] MXU contraction; every operand is an
     integer < 2^24, so f32 arithmetic is exact and equality is exact);
  3. hashes the surviving tile leaders with the SAME ``hash_pair``
     formulation the hasht family probes by (fmix32 salted folds over
     big-endian uint32 lanes, core/packing.py);
  4. walks the hasht probe sequence ``slot_p = (h1 + p*(h2|1)) % S`` over
     a ``[t_hi, t_lo]``-tiled accumulator table kept RESIDENT in VMEM
     across grid steps (config.fused_grid / FUSED_TABLE_SLOTS): empty-slot
     key writes, full-key verify, and count combine are all spelled as
     one-hot f32 contractions — the PR 4 limb-decomposition MXU trick,
     simplified to a single count plane because wordcount emits are 1 and
     a block's count total stays < 2^24 (the engine guards this bound);
  5. streams tile leaders the probe rounds strand through a bounded
     per-tile residual buffer (one-hot placement by prefix-sum rank);
     residual overflow raises a sticky flag and the ENGINE re-folds the
     whole block through the stock hasht path — exact either way.

Exactness story (the same shape as hash_table.py's):

* A row resolves into a slot ONLY on a full-key byte compare against the
  stored planes, so hash collisions can never merge distinct keys.
* Two distinct keys writing the same empty slot in one round produce a
  byte-plane SUM ("chimera") — the analog of hasht's unspecified
  duplicate-index row write.  Chimera slots match no writer (the sums
  differ from either key, and a plane > 255 can equal no key byte; all
  arithmetic is f32-exact, no bf16 rounding anywhere), so both writers
  keep probing or strand to the residual; a chimera that happens to equal
  a THIRD key's bytes simply becomes that key's slot.
* Everything not in the table comes back out: stranded leaders exit via
  the residual stream, and a residual-buffer overflow flags the block for
  the engine's stock re-fold.  No path can lose a row silently.

Bit-identity with "hasht" (tests/test_fused_fold.py): the engine settles
``concat(acc, kernel_table, residual)`` through the UNCHANGED
``hash_table.aggregate_exact``.  hasht's final table is a pure function
of the distinct-key set (each key's (h1, h2) drives the same probe
sequence regardless of row multiplicity; claim scatter-min, full-lane
verify and the commutative combines are all order- and
multiplicity-independent) plus the per-key mod-2^32 totals — and the
kernel preserves exactly that: same distinct keys, same totals (every
valid emit lands in exactly one leader count; leader counts land in
exactly one table slot or residual row per tile; the settlement re-merges
per-tile duplicates like any other duplicate key rows).  The one
divergence window: the settlement's exactness LADDER counts stranded
ROWS, and this mode strands one pre-aggregated row per key where hasht
strands every raw row — so in the pathological > RESIDUAL_CAP-stranded-
rows regime hasht takes the full-sort rebuild while fused may still take
the (cheaper) residual branch.  Both stay exact (identical host pairs);
only the slot LAYOUT can differ there, and reaching it needs > 4096
probe-exhausted raw rows in a single fold.

Megakernel v2 adds two more FORMULATIONS of this same kernel — not new
kernels (both call :func:`fused_block_preagg` unchanged, so the
bit-identity argument above carries over verbatim):

* **Persistent streaming** (engine.run_stream): the kernel already keeps
  its table planes at a constant index_map — VMEM-resident across ALL
  grid steps — and accepts any tile-multiple line count, so the engine
  feeds it SEGMENTS of ``config.FUSED_STREAM_BLOCKS`` staged blocks per
  launch.  Pallas double-buffers the per-tile line DMA automatically
  (indexed input BlockSpec), the bounded residual drains per tile as
  before, and the acc->settle->acc HBM round-trip plus the table flush
  amortize by the segment length (utils/roofline.py "fused-stream").
  Exactness: the per-SEGMENT emit budget must stay < 2^24 for the f32
  count planes — :func:`config.fused_stream_seg_blocks` clamps the
  segment to that bound (and to the interpret-cost cap off-TPU).
* **Mesh-native** (parallel/shuffle.py, parallel/hierarchical.py): the
  kernel runs per shard UNDER shard_map, replacing map+local-combine in
  the shuffle step body; the per-shard table+residual settle through the
  UNCHANGED per-shard merge + hierarchical combine.  TPU-only
  (:func:`fused_mesh_eligible`): off-TPU the mesh engines demote to
  plain hasht with an explicit one-time log and a ``fused_demoted``
  result field — the interpret kernel NEVER runs inside a CPU mesh
  program (the check_vma segfault class, CLAUDE.md).

Validation off-TPU uses interpret mode strictly under the pinned
direct-test pattern — NEVER inside a full CPU mesh program (the
check_vma segfault class, CLAUDE.md); the mesh engines run this mode as
plain hasht, and ``config.FUSED_INTERPRET_MAX_LINES`` bounds the
interpreter's per-grid-step re-trace on the single-device path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from locust_tpu.config import (
    DELIMITERS,
    FUSED_RESID_PAD,
    FUSED_RESIDUAL_ROWS,
    FUSED_TABLE_SLOTS,
    FUSED_TILE_LINES,
    HASHT_PROBES,
    EngineConfig,
    # The physical [t_hi, t_lo] plane layout is decided ONCE in config
    # (jax-free) so utils/roofline.py prices the same padded table this
    # kernel allocates.
    fused_table_layout,
)
from locust_tpu.core.kv import KVBatch

# Residual row layout: key bytes [0..K-1], count [K], valid flag [K+1],
# zero padding out to K + RESID_PAD lanes.  Kept narrow deliberately:
# residual rows DO cross HBM, and utils/roofline.py prices exactly this
# width off the SAME config constant (config.FUSED_RESID_PAD) — a
# drifted copy would silently model the wrong residual traffic.
RESID_PAD = FUSED_RESID_PAD



def _fmix32(h):
    """murmur3 finalizer on uint32 — the packing._fmix32 formulation."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _salted_fold_lanes(lanes, salt_prime, pre_mul):
    """packing._salted_fold over a LIST of [N, 1] uint32 lane columns:
    fmix32(sum_j fmix32(lane_j ^ salt_j)), wraparound uint32 adds."""
    acc = None
    for j, lane in enumerate(lanes):
        x = lane if pre_mul is None else lane * jnp.uint32(pre_mul)
        term = _fmix32(x ^ jnp.uint32(((j + 1) * salt_prime) & 0xFFFFFFFF))
        acc = term if acc is None else acc + term
    return _fmix32(acc)


def _fused_kernel(
    x_ref, tab_ref, resid_ref, ovf_ref, flag_ref,
    *, emits, key_w, width, slots, t_hi, t_lo, probes, r_cap,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        # The accumulator planes live at a CONSTANT index_map, so Pallas
        # keeps them in VMEM across grid steps; step 0 owns the init.
        tab_ref[:] = jnp.zeros_like(tab_ref)
        ovf_ref[:] = jnp.zeros_like(ovf_ref)
        flag_ref[:] = jnp.zeros_like(flag_ref)

    # ---- 1. tokenize the tile (ops/pallas/tokenize.py formulation) ----
    x = x_ref[:]                                            # [T, W] uint8
    xi = x.astype(jnp.int32)
    is_delim = xi == 0
    for c in DELIMITERS + b"\n\r":
        is_delim = is_delim | (xi == c)
    in_tok = ~is_delim
    zeros_col = jnp.zeros((x.shape[0], 1), dtype=jnp.bool_)
    prev = jnp.concatenate([zeros_col, in_tok[:, :-1]], axis=1)
    nxt = jnp.concatenate([in_tok[:, 1:], zeros_col], axis=1)
    starts = in_tok & ~prev
    ends = in_tok & ~nxt
    csum = starts.astype(jnp.int32)
    shift = 1
    while shift < width:
        pad = jnp.zeros((csum.shape[0], shift), dtype=jnp.int32)
        csum = csum + jnp.concatenate([pad, csum[:, :-shift]], axis=1)
        shift *= 2
    tid = csum - 1                                          # [T, W]
    pos = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)   # [T, W]
    # EVERY reduction below runs in f32: this jaxlib generation's Mosaic
    # has no integer-reduction lowering, and all reduced values here are
    # integers < 2^24, where f32 sums are exact.  Elementwise integer
    # adds (the Hillis-Steele scans) lower fine and stay int.
    ntok = jnp.sum(starts.astype(jnp.float32), axis=1, keepdims=True)
    # Accumulated scalar (constant-index [1, 1] block — Mosaic requires
    # block dims divisible by the tile or equal to the array's, so a
    # per-tile (1, 1) block over an [n_tiles, 1] array cannot lower).
    ovf_ref[:] = ovf_ref[:] + jnp.sum(
        jnp.maximum(ntok - float(emits), 0.0)
    )[None, None].astype(jnp.int32)

    # Per-(slot, byte) masked VPU reductions -> flat [N = emits*T] rows
    # in emit-major order, one [N, 1] column per key byte (row order is
    # immaterial: the table is a set, and the dedupe below is
    # order-blind).  Column-wise instead of one [N, K] array so no later
    # step needs an unaligned lane slice of a packed key matrix.
    byte_cols = [[] for _ in range(key_w)]                  # [K][E] of [T,1]
    valid_cols = []
    pos_f = pos.astype(jnp.float32)
    xi_f = xi.astype(jnp.float32)
    for e in range(emits):  # static unroll: emits is a config constant
        sel = tid == e
        m_start = (starts & sel).astype(jnp.float32)
        m_end = (ends & sel).astype(jnp.float32)
        s_idx = jnp.sum(
            pos_f * m_start, axis=1, keepdims=True
        ).astype(jnp.int32)                                     # [T, 1]
        e_idx = jnp.sum(
            pos_f * m_end, axis=1, keepdims=True
        ).astype(jnp.int32)                                     # [T, 1]
        has_tok = jnp.sum(m_start, axis=1, keepdims=True) > 0.0  # [T, 1]
        tok_len = jnp.clip(e_idx - s_idx + 1, 0, key_w)
        valid_cols.append(has_tok)
        for k in range(key_w):  # static unroll: key bytes
            hit = (pos == s_idx + k) & has_tok & (k < tok_len)
            byte_cols[k].append(
                jnp.sum(
                    xi_f * hit.astype(jnp.float32), axis=1, keepdims=True
                )
            )
    bcols = [
        jnp.concatenate(byte_cols[k], axis=0) for k in range(key_w)
    ]                                                       # [K] of [N,1] f32
    valid = jnp.concatenate(valid_cols, axis=0)             # [N, 1] bool
    bf = jnp.concatenate(bcols, axis=1)                     # [N, K] f32
    n_rows = bf.shape[0]
    ones_col = jnp.ones((n_rows, 1), dtype=jnp.float32)

    def row_bcast(col):
        """[N, 1] -> [N, N] carrying col[m] at (n, m): a rank-1 ones x
        col contraction — the lane-major broadcast WITHOUT an in-kernel
        transpose (Mosaic-safe), exact for one-hot/byte magnitudes."""
        return jax.lax.dot_general(
            ones_col, col, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # ---- 2. exact within-tile dedupe via the Gram matrix ----
    gram = jax.lax.dot_general(
        bf, bf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                       # [N, N]
    norm = jnp.zeros((n_rows, 1), dtype=jnp.float32)
    for c in bcols:
        norm = norm + c * c                                 # [N, 1]
    d2 = norm + row_bcast(norm) - 2.0 * gram                # exact: < 2^24
    eq = (d2 == 0.0) & valid & (row_bcast(valid.astype(jnp.float32)) > 0.0)
    ridx = jax.lax.broadcasted_iota(jnp.int32, (n_rows, n_rows), 0)
    cidx = jax.lax.broadcasted_iota(jnp.int32, (n_rows, n_rows), 1)
    has_prev = jnp.sum(
        (eq & (cidx < ridx)).astype(jnp.float32), axis=1, keepdims=True
    ) > 0.0
    leader = valid & ~has_prev                              # [N, 1]
    cnt = jnp.sum(eq.astype(jnp.float32), axis=1, keepdims=True)  # [N, 1]

    # ---- 3. hash leaders (packing.hash_pair formulation) ----
    lanes = []
    for j in range(key_w // 4):
        # f32 -> int32 -> uint32: the direct f32->u32 convert recurses in
        # this jaxlib generation's Mosaic _convert_helper; the two-step
        # spelling is exact (bytes are 0..255) and lowers everywhere.
        b0 = bcols[4 * j].astype(jnp.int32).astype(jnp.uint32)
        b1 = bcols[4 * j + 1].astype(jnp.int32).astype(jnp.uint32)
        b2 = bcols[4 * j + 2].astype(jnp.int32).astype(jnp.uint32)
        b3 = bcols[4 * j + 3].astype(jnp.int32).astype(jnp.uint32)
        lanes.append((b0 << 24) | (b1 << 16) | (b2 << 8) | b3)
    h1 = _salted_fold_lanes(lanes, 0x9E3779B9, None)        # [N, 1] uint32
    h2 = _salted_fold_lanes(lanes, 0xC2B2AE3D, 0x01000193)
    step = h2 | jnp.uint32(1)
    lo_bits = (t_lo - 1).bit_length() if t_lo > 1 else 0

    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (n_rows, t_lo), 1)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (n_rows, t_hi), 1)

    def gather_plane(p, oh_lo, oh_hi):
        """tab plane ``p`` value at each row's slot, via one one-hot
        contraction + a masked hi-reduction — exact (single hot term)."""
        plane = tab_ref[p * t_hi:(p + 1) * t_hi, :]         # [t_hi, t_lo]
        g = jax.lax.dot_general(
            oh_lo, plane, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                   # [N, t_hi]
        return jnp.sum(oh_hi * g, axis=1, keepdims=True)    # [N, 1]

    def scatter_plane(p, oh_lo, oh_hi, w):
        """tab plane ``p`` += one-hot scatter of per-row weights ``w``."""
        delta = jax.lax.dot_general(
            oh_hi * w, oh_lo, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                   # [t_hi, t_lo]
        rows = tab_ref[p * t_hi:(p + 1) * t_hi, :]
        tab_ref[p * t_hi:(p + 1) * t_hi, :] = rows + delta

    # ---- 4. hasht probe sequence over the resident table ----
    unres = leader
    for p in range(probes):  # static unroll: probes is a config constant
        slot = (h1 + jnp.uint32(p) * step) & jnp.uint32(slots - 1)
        s32 = slot.astype(jnp.int32)                        # [N, 1]
        hi = s32 >> lo_bits
        lo = s32 & (t_lo - 1)
        oh_lo = (lo == iota_lo).astype(jnp.float32)         # [N, t_lo]
        oh_hi = (hi == iota_hi).astype(jnp.float32)         # [N, t_hi]
        # Empty = occupied plane reads 0 (plane K = writer count).
        occ = gather_plane(key_w, oh_lo, oh_hi)
        writer = (unres & (occ == 0.0)).astype(jnp.float32)
        for k in range(key_w):
            scatter_plane(k, oh_lo, oh_hi, bcols[k] * writer)
        scatter_plane(key_w, oh_lo, oh_hi, writer)
        # Full-key verify AFTER this round's writes (a clean writer must
        # match its own write).  Empty slots read all-zero planes and a
        # real key's byte 0 is >= 1, so no occupied check is needed.
        match = unres
        for k in range(key_w):
            match = match & (gather_plane(k, oh_lo, oh_hi) == bcols[k])
        scatter_plane(key_w + 1, oh_lo, oh_hi,
                      cnt * match.astype(jnp.float32))
        unres = unres & ~match

    # ---- 5. residual stream: rank-compact stranded leaders ----
    ri = unres.astype(jnp.int32)                            # [N, 1]
    shift = 1
    while shift < n_rows:
        pad = jnp.zeros((shift, 1), dtype=jnp.int32)
        ri = ri + jnp.concatenate([pad, ri[:-shift]], axis=0)
        shift *= 2
    rank = ri - 1                                           # [N, 1]
    n_resid = jnp.sum(unres.astype(jnp.float32))
    iota_r = jax.lax.broadcasted_iota(jnp.int32, (n_rows, r_cap), 1)
    place = ((rank == iota_r) & unres).astype(jnp.float32)  # [N, r_cap]

    def compact(cols):
        return jax.lax.dot_general(
            place, cols, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                   # [r_cap, .]

    # One full-width store (no partial lane-dim ref slices): bytes,
    # count, valid flag, zero tail.
    resid_ref[:] = jnp.concatenate(
        [
            compact(bf),
            compact(cnt),
            compact(unres.astype(jnp.float32)),
            jnp.zeros((r_cap, RESID_PAD - 2), dtype=jnp.float32),
        ],
        axis=1,
    )
    flag_ref[:] = jnp.maximum(
        flag_ref[:],
        (n_resid > float(r_cap)).astype(jnp.int32)[None, None],
    )


def fused_engine_eligible(cfg: EngineConfig, map_fn, combine: str):
    """Can the single-device engine run this fold through the megakernel?

    Returns ``(ok, reason)`` — ``reason`` says why not, so the engine can
    log the hasht-identical degrade ONCE at construction (outside any
    traced code; keeps the kernel body R002-clean).  The checks are all
    static:

    * the kernel bakes in the wordcount tokenizer and the sum monoid
      ("count" lowers to emit-1 + sum, which IS the kernel's count
      plane); any other map_fn/combine folds exactly like "hasht";
    * tile/lane alignment: block_lines a multiple of FUSED_TILE_LINES,
      line_width a multiple of 128 (the uint8 VMEM tile);
    * ``emits_per_block < 2^24``: the kernel accumulates counts in f32
      planes, exact only below the float24 integer ceiling;
    * off-TPU, blocks above FUSED_INTERPRET_MAX_LINES stay on the stock
      path — the interpreter re-traces the kernel body per grid step and
      production block sizes cost minutes of XLA CPU compile (see
      BITONIC_INTERPRET_MAX for the precedent).
    """
    from locust_tpu.config import FUSED_INTERPRET_MAX_LINES
    from locust_tpu.ops.map_stage import wordcount_map

    if map_fn is not wordcount_map:
        return False, (
            "map_fn is not the wordcount tokenizer (the kernel bakes "
            "tokenize+count in); folding exactly like 'hasht'"
        )
    if combine not in ("sum", "count"):
        return False, (
            f"combine={combine!r} has no kernel spelling (sum/count only); "
            "folding exactly like 'hasht'"
        )
    if cfg.block_lines % FUSED_TILE_LINES != 0:
        return False, (
            f"block_lines={cfg.block_lines} not a multiple of the "
            f"{FUSED_TILE_LINES}-line kernel tile; folding exactly like "
            "'hasht'"
        )
    if cfg.line_width % 128 != 0:
        return False, (
            f"line_width={cfg.line_width} not a multiple of 128 (uint8 "
            "VMEM tile); folding exactly like 'hasht'"
        )
    if cfg.emits_per_block >= 1 << 24:
        return False, (
            f"emits_per_block={cfg.emits_per_block} >= 2^24 breaks the "
            "kernel's f32 count exactness; folding exactly like 'hasht'"
        )
    if (
        jax.default_backend() != "tpu"
        and cfg.block_lines > FUSED_INTERPRET_MAX_LINES
    ):
        return False, (
            f"off-TPU interpret mode capped at "
            f"{FUSED_INTERPRET_MAX_LINES} lines/block "
            f"(block_lines={cfg.block_lines}; LOCUST_FUSED_INTERPRET_"
            "MAX_LINES overrides); folding exactly like 'hasht'"
        )
    return True, ""


def fused_mesh_eligible(cfg: EngineConfig, map_fn, combine: str):
    """Can the MESH engines run their per-shard map+combine through the
    megakernel?  Returns ``(ok, reason)`` like :func:`fused_engine_eligible`.

    Everything static, decided once at engine construction (the mesh
    engines log the demotion there and surface it as ``fused_demoted``
    on DistributedResult — the ISSUE 19 fix for the silent fallback):

    * all single-device checks apply per shard (each shard folds
      ``cfg.block_lines`` lines per round — the same block the kernel
      pre-aggregates);
    * **TPU only**: the interpret-mode kernel inside a full CPU mesh
      program segfaults XLA's CPU compiler (the check_vma class,
      CLAUDE.md) — off-TPU the mesh fold stays plain hasht, period.
      The CPU kernel-under-shard_map path is pinned by a small DIRECT
      test instead (tests/test_fused_fold.py);
    * the pre-aggregated rows (table slots + per-tile residuals) must
      fit the shard's ``emits_per_block`` KV capacity — the shuffle
      step's capacity contract is that the local combiner's output size
      equals the raw emit count, and the kernel's output pads up to it.
    """
    ok, why = fused_engine_eligible(cfg, map_fn, combine)
    if not ok:
        return False, why
    if jax.default_backend() != "tpu":
        return False, (
            "mesh fused mode is TPU-only (the interpret kernel never "
            "runs inside a CPU mesh program — check_vma segfault class); "
            "folding exactly like 'hasht'"
        )
    t_hi, t_lo = fused_table_layout()
    n_tiles = cfg.block_lines // FUSED_TILE_LINES
    preagg_rows = t_hi * t_lo + n_tiles * FUSED_RESIDUAL_ROWS
    if preagg_rows > cfg.emits_per_block:
        return False, (
            f"kernel output ({preagg_rows} table+residual rows) exceeds "
            f"the shard's emit capacity ({cfg.emits_per_block}); folding "
            "exactly like 'hasht'"
        )
    return True, ""


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "interpret", "table_slots", "resid_rows", "probes",
        "tile_lines",
    ),
)
def fused_block_preagg(
    lines: jax.Array,
    cfg: EngineConfig,
    interpret: bool = False,
    table_slots: int | None = None,
    resid_rows: int | None = None,
    probes: int | None = None,
    tile_lines: int | None = None,
):
    """Pre-aggregate one ``[block_lines, line_width]`` uint8 block in VMEM.

    Returns ``(table, residual, overflow, resid_overflow)``:

    * ``table`` — KVBatch over the (sublane-padded) kernel table: each
      valid slot holds one distinct key of the block with its exact
      occurrence count (int32; the engine guards ``block_lines *
      emits_per_line < 2^24`` so the in-kernel f32 counts are exact);
    * ``residual`` — KVBatch of ``n_tiles * resid_rows`` rows: per-tile
      distinct keys the probe rounds stranded, with their tile counts
      (the same key may appear once per tile — the settlement fold
      re-merges duplicate key rows exactly, hash_table.aggregate_exact);
    * ``overflow`` — int32 tokens dropped by the per-line emit cap, the
      tokenize contract (identical formulation to tokenize_block);
    * ``resid_overflow`` — bool: some tile stranded more leaders than the
      residual buffer holds; the caller MUST discard this call's table
      and residual and re-fold the block through the stock path (the
      engine's lax.cond does).  Nothing is lost either way — the flag is
      sticky across grid steps.

    The union of table and residual rows carries exactly the block's
    distinct keys with exact per-key totals — the invariant the
    bit-identity argument in the module docstring rests on.
    """
    num_lines, width = lines.shape
    tile = FUSED_TILE_LINES if tile_lines is None else tile_lines
    slots = FUSED_TABLE_SLOTS if table_slots is None else table_slots
    r_cap = FUSED_RESIDUAL_ROWS if resid_rows is None else resid_rows
    n_probes = HASHT_PROBES if probes is None else probes
    if num_lines % tile != 0:
        raise ValueError(f"block_lines must be a multiple of {tile}")
    if width % 128 != 0:
        raise ValueError(f"line_width must be a multiple of 128, got {width}")
    if slots < 2 or slots & (slots - 1):
        raise ValueError(f"table_slots must be a power of two, got {slots}")
    emits, key_w = cfg.emits_per_line, cfg.key_width
    t_hi, t_lo = fused_table_layout(slots)
    out_slots = t_hi * t_lo                                 # >= slots
    n_tiles = num_lines // tile
    rw = key_w + RESID_PAD

    kernel = functools.partial(
        _fused_kernel, emits=emits, key_w=key_w, width=width,
        slots=slots, t_hi=t_hi, t_lo=t_lo, probes=n_probes, r_cap=r_cap,
    )
    tab, resid, ovf, flag = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile, width), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(((key_w + 2) * t_hi, t_lo), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r_cap, rw), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(((key_w + 2) * t_hi, t_lo), jnp.float32),
            jax.ShapeDtypeStruct((n_tiles * r_cap, rw), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        interpret=interpret,
    )(lines)

    # Decode the plane-major table into a slot-major KVBatch (slot id =
    # hi * t_lo + lo, the same split the kernel addressed).  Chimera
    # slots (count 0) may hold byte sums > 255; they are invalid and the
    # uint8 wrap below never reaches a consumer.
    planes = tab.reshape(key_w + 2, t_hi, t_lo)
    key_bytes = (
        planes[:key_w].transpose(1, 2, 0).reshape(out_slots, key_w)
        .astype(jnp.uint8)
    )
    counts = planes[key_w + 1].reshape(out_slots).astype(jnp.int32)
    table_kv = KVBatch.from_bytes(key_bytes, counts, counts > 0)

    resid_kv = KVBatch.from_bytes(
        resid[:, :key_w].astype(jnp.uint8),
        resid[:, key_w].astype(jnp.int32),
        resid[:, key_w + 1] > 0.0,
    )
    return table_kv, resid_kv, ovf[0, 0], flag[0, 0] > 0
