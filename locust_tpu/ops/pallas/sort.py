"""Pallas TPU bitonic sort for the Process stage (VERDICT r3 next #2).

The Process-stage sort is where the reference's target is won or lost
(94% of its GPU runtime: reference MapReduce/src/main.cu:414-415 region);
ours runs on stock ``lax.sort``, whose TPU lowering streams every sort
operand through HBM on each of ~k(k+1)/2 compare-exchange passes
(k = ceil(log2 n) ~ 20 at engine shape -> ~210 passes).  A bitonic
network has a locality structure XLA does not exploit: every substage
with compare distance d < tile operates INSIDE an aligned tile, so one
VMEM-resident kernel invocation can run ALL such substages back-to-back,
paying ONE HBM round-trip where the stock sort pays dozens.

Structure (n padded to 2^k, element e lives at [row e//128, lane e%128]):

  * stage s = 1..k, substage t = s..1, distance d = 2^(t-1);
    partner(e) = e ^ d; block direction asc = ((e >> s) & 1) == 0;
    the lower partner keeps the min iff asc (Batcher's network).
  * substages with d <= tile/2 are tile-local -> fused Pallas kernel
    (grid over tiles, key + payload operands pinned in VMEM; lane-dim
    exchanges (d < 128) via jnp.roll along lanes, sublane-dim exchanges
    via a leading-axis reshape swap).
  * substages with d >= tile are a single elementwise pass each — plain
    XLA on a [n/2d, 2, d-elements] view (one fused read+write of the
    array; no Pallas needed, there is no reuse to exploit).

HBM round-trips: 1 + sum_{s=m+1..k} (s - m + 1) where 2^m = tile
(e.g. ~21 at n=2^20, tile=2^15) vs ~210 operand streamings for the
stock network — the "hand-managed VMEM" formulation of the one-pass
rank/cumsum idea that made the pure-XLA radix attempt lose
(ops/radix_sort.py: its per-pass gathers go to HBM; here they stay in
VMEM).  That count assumes unlimited fusion; when BITONIC_MAX_FUSED
caps the substages per launch (the Mosaic compile-size mitigation),
the true count is ``len(config.bitonic_schedule(k, m))`` — the shared
launch plan both this kernel and utils/roofline.py consume.

The engine-facing mode ("bitonic", config.SORT_MODES) sorts the folded
31-bit-hash+validity key (process_stage._folded_key, same collision
story as "hash1") and carries the row as payload (same payload-carriage
win as "hashp").  Correctness is oracle-tested in interpret mode off-TPU;
the on-hardware A/B rides scripts/opp_resume.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from locust_tpu.config import BITONIC_TILE_ROWS, bitonic_schedule

# Default tile: 2^15 elements = 256 rows x 128 lanes.  Working set per
# operand = 128KB; key + 9 payload operands (key_width 32) = 1.25MB of
# VMEM — comfortable, and m=15 leaves few cross stages.  Parsed and
# validated in config.py (jax-free, shared with the roofline model);
# $LOCUST_BITONIC_TILE_ROWS overrides, and the on-hardware tile sweep
# (scripts/tpu_checks.py bitonic_tile_ab) measures where the knee is.
TILE_ROWS = BITONIC_TILE_ROWS

_LANES = 128


def _ilog2(n: int) -> int:
    b = n.bit_length() - 1
    if n != (1 << b):
        raise ValueError(f"{n} is not a power of two")
    return b


def _compare_exchange(arrs, pv, keep_min_i):
    """One compare-exchange: arrs[0] is the key; every operand takes its
    partner's value where the key decision says so.  Ties never swap, so
    the two partners always agree.  ``keep_min_i`` is int32 0/1 and the
    lt/gt outcomes are widened to int32 before the select: a select whose
    OPERANDS are bools lowers to ``arith.trunci i8 -> i1``, which v5e
    Mosaic rejects ("Unsupported target bitwidth for truncation",
    measured on-hardware 2026-07-31) — masks may be i1, data may not."""
    key, pkey = arrs[0], pv[0]
    lt = (pkey < key).astype(jnp.int32)
    gt = (pkey > key).astype(jnp.int32)
    take = jnp.where(keep_min_i != 0, lt, gt) != 0
    return [jnp.where(take, p, a) for a, p in zip(arrs, pv)]


def _local_stages_kernel(*refs, stages, tile_rows, n_ops):
    """Run ``stages`` = ((s, t_hi, t_lo), ...) with every substage
    t_hi..t_lo tile-local in VMEM.  refs = n_ops inputs then n_ops
    outputs (aliased)."""
    ins, outs = refs[:n_ops], refs[n_ops:]
    arrs = [r[:] for r in ins]
    base = pl.program_id(0) * tile_rows * _LANES
    row = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, _LANES), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, _LANES), 1)
    gidx = base + row * _LANES + lane

    for s, t_hi, t_lo in stages:
        asc_i = ((gidx >> s) & 1) ^ 1  # int32 1 = ascending block
        for t in range(t_hi, t_lo - 1, -1):
            d = 1 << (t - 1)
            # int32 throughout (no i1==i1 compares, no bool-operand
            # selects — see _compare_exchange for the Mosaic constraint).
            is_lower_i = ((gidx & d) == 0).astype(jnp.int32)
            keep_min_i = 1 - (asc_i ^ is_lower_i)
            if d < _LANES:
                # Lane-dim exchange: partner lane = lane ^ d.  l + d keeps
                # bit d set iff it was clear, so the two rotations cover
                # both partner directions; the wrapped values are never
                # selected.  Rotation is spelled slice+concat rather than
                # jnp.roll: roll's lowering drops the varying-manual-axes
                # type under shard_map(check_vma=True), poisoning every
                # downstream compare (jax issue; VERDICT r4 next #7) —
                # slice/concat propagate vma correctly and lower the same.
                def _rot(a, k):  # left-rotate lanes by k
                    return jnp.concatenate([a[:, k:], a[:, :k]], axis=1)

                down = [_rot(a, d) for a in arrs]
                up = [_rot(a, _LANES - d) for a in arrs]
                pv = [
                    jnp.where((lane & d) == 0, dn, u)
                    for dn, u in zip(down, up)
                ]
            else:
                # Sublane-dim exchange: partner row = row ^ (d/128); an
                # aligned leading-axis reshape turns it into a pair swap.
                dr = d // _LANES
                g = tile_rows // (2 * dr)

                def swap(a, g=g, dr=dr):
                    a4 = a.reshape(g, 2, dr, _LANES)
                    return jnp.concatenate(
                        [a4[:, 1:2], a4[:, 0:1]], axis=1
                    ).reshape(tile_rows, _LANES)

                pv = [swap(a) for a in arrs]
            arrs = _compare_exchange(arrs, pv, keep_min_i)

    for o, a in zip(outs, arrs):
        o[:] = a


def _run_local(arrs, stages, tile_rows, interpret):
    """One pallas_call over all tiles; operands aliased in-place."""
    n_ops = len(arrs)
    rows = arrs[0].shape[0]
    grid = rows // tile_rows
    spec = pl.BlockSpec(
        (tile_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _local_stages_kernel,
        stages=tuple(stages),
        tile_rows=tile_rows,
        n_ops=n_ops,
    )

    def out_sds(a):
        # Inside shard_map with check_vma=True, pallas outputs must state
        # how they vary across mesh axes; the sort is elementwise over
        # its own shard, so each output varies exactly like its (aliased)
        # input.  Outside shard_map, vma is absent/empty — plain struct.
        typeof = getattr(jax, "typeof", None)  # absent on jax 0.4.x
        vma = getattr(typeof(a), "vma", None) if typeof else None
        if vma is not None:  # frozenset() (replicated) must pass through
            return jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return list(
        pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[spec] * n_ops,
            out_specs=[spec] * n_ops,
            out_shape=[out_sds(a) for a in arrs],
            input_output_aliases={i: i for i in range(n_ops)},
            interpret=interpret,
        )(*arrs)
    )


def _run_cross(arrs, s, t):
    """One cross-tile substage (d >= tile) as a single fused XLA pass."""
    d = 1 << (t - 1)
    dr = d // _LANES
    g = arrs[0].shape[0] // (2 * dr)
    # Direction is constant over each 2d block (t <= s), so it is a
    # per-block scalar vector, broadcast over the pair.
    block_start = jnp.arange(g, dtype=jnp.int32) * 2 * d
    asc = ((block_start >> s) & 1) == 0
    asc = asc[:, None, None]

    a4 = [a.reshape(g, 2, dr, _LANES) for a in arrs]
    lo = [a[:, 0] for a in a4]
    hi = [a[:, 1] for a in a4]
    key_lo, key_hi = lo[0], hi[0]
    # Lower partner keeps min iff ascending; ties never swap.
    swap = jnp.where(asc, key_hi < key_lo, key_hi > key_lo)
    out = []
    for alo, ahi in zip(lo, hi):
        nlo = jnp.where(swap, ahi, alo)
        nhi = jnp.where(swap, alo, ahi)
        out.append(
            jnp.stack([nlo, nhi], axis=1).reshape(arrs[0].shape)
        )
    return out


def bitonic_sort(
    key: jax.Array,
    payloads: tuple[jax.Array, ...] = (),
    tile_rows: int = TILE_ROWS,
    interpret: bool = False,
    max_fused: int | None = None,
) -> tuple[jax.Array, tuple[jax.Array, ...]]:
    """Ascending sort of a uint32 ``key`` [n]; ``payloads`` ride along.

    n is padded to the next power of two with 0xFFFFFFFF keys (sorted to
    the tail, sliced off).  Not stable (equal keys may reorder) — callers
    sort hash keys whose grouping semantics tolerate that, exactly like
    lax.sort's use in the "hash*" modes.  Arrays smaller than one tile
    shrink the tile to fit (floor 8 rows, the int32 min sublane tile).

    PAD-SENTINEL CAVEAT: rows whose key is exactly 0xFFFFFFFF tie with
    the pad rows, and since ties reorder arbitrarily, the ``[:n]`` slice
    may keep a pad row (zero payloads) in place of a real sentinel-keyed
    row — the sentinel-run PAYLOADS are then not a permutation of the
    inputs.  Callers must either keep keys < 0xFFFFFFFF or not care
    about sentinel-row payloads.  The engine's "bitonic" mode is safe by
    construction: its folded key reserves 0xFFFFFFFF for INVALID rows
    (process_stage._folded_key), whose payloads are dead downstream
    (valid=False) — pinned by a test.  The on-hardware checkers generate
    keys < 0xFFFFFFFF for the same reason.
    """
    n = key.shape[0]
    if key.dtype != jnp.uint32:
        raise TypeError(f"key must be uint32, got {key.dtype}")
    pay = [p.astype(jnp.uint32) for p in payloads]
    pay_dtypes = [p.dtype for p in payloads]

    # Next power of two >= n, floor 1024 (8 sublanes x 128 lanes, the
    # int32 min tile): 2^bit_length(n-1) >= n always holds.
    n_pad = max(1 << 10, 1 << max(n - 1, 1).bit_length())
    pad = n_pad - n
    key_p = jnp.pad(key, (0, pad), constant_values=jnp.uint32(0xFFFFFFFF))
    pay_p = [jnp.pad(p, (0, pad)) for p in pay]

    rows = n_pad // _LANES
    tr = min(tile_rows, rows)
    kbits = _ilog2(n_pad)
    m = _ilog2(tr * _LANES)

    arrs = [key_p.reshape(rows, _LANES)] + [
        p.reshape(rows, _LANES) for p in pay_p
    ]
    # Execute the shared launch plan (config.bitonic_schedule): fused
    # VMEM launches for tile-local substage runs (capped at
    # BITONIC_MAX_FUSED substages each — unlimited fusion crashed axon's
    # Mosaic remote compile), single XLA passes for cross-tile substages.
    for step in bitonic_schedule(kbits, m, max_fused):
        if step[0] == "local":
            arrs = _run_local(arrs, step[1], tr, interpret)
        else:
            arrs = _run_cross(arrs, step[1], step[2])

    out_key = arrs[0].reshape(-1)[:n]
    out_pay = tuple(
        a.reshape(-1)[:n].astype(dt)
        for a, dt in zip(arrs[1:], pay_dtypes)
    )
    return out_key, out_pay
