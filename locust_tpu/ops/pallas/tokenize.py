"""Pallas TPU kernel for the Map stage tokenizer.

The jnp tokenizer (ops/map_stage.py) materializes ``[lines, width, emits]``
one-hot tensors for the slot-index reduction; whether those round-trip HBM
is up to XLA's fusion heuristics.  This kernel pins the whole per-tile
working set in VMEM and never builds a 3-D intermediate: the emit-slot loop
is statically unrolled (emits_per_line is a small config constant, the
reference's EMITS_PER_LINE=20, main.cu:19), and each (slot, byte) output is
a masked VPU reduction over the line.

Replaces the reference's one-CUDA-thread-per-line ``kernMap``
(reference MapReduce/src/main.cu:155-159) whose inner ``my_strtok_r`` loop
is inherently sequential per thread; here every line in the tile advances
in lockstep vector operations.

Grid: one program per tile of ``TILE_LINES`` lines.  uint8 inputs use the
(32, 128) min tile, so TILE_LINES is a multiple of 32 and line_width a
multiple of 128.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from locust_tpu.config import DELIMITERS, EngineConfig

TILE_LINES = 64


def _tokenize_kernel(x_ref, keys_ref, valid_ref, ovf_ref, *, emits, key_w, width):
    x = x_ref[:]  # [T, W] uint8
    xi = x.astype(jnp.int32)

    # Delimiter classification, statically unrolled over the delimiter set
    # (reference delimiters, main.cu:138, + NUL pad + CR/LF).  Compare on
    # the int32 widening: v5e Mosaic rejects i8 vector compares
    # ("Target does not support this comparison", measured on-hardware).
    is_delim = xi == 0
    for c in DELIMITERS + b"\n\r":
        is_delim = is_delim | (xi == c)
    in_tok = ~is_delim

    zeros_col = jnp.zeros((x.shape[0], 1), dtype=jnp.bool_)
    prev = jnp.concatenate([zeros_col, in_tok[:, :-1]], axis=1)
    nxt = jnp.concatenate([in_tok[:, 1:], zeros_col], axis=1)
    starts = in_tok & ~prev
    ends = in_tok & ~nxt
    # Inclusive prefix sum along the line, as a statically-unrolled
    # Hillis-Steele doubling scan: log2(W) shift-adds.  (jnp.cumsum has no
    # Pallas TPU lowering; this form is plain vector adds.)
    csum = starts.astype(jnp.int32)
    shift = 1
    while shift < width:
        pad = jnp.zeros((csum.shape[0], shift), dtype=jnp.int32)
        csum = csum + jnp.concatenate([pad, csum[:, :-shift]], axis=1)
        shift *= 2
    tid = csum - 1                                          # [T, W]
    pos = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)   # [T, W]

    ntok = jnp.sum(starts.astype(jnp.int32), axis=1, keepdims=True)  # [T, 1]
    ovf_ref[:] = jnp.maximum(ntok - emits, 0)

    for e in range(emits):  # static unroll: emits is a config constant
        sel = tid == e
        m_start = (starts & sel).astype(jnp.int32)
        m_end = (ends & sel).astype(jnp.int32)
        s_idx = jnp.sum(pos * m_start, axis=1, keepdims=True)   # [T, 1]
        e_idx = jnp.sum(pos * m_end, axis=1, keepdims=True)     # [T, 1]
        has_tok = jnp.sum(m_start, axis=1, keepdims=True) > 0   # [T, 1]
        tok_len = jnp.clip(e_idx - s_idx + 1, 0, key_w)
        valid_ref[:, e : e + 1] = has_tok.astype(jnp.int32)
        for k in range(key_w):  # static unroll: key bytes
            # Byte k of slot e = x[l, s_idx + k], as a masked VPU reduction.
            hit = (pos == s_idx + k) & has_tok & (k < tok_len)
            byte = jnp.sum(xi * hit.astype(jnp.int32), axis=1, keepdims=True)
            keys_ref[:, e * key_w + k : e * key_w + k + 1] = byte.astype(
                jnp.uint8
            )


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def tokenize_block_pallas(
    lines: jax.Array, cfg: EngineConfig, interpret: bool = False
):
    """Pallas variant of ops/map_stage.tokenize_block (same contract).

    Returns (keys [L, E, K] uint8, valid [L, E] bool, overflow int32).
    """
    num_lines, width = lines.shape
    if num_lines % TILE_LINES != 0:
        raise ValueError(f"block_lines must be a multiple of {TILE_LINES}")
    if width % 128 != 0:
        # uint8 tiles are (32, 128): a non-multiple width would misalign
        # every VMEM block (module docstring constraint, now enforced).
        raise ValueError(f"line_width must be a multiple of 128, got {width}")
    emits, key_w = cfg.emits_per_line, cfg.key_width
    grid = (num_lines // TILE_LINES,)

    kernel = functools.partial(
        _tokenize_kernel, emits=emits, key_w=key_w, width=width
    )
    keys, valid, ovf = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_LINES, width), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((TILE_LINES, emits * key_w), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_LINES, emits), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_LINES, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((num_lines, emits * key_w), jnp.uint8),
            jax.ShapeDtypeStruct((num_lines, emits), jnp.int32),
            jax.ShapeDtypeStruct((num_lines, 1), jnp.int32),
        ),
        interpret=interpret,
    )(lines)
    return (
        keys.reshape(num_lines, emits, key_w),
        valid.astype(bool),
        jnp.sum(ovf),
    )
