from locust_tpu.ops.pallas.tokenize import tokenize_block_pallas  # noqa: F401
