"""LSD radix argsort in pure XLA: the optimized Process-stage sort attempt.

The reference's Process stage is ``thrust::sort`` — on its GPU, 94% of
total runtime (reference MapReduce/src/main.cu:414-415, README.md:72-80) —
and SURVEY.md §7.3.2 calls sort throughput the make-or-break of the perf
target.  ``jax.lax.sort`` on TPU lowers to a comparison network whose cost
scales ~n·log^2(n) per key operand; for the hash sort mode the keys are
machine integers, where an O(n·passes) radix sort can win.

Design (per 2^bits-bucket stable counting pass, LSD order):

  * digits            d[i]   = (key[i] >> shift) & (B-1)
  * stable rank       r[i]   = |{j < i : d[j] == d[i]}|
  * bucket bases      base[b] = exclusive-sum of the digit histogram
  * scatter           out[base[d[i]] + r[i]] = in[i]

Everything is computed with fixed-shape vectorized ops — no data-dependent
control flow, so the whole sort jits into one XLA program:

  * ranks/histograms come from a chunked one-hot cumulative sum:
    ``[chunks, chunk_len, B]`` one-hot, cumsum along the chunk axis for
    within-chunk ranks, summed for per-chunk histograms, cumsum across
    chunks for chunk offsets.  uint16 accumulators keep the one-hot
    intermediate (the bandwidth cost of the algorithm) at 2·B bytes/row.
  * the scatter is ``jnp.ndarray.at[pos].set`` — one XLA scatter per pass.

Stability makes LSD correct: pass p orders by digit p preserving the order
of passes < p, so after ceil(keybits/bits) passes the keys are fully
sorted and ties keep their original index order (needed by the engine: the
valid-first convention relies on padded rows sorting after real rows with
the same sentinel key, see scripts/bench_sort_variants.variant_e).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("bits", "chunk", "key_bits"))
def radix_argsort(
    key: jax.Array,
    bits: int = 8,
    chunk: int = 8192,
    key_bits: int = 32,
) -> jax.Array:
    """Stable ascending argsort of a uint32 key via LSD counting passes.

    Returns an int32 permutation ``sidx`` with ``key[sidx]`` sorted and
    equal keys in original order.  ``bits`` is the digit width (B = 2^bits
    buckets per pass), ``chunk`` the row-block size of the rank cumsum,
    ``key_bits`` how many low bits of the key participate (fewer passes if
    the caller packed its information narrow).
    """
    if key.dtype != jnp.uint32:
        raise TypeError(f"radix_argsort expects uint32 keys, got {key.dtype}")
    n = key.shape[0]
    B = 1 << bits
    if B > 65536 or chunk >= 65536:
        # uint16 rank accumulators: within-chunk counts must fit.
        raise ValueError(f"bits={bits}/chunk={chunk} overflow uint16 ranks")
    n_passes = -(-key_bits // bits)

    # Pad to a chunk multiple with the max key: stability puts pad rows
    # after every real row of the same key, so perm[:n] is exactly the
    # real-row permutation.
    n_pad = -(-n // chunk) * chunk
    kpad = jnp.full((n_pad - n,), jnp.uint32(0xFFFFFFFF))
    k = jnp.concatenate([key, kpad]) if n_pad != n else key
    perm = jnp.arange(n_pad, dtype=jnp.int32)
    C = n_pad // chunk
    crange = jnp.arange(C, dtype=jnp.int32)[:, None]
    buckets = jnp.arange(B, dtype=jnp.int32)

    for p in range(n_passes):
        d = ((k >> (p * bits)) & (B - 1)).astype(jnp.int32).reshape(C, chunk)
        oh = (d[..., None] == buckets).astype(jnp.uint16)        # [C, M, B]
        within = jnp.cumsum(oh, axis=1, dtype=jnp.uint16) - oh   # exclusive
        rank = jnp.take_along_axis(within, d[..., None], axis=-1)[..., 0]
        hist = jnp.sum(oh, axis=1, dtype=jnp.uint32)             # [C, B]
        chunk_base = jnp.cumsum(hist, axis=0, dtype=jnp.uint32) - hist
        total = jnp.sum(hist, axis=0, dtype=jnp.uint32)          # [B]
        digit_base = jnp.cumsum(total, dtype=jnp.uint32) - total
        pos = (
            digit_base[d] + chunk_base[crange, d] + rank.astype(jnp.uint32)
        ).reshape(n_pad).astype(jnp.int32)
        perm = jnp.zeros_like(perm).at[pos].set(perm)
        k = jnp.zeros_like(k).at[pos].set(k)

    return perm[:n]
