"""Process stage: compaction + key sort in one multi-operand ``lax.sort``.

The reference runs two device passes: ``thrust::partition`` to push empty
emit slots to the tail (reference MapReduce/src/main.cu:411) then
``thrust::sort`` with the byte-loop ``KIVComparator`` over the live prefix
(main.cu:414-415, KeyValue.h:20-33).  That stage is 94% of its GPU runtime
(reference README.md:72-80) and is the headline perf target (BASELINE.json).

TPU-native formulation: ONE ``jax.lax.sort`` whose most-significant key is
the inverted validity bit and whose remaining keys are the big-endian uint32
key lanes.  Sorting ascending then yields exactly "valid entries first, in
lexicographic key order" — partition and sort fused into a single XLA sort,
with integer lane compares instead of a data-dependent byte loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from locust_tpu.core.kv import KVBatch


def sort_and_compact(batch: KVBatch) -> KVBatch:
    """Sort by (validity desc, key lex asc), carrying values along.

    Equivalent of partition+sort (main.cu:411-415) as one fused sort.
    """
    lanes = batch.key_lanes
    n_lanes = lanes.shape[-1]
    invalid = (~batch.valid).astype(jnp.uint32)            # 0 = valid, first
    operands = (
        invalid,
        *(lanes[:, i] for i in range(n_lanes)),
        batch.values,
    )
    out = jax.lax.sort(operands, num_keys=1 + n_lanes)
    sorted_valid = out[0] == 0
    sorted_lanes = jnp.stack(out[1 : 1 + n_lanes], axis=-1)
    sorted_values = out[1 + n_lanes]
    return KVBatch(
        key_lanes=sorted_lanes, values=sorted_values, valid=sorted_valid
    )
