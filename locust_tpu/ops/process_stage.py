"""Process stage: compaction + key-grouping sort in one ``lax.sort``.

The reference runs two device passes: ``thrust::partition`` to push empty
emit slots to the tail (reference MapReduce/src/main.cu:411) then
``thrust::sort`` with the byte-loop ``KIVComparator`` over the live prefix
(main.cu:414-415, KeyValue.h:20-33).  That stage is 94% of its GPU runtime
(reference README.md:72-80) and is the headline perf target (BASELINE.json).

TPU-native formulations, selected by ``EngineConfig.sort_mode`` (also
"hashp"/"hashp2"/"hashp1" = payload-carry at 3/2/1 hash key operands,
"hash1" = one folded 32-bit key + gather, "radix" = LSD counting sort,
"bitonic" = the hand-written Pallas VMEM-tiled network
(ops/pallas/sort.py), and "hasht" = the fold-level SORT-FREE hash-table
aggregation (ops/hash_table.py; this module serves its grouping-interface
consumers via the hashp1 formulation); see the variant functions below):

* **"lex"** — ONE multi-operand ``jax.lax.sort`` whose most-significant key
  is the inverted validity bit and whose remaining keys are the big-endian
  uint32 key lanes.  Ascending sort yields "valid entries first, in
  lexicographic key order": partition and sort fused into a single XLA sort,
  integer lane compares instead of a data-dependent byte loop.

* **"hash"** (default) — sort by ``(invalid, hash64(key))`` with only an
  index payload, then gather rows into place.  3 sort keys + 1 payload
  instead of 1+key_lanes keys: measured ~2x faster per sort and ~6x faster
  to XLA-compile on TPU v5e at 393k rows.  Equal keys still land adjacent
  (equal keys => equal hash), which is the only property the downstream
  segment reduce needs; it compares FULL key lanes at segment boundaries, so
  hash collisions between distinct keys cannot merge counts — the worst case
  (a full 64-bit collision interleaving two keys, ~n^2/2^64) duplicates a
  table row, which the host-side finalize re-merges.  Device order is hash
  order; lexicographic output order is restored host-side on a table that is
  orders of magnitude smaller than the emit stream.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from locust_tpu.config import HASHT_FAMILY
from locust_tpu.core import packing
from locust_tpu.core.kv import KVBatch

logger = logging.getLogger("locust_tpu")
_warned_bitonic_fallback = False
_warned_bitonic_interpret = False

# Trace-time "inside a mesh engine's shard_map step" marker.  On jax
# versions WITH ``jax.typeof`` the vma machinery already tells
# _bitonic_sort it is under a check_vma=True manual trace; on 0.4.x there
# is no vma to inspect (and compat_shard_map forces the replication check
# off), so the mesh engines mark their step bodies explicitly and the
# off-TPU segfault guard keys on this instead (CLAUDE.md: the interpret
# bitonic kernel inside a full mesh program crashes XLA's CPU compiler).
import contextlib as _contextlib
import contextvars as _contextvars

_IN_MESH_STEP = _contextvars.ContextVar("locust_in_mesh_step", default=False)


@_contextlib.contextmanager
def mesh_step_scope():
    """Engines wrap their shard_map step BODIES in this (active exactly
    while jax traces the per-device program)."""
    tok = _IN_MESH_STEP.set(True)
    try:
        yield
    finally:
        _IN_MESH_STEP.reset(tok)


def _vma_of(x) -> frozenset:
    """The array's varying-manual-axes set; empty on jax without typeof."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", None) or frozenset()

# Largest padded element count the INTERPRET-mode bitonic kernel (the
# off-TPU test vehicle) is allowed to trace: the interpreter re-traces
# every fused VMEM launch into one XLA program, and at production shapes
# under a mesh program that blows up the CPU compiler (observed: SIGSEGV
# inside XLA at full-hamlet mesh-merge shapes, 8 shards x 2^18 rows x 10
# operands).  Above the cap, off-TPU callers get the stock formulation
# with a loud one-time notice; on TPU the real Mosaic kernel always runs.
import os as _os

BITONIC_INTERPRET_MAX: int = int(
    _os.environ.get("LOCUST_BITONIC_INTERPRET_MAX", 1 << 16)
)


def sort_and_compact(batch: KVBatch, mode: str = "hash") -> KVBatch:
    """Group equal keys adjacently with valid rows first, carrying values.

    Equivalent of partition+sort (main.cu:411-415) as one fused sort.
    ``mode`` as in ``EngineConfig.sort_mode``.
    """
    if mode == "hash":
        return _hash_sort(batch)
    if mode == "hashp":
        return _hashp_sort(batch)
    if mode == "hashp2":
        return _hashp2_sort(batch)
    if mode == "hashp1":
        return _hashp1_sort(batch)
    if mode in HASHT_FAMILY:
        # The hasht family is a FOLD-level strategy
        # (ops/hash_table.aggregate_exact — "hasht-mxu" only changes the
        # fold's combine-scatter spelling; wired in engine.fold_block and
        # the mesh engines' merge / combiner sites); consumers of the
        # grouping interface proper (timed_run's split stages, the staged
        # CLI) get the stock formulation with the same key-grouping
        # guarantees.
        return _hashp1_sort(batch)
    if mode == "hash1":
        return _hash1_sort(batch)
    if mode == "radix":
        return _radix_sort(batch)
    if mode == "bitonic":
        return _bitonic_sort(batch)
    if mode == "lex":
        return _lex_sort(batch)
    raise ValueError(f"unknown sort mode {mode!r}")


def _lex_sort(batch: KVBatch) -> KVBatch:
    lanes = batch.key_lanes
    n_lanes = lanes.shape[-1]
    invalid = (~batch.valid).astype(jnp.uint32)            # 0 = valid, first
    operands = (
        invalid,
        *(lanes[:, i] for i in range(n_lanes)),
        batch.values,
    )
    out = jax.lax.sort(operands, num_keys=1 + n_lanes)
    sorted_valid = out[0] == 0
    sorted_lanes = jnp.stack(out[1 : 1 + n_lanes], axis=-1)
    sorted_values = out[1 + n_lanes]
    return KVBatch(
        key_lanes=sorted_lanes, values=sorted_values, valid=sorted_valid
    )


def _hash_sort(batch: KVBatch) -> KVBatch:
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n = lanes.shape[0]
    invalid = (~valid).astype(jnp.uint32)                  # 0 = valid, first
    h1, h2 = packing.hash_pair(lanes)
    idx = jnp.arange(n, dtype=jnp.int32)
    _, _, _, sidx = jax.lax.sort((invalid, h1, h2, idx), num_keys=3)
    return KVBatch(
        key_lanes=lanes[sidx], values=values[sidx], valid=valid[sidx]
    )


def _hashp_sort(batch: KVBatch) -> KVBatch:
    """Hash keys, rows ride as sort PAYLOADS — no post-sort gather.

    Same 3 sort keys as "hash" but the key lanes and values travel through
    ``lax.sort`` as payload operands instead of being gathered by a sorted
    index afterwards.  On TPU v5e at 720k rows this is ~19% faster than the
    gather form (artifacts/tpu_runs.jsonl sort_variants: C 67.4ms vs
    B 82.6ms) — the gather's random-access HBM reads cost more than
    carrying 9 extra payload operands through the sort's sequential passes.
    Collision/correctness story identical to "hash".
    """
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n_lanes = lanes.shape[-1]
    invalid = (~valid).astype(jnp.uint32)                  # 0 = valid, first
    h1, h2 = packing.hash_pair(lanes)
    out = jax.lax.sort(
        (invalid, h1, h2, *(lanes[:, i] for i in range(n_lanes)), values),
        num_keys=3,
    )
    return KVBatch(
        key_lanes=jnp.stack(out[3 : 3 + n_lanes], axis=-1),
        values=out[3 + n_lanes],
        valid=out[0] == 0,
    )


def _hashp2_sort(batch: KVBatch) -> KVBatch:
    """2 sort keys + payload-carry: validity folded into the primary hash.

    Like "hashp" but the invalid flag rides in the top bit of a 31-bit
    primary hash (``_folded_key``) with the full h2 as tiebreaker — one
    fewer key operand per sort pass.  Valid rows keep ``h1 >> 1`` (top bit
    0, < 0x80000000), invalid rows get 0xFFFFFFFF, so ascending order is
    still valid-first and validity is reconstructed from the sorted key.
    Grouping tiebreak is 31+32 hash bits; as everywhere, the segment
    reduce compares full key lanes at boundaries so collisions only
    duplicate a table row (re-merged downstream).  Micro-bench: ~19%
    faster than "hashp" on CPU at 393k rows
    (artifacts/sort_variants_cpu_r3.jsonl G_hash2_payload vs
    C_hash3_payload); TPU A/B armed in scripts/bench_sort_variants.py.
    """
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n_lanes = lanes.shape[-1]
    h1, h2 = packing.hash_pair(lanes)
    folded = jnp.where(valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))
    out = jax.lax.sort(
        (folded, h2, *(lanes[:, i] for i in range(n_lanes)), values),
        num_keys=2,
    )
    return KVBatch(
        key_lanes=jnp.stack(out[2 : 2 + n_lanes], axis=-1),
        values=out[2 + n_lanes],
        valid=out[0] < jnp.uint32(0x80000000),
    )


def _hashp1_sort(batch: KVBatch) -> KVBatch:
    """1 sort key + payload-carry: the minimum-traffic lax.sort formulation.

    One step further down the ladder from "hashp2": the single folded
    31-bit key (``_folded_key``: validity in the top bit) with NO h2
    tiebreaker, rows riding as payloads — 6 uint32 operands per pass vs
    hashp2's 7, i.e. ~14% less HBM traffic through the stage the whole
    pipeline is bottlenecked on.  Collision story is exactly "hash1"'s
    (same 31-bit grouping key, already shipped): ~C(n,2)/2^31 colliding
    pairs interleave within a hash run, the segment reduce's full-lane
    boundary compare splits them into duplicate table rows, and the next
    fold or the host finalize re-merges those — never a wrong count.
    Hardware A/B rides scripts/opp_resume.py phase 3.
    """
    lanes, values = batch.key_lanes, batch.values
    n_lanes = lanes.shape[-1]
    out = jax.lax.sort(
        (_folded_key(batch), *(lanes[:, i] for i in range(n_lanes)), values),
        num_keys=1,
    )
    return KVBatch(
        key_lanes=jnp.stack(out[1 : 1 + n_lanes], axis=-1),
        values=out[1 + n_lanes],
        valid=out[0] < jnp.uint32(0x80000000),
    )


def _folded_key(batch: KVBatch) -> jax.Array:
    """ONE uint32 sort key: 31 hash bits + validity in the top bit.

    Invalid rows get the max key, so ascending order is valid-first —
    partition and grouping in a single-operand sort.  Collisions between
    distinct keys (~n^2/2^31 per sort) interleave within a hash run; the
    downstream segment reduce compares FULL key lanes at boundaries, so
    the worst case is a duplicated table row which the next fold (same
    hash -> adjacent again) or the host finalize re-merges — the same
    safety argument as the 64-bit "hash" mode at half the sort-key
    bandwidth (scripts/bench_sort_variants.py variants D/E).
    """
    h1, _ = packing.hash_pair(batch.key_lanes)
    return jnp.where(batch.valid, h1 >> 1, jnp.uint32(0xFFFFFFFF))


def _hash1_sort(batch: KVBatch) -> KVBatch:
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    idx = jnp.arange(lanes.shape[0], dtype=jnp.int32)
    _, sidx = jax.lax.sort((_folded_key(batch), idx), num_keys=1)
    return KVBatch(
        key_lanes=lanes[sidx], values=values[sidx], valid=valid[sidx]
    )


def _radix_sort(batch: KVBatch) -> KVBatch:
    """LSD radix passes over the folded key (ops/radix_sort.py) — the O(n)
    alternative to lax.sort's comparison network for the Process stage."""
    from locust_tpu.ops.radix_sort import radix_argsort

    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    sidx = radix_argsort(_folded_key(batch))
    return KVBatch(
        key_lanes=lanes[sidx], values=values[sidx], valid=valid[sidx]
    )


def _bitonic_sort(batch: KVBatch) -> KVBatch:
    """Hand-written Pallas bitonic network over the folded key, row as
    payload (ops/pallas/sort.py): "hash1"'s single 31-bit-hash+validity
    operand with "hashp"'s payload carriage, but the tile-local compare
    passes run in VMEM instead of streaming HBM.  Interpret mode engages
    automatically off-TPU (slow; CI uses small shapes) and is CAPPED at
    BITONIC_INTERPRET_MAX padded elements — beyond it, off-TPU callers
    get the stock formulation with a one-time notice (the interpreter's
    re-trace crashes the CPU XLA compiler at production mesh shapes);
    on TPU the Mosaic kernel always runs.

    Inside a ``shard_map(check_vma=True)`` manual trace the kernel
    cannot trace: jax's vma machinery breaks inside the pallas
    interpret re-trace (a mixed-vma ``lt``; a ``pvary`` re-attach fails
    again in the physical-type re-trace).  BOTH mesh engines therefore
    pass ``check_vma=False`` on their round step when this mode is
    configured (shuffle.py and hierarchical.py engine ctors;
    hierarchical's sync/combine shard_maps are check_vma=False for
    their own all_gather-replication reason), which removes vma types
    entirely and the kernel RUNS — pinned by
    tests/test_distributed.py::test_mesh_engines_run_bitonic_kernel.
    This fallback remains only for third-party shard_map sites that
    keep check_vma=True: there the mode serves the semantically
    IDENTICAL stock formulation — same single folded-key operand, same
    payload carriage via ``lax.sort`` — with a loud one-time warning so
    no A/B can silently time the fallback believing it measured the
    kernel."""
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n_lanes = lanes.shape[-1]
    folded = _folded_key(batch)
    vma = frozenset().union(
        *(_vma_of(x) for x in (folded, lanes, values))
    )
    # Legacy jax (no typeof/vma): the engines' explicit mesh-step marker
    # stands in for the vma signal — off-TPU mesh programs must take the
    # same stock fallback (the interpret kernel inside a full mesh
    # program is the CPU-compiler segfault class, CLAUDE.md).
    legacy_mesh_cpu = (
        not hasattr(jax, "typeof")
        and _IN_MESH_STEP.get()
        and jax.default_backend() != "tpu"
    )
    if vma or legacy_mesh_cpu:
        # Loud once: evidence recorded as sort_mode="bitonic" on a mesh
        # engine measured THIS stock formulation, not the Pallas kernel —
        # a silent substitution would let a future A/B conclude the
        # kernel gives no mesh speedup when it never ran.
        global _warned_bitonic_fallback  # locust: noqa[R002] deliberate warn-once AT TRACE TIME: the substitution notice must fire exactly when tracing picks the stock fallback
        if not _warned_bitonic_fallback:
            _warned_bitonic_fallback = True
            logger.warning(
                "sort_mode='bitonic' inside shard_map(check_vma=True): "
                "jax's vma machinery cannot trace the Pallas kernel "
                "(mixed-vma compare in the pallas interpret re-trace); "
                "using the equivalent stock lax.sort formulation — these "
                "timings do NOT measure the hand-written kernel.  On TPU "
                "the built-in mesh engines avoid this by passing "
                "check_vma=False for this mode (off-TPU they keep the "
                "check: the interpret kernel inside a mesh program can "
                "crash XLA's CPU compiler)"
            )
        # The stock formulation of the same sort IS mode "hashp1" —
        # delegate so "semantically identical" stays true by construction.
        return _hashp1_sort(batch)
    from locust_tpu.ops.pallas.sort import bitonic_sort

    interpret = jax.default_backend() != "tpu"
    n_pad = max(1 << 10, 1 << max(batch.size - 1, 1).bit_length())
    if interpret and not hasattr(jax, "typeof"):
        # Legacy jax (0.4.x): the engines share one process with mesh
        # programs there (compat_shard_map), and the interpret kernel's
        # re-trace alongside accumulated mesh-program state has crashed
        # XLA's CPU compiler at FUZZ shapes too (the CLAUDE.md segfault
        # class, reproduced suite-order-dependently on 0.4.37) — so
        # engine dispatch never takes interpret mode on legacy jax; the
        # kernel's interpret traceability stays covered by the direct
        # small tests (tests/test_bitonic.py, test_distributed.py).
        global _warned_bitonic_interpret  # locust: noqa[R002] deliberate warn-once AT TRACE TIME: the legacy-jax interpret-skip notice must fire exactly when tracing takes this branch
        if not _warned_bitonic_interpret:
            _warned_bitonic_interpret = True
            logger.warning(
                "sort_mode='bitonic' off-TPU on jax %s: interpret-mode "
                "kernel skipped on legacy jax (CPU-compiler crash risk "
                "alongside mesh programs); using the equivalent stock "
                "lax.sort formulation", jax.__version__,
            )
        return _hashp1_sort(batch)
    if interpret and n_pad > BITONIC_INTERPRET_MAX:
        # Interpret mode is the off-TPU TEST vehicle; at production
        # shapes its re-trace of every fused launch crashes the CPU
        # XLA compiler (SIGSEGV at mesh-merge shapes).  Off-TPU big
        # sorts take the stock formulation — loudly, so no CPU timing
        # can be mistaken for a kernel measurement.
        if not _warned_bitonic_interpret:
            _warned_bitonic_interpret = True
            logger.warning(
                "sort_mode='bitonic' off-TPU at %d rows (> %d): interpret-"
                "mode kernel skipped; using the equivalent stock lax.sort "
                "formulation (LOCUST_BITONIC_INTERPRET_MAX overrides)",
                batch.size, BITONIC_INTERPRET_MAX,
            )
        return _hashp1_sort(batch)
    key, pays = bitonic_sort(
        folded,
        tuple(lanes[:, i] for i in range(n_lanes)) + (values,),
        interpret=interpret,
    )
    return KVBatch(
        key_lanes=jnp.stack(pays[:n_lanes], axis=-1),
        values=pays[n_lanes],
        valid=key < jnp.uint32(0x80000000),
    )
