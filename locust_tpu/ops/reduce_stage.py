"""Reduce stage: segment boundaries + segment combine.

The reference reduces in three device phases (reference
MapReduce/src/main.cu:161-238,447-465): ``kernFindUniqBool`` marks rows whose
key differs from the left neighbor, ``thrust::partition`` compacts the
boundary markers, and ``kernGetCount`` takes adjacent differences of boundary
indices to recover per-key counts.  That construction is the hand-rolled form
of a textbook vectorized identity (SURVEY.md §7.1):

    boundary_i  = valid_i & (i == 0 | key_i != key_{i-1})
    segment_ids = cumsum(boundary) - 1
    combined    = segment_combine(values, segment_ids)

which is how it is written here — one pass, no phase barriers, and it
generalizes beyond counting: any monoid (sum/min/max) is a drop-in
``jax.ops.segment_*``.  The reference's count-by-index-difference only works
because every value is 1; ``segment_sum`` over the actual values subsumes it.

Input must be key-sorted with valid rows first (ops/process_stage.py), the
same precondition the reference's reduce has — and which its distributed mode
silently violates (SURVEY.md Q6); our distributed path re-sorts after the
shuffle instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from locust_tpu.core.kv import KVBatch

# Monoid combiners available to reduce_fn. "count" treats every value as 1
# (the reference's WordCount semantics even if upstream emitted other values).
COMBINERS = ("sum", "min", "max", "count")


def normalize_combine(map_fn, combine: str):
    """Lower "count" to an associative form for MULTI-LEVEL engines.

    "count" is not a monoid over its own outputs: merging two per-key
    counts must SUM them, while a second ``segment_reduce(..., "count")``
    would count table ROWS — every engine that folds partial tables
    (block accumulator, cross-round shard carry, cross-slice combine)
    would return the number of partials holding the key, not the count.
    The associative equivalent is exact: emit value 1 at the leaves and
    sum at every level.  Returns ``(map_fn', combine')``; identity for
    the genuinely associative combiners.  Single-level uses (one
    ``segment_reduce`` over raw emits, e.g. the inverted index's postings
    counts) keep calling "count" directly.
    """
    if combine != "count":
        return map_fn, combine

    def count_map(lines, cfg, _base=map_fn):
        kv, overflow = _base(lines, cfg)
        return (
            KVBatch(
                key_lanes=kv.key_lanes,
                values=jnp.ones_like(kv.values),
                valid=kv.valid,
            ),
            overflow,
        )

    count_map.__name__ = f"count_of_{getattr(map_fn, '__name__', 'map_fn')}"
    return count_map, "sum"


def segment_reduce_into(
    batch: KVBatch, out_size: int, combine: str = "sum"
) -> tuple[KVBatch, jax.Array]:
    """Segment-combine a key-grouped batch into a compact ``out_size`` table.

    Returns ``(table, num_segments)`` where ``table`` holds the first
    ``out_size`` segments (in input order) and ``num_segments`` is the TRUE
    distinct-key count (may exceed ``out_size`` — the caller's truncation
    signal).

    This is ``segment_reduce`` with the head-slice fused in: the key-row
    gather and the value scatter both touch ``out_size`` rows instead of the
    full batch — on TPU v5e, gathering/scattering [n, lanes] rows at the
    full emit-stream size is ~60% of the whole reduce stage, and the engine
    immediately slices to table capacity anyway (engine.py fold_block).
    """
    if combine not in COMBINERS:
        raise ValueError(f"combine must be one of {COMBINERS}, got {combine!r}")
    lanes, values, valid = batch.key_lanes, batch.values, batch.valid
    n = lanes.shape[0]

    prev = jnp.roll(lanes, 1, axis=0)
    neq = jnp.any(lanes != prev, axis=-1)
    first = jnp.arange(n) == 0
    boundary = valid & (first | neq)                        # [N]
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1        # [N]
    num_segments = jnp.sum(boundary.astype(jnp.int32))
    # Segments beyond out_size and invalid rows all fold into the dump slot.
    ids = jnp.where(valid, jnp.minimum(seg, out_size), out_size)

    if combine == "sum":
        combined = jax.ops.segment_sum(values, ids, num_segments=out_size + 1)
    elif combine == "count":
        combined = jax.ops.segment_sum(
            jnp.ones_like(values), ids, num_segments=out_size + 1
        )
    elif combine == "min":
        combined = jax.ops.segment_min(values, ids, num_segments=out_size + 1)
    else:  # max
        combined = jax.ops.segment_max(values, ids, num_segments=out_size + 1)
    combined = combined[:out_size]

    # First row index of each kept segment (scatter-min, 1-wide), then a
    # row gather of only out_size key rows.
    start = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32),
        jnp.where(boundary, jnp.minimum(seg, out_size), out_size),
        num_segments=out_size + 1,
    )[:out_size]
    out_valid = jnp.arange(out_size, dtype=jnp.int32) < num_segments
    safe_start = jnp.where(out_valid, start, 0)
    out_lanes = lanes[safe_start] * out_valid[:, None].astype(lanes.dtype)
    return (
        KVBatch(
            key_lanes=out_lanes,
            values=jnp.where(out_valid, combined, 0),
            valid=out_valid,
        ),
        num_segments,
    )


def segment_reduce(batch: KVBatch, combine: str = "sum") -> KVBatch:
    """Combine values of equal adjacent keys; output keeps input key order.

    Returns a same-capacity KVBatch whose first ``num_segments`` rows are the
    unique keys (in order) with combined values; the tail is invalid.
    Same-capacity special case of ``segment_reduce_into``.
    """
    return segment_reduce_into(batch, batch.size, combine)[0]
