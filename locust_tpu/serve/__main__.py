"""CLI surface for the serve tier.

    python -m locust_tpu.serve [--host H] [--port P] [--secret-env VAR]
        [--max-queue N] [--max-batch N] [--warm-dir DIR]
        [--workers H:P,H:P] [--shard-min-blocks N]
        [--fault-plan PLAN] [--trace-out FILE]        # run the daemon
        # --workers: scale-out dispatch across serve-capable distributor
        # workers (each started with --serve); docs/SERVING.md

    python -m locust_tpu.serve submit FILE [--tenant T] [--weight W]
        [--block-lines N] [--sort-mode M] [--no-wait] ...   # one job
    python -m locust_tpu.serve submit FILE --plan PLAN.json # a dataflow
        # plan job (docs/PLAN.md): FILE is the corpus (text or an edge
        # list), PLAN.json the validated plan document; the result is
        # the pipeline's rendered output, byte-identical to the
        # hand-wired CLI over the same input
    python -m locust_tpu.serve result JOB_ID [--wait]       # fetch by id
    python -m locust_tpu.serve stats                        # daemon stats
    python -m locust_tpu.serve shutdown                     # stop it
    python -m locust_tpu.serve promote [--port P]           # standby ->
        # primary takeover (docs/SERVING.md "High availability"); run
        # the standby with --journal-dir DIR --standby-of H:P and the
        # primary with --journal-dir DIR --ship-to H:P; client commands
        # take --daemon H:P,H:P (an HA roster with transparent
        # not_primary redirect following)

A structured daemon rejection (``ServeError``) prints as
``error: [code] message`` and exits 1 — the code is the machine-readable
part (``queue_full`` -> back off, ``not_done`` -> poll again, ...).

The daemon refuses to start without a shared secret (same Q8 stance as
the distributor worker); clients read the same env var.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from locust_tpu.utils import faultplan

_CLIENT_CMDS = ("submit", "result", "stats", "shutdown", "promote")


def _secret(args) -> bytes:
    secret = os.environ.get(args.secret_env, "").encode()
    if not secret:
        print(f"error: set ${args.secret_env} (refusing unauthenticated "
              "mode)", file=sys.stderr)
        raise SystemExit(2)
    return secret


def _daemon_main(argv) -> int:
    p = argparse.ArgumentParser(prog="locust-serve")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1347)
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--tenant-quota", type=int, default=32,
                   help="pending jobs per tenant (0 = unlimited)")
    p.add_argument("--warm-dir", default=None,
                   help="persist the result cache across restarts here "
                        "(async snapshot writer, docs/SERVING.md)")
    p.add_argument("--journal-dir", default=None,
                   help="write-ahead job journal: accepted jobs survive "
                        "kill -9 and replay on restart (docs/SERVING.md "
                        "durability)")
    p.add_argument("--fault-plan", default=None,
                   help="chaos-test fault plan: JSON text or a path "
                        f"(also ${faultplan.ENV_VAR}); see docs/FAULTS.md")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="export the daemon's serve.* telemetry as "
                        "Chrome-trace JSON at exit (docs/OBSERVABILITY.md)")
    p.add_argument("--workers", default=None, metavar="H:P,H:P",
                   help="scale-out dispatch: comma-separated host:port "
                        "roster of serve-capable distributor workers "
                        "(python -m locust_tpu.distributor.worker "
                        "--serve); batches place across them with "
                        "cache affinity, the local engine stays the "
                        "floor (docs/SERVING.md)")
    p.add_argument("--shard-min-blocks", type=int, default=64,
                   help="blocks at which a large job fans out across "
                        "the worker pool (with --workers)")
    p.add_argument("--ship-to", default=None, metavar="H:P",
                   help="high availability (docs/SERVING.md): ship every "
                        "fsync'd WAL record to the hot-standby daemon at "
                        "this address (requires --journal-dir; shipping "
                        "is async — a dead standby never slows admits)")
    p.add_argument("--standby-of", default=None, metavar="H:P",
                   help="start as a HOT STANDBY of the primary at this "
                        "address (requires --journal-dir): apply shipped "
                        "WAL records, answer stats/ping, refuse the job "
                        "plane with not_primary until promoted "
                        "(`... promote` or --lease expiry)")
    p.add_argument("--lease", type=float, default=None, metavar="S",
                   help="standby auto-promotion: take over after S "
                        "seconds without primary contact (default: "
                        "manual `promote` only)")
    args = p.parse_args(argv)
    faultplan.install(args.fault_plan)
    from locust_tpu import obs

    if args.trace_out:
        obs.enable(process="serve")
    try:
        daemon = _build_daemon(args)
    except ValueError as e:
        # Config refusals (e.g. --ship-to without --journal-dir) are an
        # operator one-liner, not a traceback.
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"[serve] listening on {daemon.addr[0]}:{daemon.addr[1]}"
          + (f" (role {daemon.role})" if (args.standby_of or args.ship_to)
             else ""),
          file=sys.stderr)
    try:
        daemon.serve_forever()
    except KeyboardInterrupt:
        # serve_forever's finally already flushed warm state + closed.
        print("[serve] interrupted; warm state flushed", file=sys.stderr)
    finally:
        if args.trace_out:
            try:
                obs.export(args.trace_out)
                print(f"[serve] trace written to {args.trace_out}",
                      file=sys.stderr)
            except OSError as e:
                print(f"[serve] trace export failed: {e}", file=sys.stderr)
            obs.disable()
    return 0


def _build_daemon(args):
    from locust_tpu.serve.daemon import ServeConfig, ServeDaemon

    return ServeDaemon(
        args.host, args.port, _secret(args),
        cfg=ServeConfig(
            max_queue=args.max_queue,
            max_batch=args.max_batch,
            tenant_quota=args.tenant_quota,
            warm_dir=args.warm_dir,
            journal_dir=args.journal_dir,
            workers=tuple(
                w.strip() for w in (args.workers or "").split(",")
                if w.strip()
            ),
            shard_min_blocks=args.shard_min_blocks,
            ship_to=args.ship_to,
            standby_of=args.standby_of,
            lease_s=args.lease,
        ),
    )


def _client(args):
    from locust_tpu.serve.client import ServeClient

    # --daemon H:P[,H:P...] is the HA roster spelling: the client tries
    # each address, follows not_primary redirects, and sticks to
    # whoever answers — submit/result/stats survive a takeover without
    # the operator editing commands (docs/SERVING.md).
    addr = getattr(args, "daemon", None) or (args.host, args.port)
    return ServeClient(addr, _secret(args))


def _add_daemon_arg(p) -> None:
    p.add_argument("--daemon", default=None, metavar="H:P[,H:P]",
                   help="daemon address roster (overrides --host/--port): "
                        "multiple addresses = HA failover, the client "
                        "follows not_primary redirects transparently")


def _submit_main(argv) -> int:
    p = argparse.ArgumentParser(prog="locust-serve submit")
    p.add_argument("file", help="corpus file (sent inline)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1347)
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    _add_daemon_arg(p)
    p.add_argument("--tenant", default="default")
    # Default None, not "wordcount": an explicitly named workload must
    # stay distinguishable so --plan + --workload is a loud conflict
    # (the client fills in the wordcount default for plain submits).
    p.add_argument("--workload", default=None)
    p.add_argument("--weight", type=float, default=1.0)
    p.add_argument("--block-lines", type=int, default=None)
    p.add_argument("--sort-mode", default=None)
    p.add_argument("--table-size", type=int, default=None)
    p.add_argument("--line-width", type=int, default=None)
    p.add_argument("--key-width", type=int, default=None)
    p.add_argument("--emits-per-line", type=int, default=None)
    p.add_argument("--deadline", type=float, default=None, metavar="S",
                   help="whole-job deadline: expiry anywhere answers the "
                        "structured deadline_exceeded code")
    p.add_argument("--max-attempts", type=int, default=None, metavar="N",
                   help="dispatches this job may kill before it is "
                        "quarantined as poison_job (default 4)")
    p.add_argument("--invalidate", action="store_true",
                   help="drop any cached result for this job first")
    p.add_argument("--no-wait", action="store_true",
                   help="print the job id and return without waiting")
    p.add_argument("--plan", default=None, metavar="PLAN.json",
                   help="submit FILE through a composable dataflow plan "
                        "(a JSON plan document, docs/PLAN.md) instead of "
                        "a named workload; the daemon validates it and "
                        "keys its caches off the plan fingerprint")
    args = p.parse_args(argv)
    with open(args.file, "rb") as f:
        corpus = f.read()
    plan_doc = None
    if args.plan is not None:
        if args.workload is not None:
            print("error: submit takes --plan OR --workload, not both",
                  file=sys.stderr)
            return 2
        with open(args.plan, "r", encoding="utf-8") as f:
            plan_doc = f.read()
    config = {
        k: v
        for k, v in (
            ("block_lines", args.block_lines),
            ("sort_mode", args.sort_mode),
            ("table_size", args.table_size),
            ("line_width", args.line_width),
            ("key_width", args.key_width),
            ("emits_per_line", args.emits_per_line),
        )
        if v is not None
    }
    client = _client(args)
    ack = client.submit(
        corpus=corpus, tenant=args.tenant, workload=args.workload,
        config=config or None, weight=args.weight,
        invalidate=args.invalidate,
        deadline_s=args.deadline, max_attempts=args.max_attempts,
        plan=plan_doc,
    )
    print(f"[serve] job {ack['job_id']} {ack['state']}"
          + (" (cached)" if ack.get("cached") else ""), file=sys.stderr)
    if args.no_wait:
        print(ack["job_id"])
        return 0
    _print_result(client.wait(ack["job_id"]))
    return 0


def _print_result(res: dict) -> None:
    if res.get("plan"):
        # A plan job's result is the pipeline's sink-rendered output as
        # ONE (bytes, 0) pair — print it raw, byte-identical to the
        # hand-wired CLI (docs/PLAN.md), not as a key<TAB>count table.
        for k, _ in res["pairs"]:
            sys.stdout.buffer.write(k)
        sys.stdout.buffer.flush()
    else:
        for k, v in sorted(res["pairs"]):
            sys.stdout.buffer.write(k + b"\t" + str(v).encode() + b"\n")
    print(
        f"[serve] {res['distinct']} distinct, cache={res['cache']}, "
        f"latency {res['latency_ms']} ms", file=sys.stderr,
    )


def _result_main(argv) -> int:
    """Fetch a job submitted earlier with ``--no-wait`` — without this
    command a detached submit's id would be a dead end the protocol can
    answer but the CLI cannot."""
    p = argparse.ArgumentParser(prog="locust-serve result")
    p.add_argument("job_id", help="id printed by `submit --no-wait`")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1347)
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    _add_daemon_arg(p)
    p.add_argument("--wait", action="store_true",
                   help="poll until the job finishes instead of "
                        "answering not_done")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="--wait deadline in seconds")
    args = p.parse_args(argv)
    client = _client(args)
    if args.wait:
        res = client.wait(args.job_id, timeout=args.timeout)
    else:
        res = client.result(args.job_id)
    _print_result(res)
    return 0


def _stats_main(argv, cmd: str) -> int:
    p = argparse.ArgumentParser(prog=f"locust-serve {cmd}")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1347)
    p.add_argument("--secret-env", default="LOCUST_SECRET")
    _add_daemon_arg(p)
    args = p.parse_args(argv)
    client = _client(args)
    if cmd == "shutdown":
        client.shutdown()
        print("[serve] daemon shutting down", file=sys.stderr)
        return 0
    if cmd == "promote":
        # Fenced takeover (docs/SERVING.md "High availability"): point
        # this at the STANDBY — it bumps the epoch, replays the
        # replicated WAL and starts dispatching; the old primary is
        # fenced out by the higher epoch wherever it reappears.
        res = client.promote()
        print(f"[serve] promoted: role={res['role']} epoch={res['epoch']}",
              file=sys.stderr)
        return 0
    print(json.dumps(client.stats(), indent=2, default=str))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] in _CLIENT_CMDS:
        from locust_tpu.serve.client import ServeError

        cmd, rest = argv[0], argv[1:]
        try:
            if cmd == "submit":
                return _submit_main(rest)
            if cmd == "result":
                return _result_main(rest)
            return _stats_main(rest, cmd)
        except ServeError as e:
            # A structured daemon answer is an exit code + one line,
            # never a traceback.
            print(f"error: {e}", file=sys.stderr)
            return 1
    return _daemon_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
