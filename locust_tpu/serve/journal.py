"""Write-ahead job journal: accepted work survives a SIGKILL.

The serve tier's PR 7 guarantee — correct result or structured error —
held only while the daemon lived: a ``kill -9`` lost every accepted-but-
unfinished job, exactly the gap the original MapReduce closed with
deterministic re-execution (Dean & Ghemawat, OSDI '04) and the reference
Locust never closed at all (its master is absent from the repo).  This
module is the durability half of that contract (docs/SERVING.md):

  * **append-before-ack**: every admitted job appends one fsync'd JSONL
    record — tenant, workload, config overrides, deadline/retry budget,
    corpus sha256 + spill path — BEFORE the client's accept ack leaves
    the daemon.  An acked job is therefore always replayable; a job lost
    in the append window was never acked, so the client retries.
  * **corpus spill**: the inline corpus bytes land content-addressed
    under ``<journal_dir>/corpus/<sha256>.bin`` (dedup'd across jobs and
    integrity-checked on read) so replay can re-stage the exact bytes.
  * **state records**: terminal transitions (done / failed / cancelled /
    rejected) append flush-only records — losing one costs a replayed
    RECOMPUTE (deterministic, byte-identical), never a wrong answer, so
    they skip the fsync the admit record must pay.
  * **replay**: ``replay()`` parses the journal tolerantly (a torn or
    corrupt line is skipped with a warning — that is what a crash
    mid-append leaves) and returns per-job admit records plus the last
    terminal state; the daemon re-enqueues unfinished jobs under their
    ORIGINAL ids and compacts the log.
  * **compaction**: ``compact()`` atomically rewrites the journal to
    just the still-live admit records (liveness decided from the
    journal's own records, under the same lock that serializes appends
    and spills — concurrent admits can never be dropped) and deletes
    unreferenced spills — run at replay, at clean shutdown, and every
    ``compact_every`` appends so a long-lived daemon's journal stays
    O(queue), not O(history).

Chaos: the ``serve.journal`` site (utils/faultplan.py) fires inside the
append — "crash" writes a TORN record then raises (the daemon dying at
the append point; the submit is rejected structured, never acked),
"corrupt" mangles the record bytes silently (replay must skip the line).
jax-free at import, like the rest of the serve control plane.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time

from locust_tpu import obs
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")

JOURNAL_FILE = "journal.jsonl"
CORPUS_DIR = "corpus"

# Journal format version: an old daemon's journal is replayed by a new
# one only when the record layout still matches; a skew is a loud warning
# and a skipped record, never a crash (same stance as the warm file).
JOURNAL_VERSION = 1

# Terminal states a "state" record may carry.  "rejected" is journal-only
# (an admit that scheduler admission then refused — replay must not
# resurrect it); the rest mirror jobs.JOB_STATES terminals.
TERMINAL_STATES = ("done", "failed", "cancelled", "rejected")


def admit_record(job) -> dict:
    """The ONE admit-record shape, shared by the append path and the
    daemon's compaction (which rebuilds live records from its in-memory
    jobs) — two spellings of the record would drift."""
    spec = job.spec
    rec = {
        "rec": "admit",
        "v": JOURNAL_VERSION,
        "job_id": job.job_id,
        "tenant": spec.tenant,
        "workload": spec.workload,
        "config": dict(job.config_overrides or {}),
        "weight": spec.weight,
        "no_cache": spec.no_cache,
        "deadline_s": spec.deadline_s,
        "max_attempts": spec.max_attempts,
        "corpus_sha": job.corpus_digest,
        "n_lines": job.n_lines,
        "t": time.time(),
    }
    if spec.plan is not None:
        # Plan jobs journal the WHOLE plan document (docs/PLAN.md): the
        # WAL is what makes the accept ack a durable promise, and for an
        # arbitrary pipeline the plan IS the half of the work the corpus
        # spill does not carry — replay re-validates and re-executes it
        # under the original id.  Additive: pre-plan records simply lack
        # the key and replay exactly as before.
        rec["plan"] = json.loads(spec.plan)
    return rec


class JournalEntry:
    """One replayable job: its admit record + last terminal state +
    any distributed-plan stage-progress records (docs/PLAN.md
    "Distributed execution") journaled before the crash."""

    __slots__ = ("admit", "terminal", "stages")

    def __init__(self, admit: dict, terminal: dict | None = None):
        self.admit = admit
        self.terminal = terminal
        self.stages: list[dict] = []


class JobJournal:
    """Append-only JSONL write-ahead log + content-addressed corpus spill.

    Thread-safe: handler threads append admits, the dispatcher appends
    state records and compacts; one lock serializes the file.  Append
    latency is accounted (``serve.journal_ms`` histogram + ``stats()``)
    because the admit-path fsync is the one cost durability adds to the
    accept ack — the bench "recovery" sub-dict pins it under 5% of admit
    latency.
    """

    def __init__(self, journal_dir: str, fsync: bool = True,
                 compact_every: int = 512):
        self.dir = journal_dir
        self.fsync = fsync
        # Replication hook (serve/replicate.py, docs/SERVING.md "High
        # availability"): called with every record dict AFTER it landed
        # durably (never for a torn/failed append — an unacked record
        # must not reach the standby).  Set by the daemon; must be fast
        # and non-raising (it only enqueues on the async shipper).
        self.on_append = None
        self.compact_every = max(1, int(compact_every))
        self._corpus_dir = os.path.join(journal_dir, CORPUS_DIR)
        os.makedirs(self._corpus_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, JOURNAL_FILE)
        # Reentrant: append_admit holds it across spill + record so
        # compaction's GC can never see (and sweep) a spill whose admit
        # record has not landed yet.
        self._lock = threading.RLock()
        self._fh = open(self.path, "ab")
        # A journal inherited from a crash may end mid-line (a torn
        # append): the next append must start on a fresh line or the
        # first post-restart record glues onto the debris and parses as
        # garbage — losing a perfectly good record to someone else's
        # torn write.
        try:
            size = os.path.getsize(self.path)
            if size:
                with open(self.path, "rb") as f:
                    f.seek(size - 1)
                    self._dirty_tail = f.read(1) != b"\n"
            else:
                self._dirty_tail = False
        except OSError:  # pragma: no cover - defensive
            self._dirty_tail = True
        self._appends_since_compact = 0
        self._appends = 0
        self._append_ms = 0.0
        self._spills = 0
        self._spill_ms = 0.0
        self._last_compact_t: float | None = None

    @property
    def corpus_dir(self) -> str:
        """The content-addressed spill directory.  The serve worker
        pool shares it (serve/pool.py): admitted corpora are already
        spilled here once, so a pool dispatch ships a reference, not
        bytes."""
        return self._corpus_dir

    # ------------------------------------------------------------- appends

    def append_admit(self, job, corpus: bytes) -> None:
        """Spill the corpus, then durably append the admit record.

        MUST complete before the accept ack: the record is what makes
        the ack a promise.  Raises on chaos crash or a real disk error —
        the caller rolls the admission back and answers structured.
        Spill and record costs are accounted separately (``stats()``):
        the record append is the O(1) per-admit WAL cost, the spill a
        corpus-proportional buffer write (dedup'd by sha, so repeat
        corpora pay it once).
        """
        with self._lock:
            # ONE lock hold across spill + record (reentrant lock): a
            # compaction between them would GC the not-yet-referenced
            # spill, turning this acked job's replay into a structured
            # spill-missing failure.
            t0 = time.perf_counter()
            self._spill(job.corpus_digest, corpus)
            self._spills += 1
            self._spill_ms += (time.perf_counter() - t0) * 1e3
            self._append(admit_record(job), durable=True)

    def append_state(self, job_id: str, state: str,
                     error: dict | None = None) -> None:
        """Flush-only terminal-state record (see module docstring for why
        these skip the fsync the admit record pays)."""
        if state not in TERMINAL_STATES:
            raise ValueError(f"not a terminal journal state: {state!r}")
        rec = {"rec": "state", "job_id": job_id, "state": state,
               "t": time.time()}
        if error is not None:
            rec["error"] = dict(error)
        self._append(rec, durable=False)

    def append_stage(self, job_id: str, stage: dict) -> None:
        """Flush-only stage-progress record for a distributed plan
        (docs/PLAN.md "Distributed execution"): one per completed map
        split, carrying its published partition references.  Replay
        hands them back so a restarted coordinator RESUMES from the
        splits whose partitions survived on disk instead of re-running
        the whole map wave.  Flush-only like state records: losing one
        to a crash only costs a recompute — the fsync'd admit record
        (which carries the whole plan) already guarantees the answer."""
        rec = {"rec": "stage", "job_id": job_id, "stage": dict(stage),
               "t": time.time()}
        self._append(rec, durable=False)

    def _append(self, rec: dict, durable: bool) -> None:
        data = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
        rule = faultplan.fire(
            "serve.journal", rec=rec["rec"], job=rec.get("job_id")
        )
        torn = False
        if rule is not None:
            if rule.action == "corrupt":
                # Silent bit rot on the record: keep the trailing newline
                # so only THIS line is damaged — replay must skip it and
                # recover every other job.
                plan = faultplan.active()
                data = plan.mutate(rule, data[:-1]) + b"\n"
            else:  # crash: the daemon dies mid-append — a torn record
                data = data[: max(1, len(data) // 2)]
                torn = True
        t0 = time.perf_counter()
        with self._lock:
            try:
                if self._dirty_tail:
                    # Start fresh after a torn/failed write: gluing this
                    # record onto line debris would lose BOTH to replay.
                    self._fh.write(b"\n")
                    self._dirty_tail = False
                self._fh.write(data)
                self._fh.flush()
            except OSError:
                self._dirty_tail = True  # a short write may have landed
                raise
            if torn:
                self._dirty_tail = True
            if durable and self.fsync:
                os.fsync(self._fh.fileno())
            self._appends += 1
            self._appends_since_compact += 1
            self._append_ms += (time.perf_counter() - t0) * 1e3
        obs.metric_observe(
            "serve.journal_ms", (time.perf_counter() - t0) * 1e3
        )
        if torn:
            raise faultplan.FaultCrash(
                "[faultplan] injected journal crash mid-append "
                f"({rec['rec']} record torn)"
            )
        cb = self.on_append
        if cb is not None:
            # Outside the journal lock (the shipper has its own): the
            # record is durable locally by now, and per-job ordering is
            # safe — a terminal record is only ever generated after its
            # admit's append (and callback) returned.
            cb(rec)

    def apply_record(self, rec: dict) -> None:
        """Standby-side replication apply: append one SHIPPED record into
        this journal verbatim (serve/replicate.py).  Admit records pay
        the same fsync the primary paid — the standby's copy is what
        promotion replays, so it must be exactly as durable."""
        self._append(dict(rec), durable=rec.get("rec") == "admit")

    def compact_due(self) -> bool:
        with self._lock:
            return self._appends_since_compact >= self.compact_every

    # ------------------------------------------------------------- corpus

    def spill_path(self, sha: str) -> str:
        return os.path.join(self._corpus_dir, f"{sha}.bin")

    def _spill(self, sha: str, corpus: bytes) -> None:
        """Content-addressed, write-once: a sha already on disk is the
        same bytes by construction, so repeat submits of one corpus pay
        nothing.  tmp + rename so a crash never leaves a half spill
        under the final name (replay verifies the sha regardless).
        Holds the journal lock: compaction's spill GC runs under the
        same lock, so a spill landing mid-GC cannot be swept before the
        record that references it is appended."""
        path = self.spill_path(sha)
        with self._lock:
            if os.path.exists(path):
                return
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(corpus)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, path)

    def spill_exists(self, sha: str) -> bool:
        return os.path.exists(self.spill_path(sha))

    def store_spill(self, sha: str, corpus: bytes) -> bool:
        """Replication-side spill store: verify-then-write (a shipped
        spill whose bytes don't hash to its sha reference must never
        land under that name — the content ADDRESS is the integrity
        contract).  False = rejected."""
        if hashlib.sha256(corpus).hexdigest() != sha:
            logger.warning(
                "shipped corpus spill %s fails its content hash; "
                "refusing to store it", sha,
            )
            return False
        with self._lock:
            self._spill(sha, corpus)
        return True

    def read_spill(self, sha: str) -> bytes | None:
        """The spilled corpus, integrity-checked; None when missing or
        damaged (the caller fails the job structured — a corrupt spill
        must never become a silently-wrong recompute)."""
        try:
            with open(self.spill_path(sha), "rb") as f:
                data = f.read()
        except OSError:
            return None
        if hashlib.sha256(data).hexdigest() != sha:
            logger.warning(
                "journal corpus spill %s fails its content hash; "
                "treating as lost", sha,
            )
            return None
        return data

    # ----------------------------------------------------- replay/compact

    def replay(self) -> list[JournalEntry]:
        """Parse the journal into per-job entries, admit order preserved.

        Tolerant by design: a torn/corrupt/version-skewed line is what a
        crash mid-append leaves, so it is skipped with a warning — every
        parseable job still replays (the chaos matrix pins this)."""
        entries: dict[str, JournalEntry] = {}
        skipped = 0
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                lines = f.readlines()
        except OSError:
            return []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                kind = rec["rec"]
                job_id = str(rec["job_id"])
                if kind == "admit":
                    if rec.get("v") != JOURNAL_VERSION:
                        raise ValueError(f"journal version {rec.get('v')!r}")
                    entries[job_id] = JournalEntry(rec)
                elif kind == "state":
                    if rec["state"] not in TERMINAL_STATES:
                        raise ValueError(f"bad state {rec['state']!r}")
                    if job_id in entries:
                        entries[job_id].terminal = rec
                elif kind == "stage":
                    if not isinstance(rec.get("stage"), dict):
                        raise ValueError("stage record without a stage")
                    if job_id in entries:
                        entries[job_id].stages.append(rec["stage"])
                else:
                    raise ValueError(f"unknown record type {kind!r}")
            except (ValueError, KeyError, TypeError) as e:
                skipped += 1
                logger.warning(
                    "journal record skipped (%s: %s): %.80r",
                    type(e).__name__, e, line,
                )
        if skipped:
            logger.warning(
                "journal replay skipped %d unparseable record(s) — "
                "jobs acked under them are lost to this restart", skipped,
            )
        return list(entries.values())

    def _parse_live(self, locked: bool = True) -> dict[str, dict]:
        """job_id -> admit record for every LIVE job (an admit with no
        later terminal state), journal order preserved.  With
        ``locked`` the caller holds the journal lock (the catch-up
        snapshot path, where atomicity is correctness); ``locked=False``
        is the informational stats read — the caller flushed already,
        and the tolerant parser handles whatever a concurrent
        append/compaction leaves.  Torn/corrupt lines are dropped
        exactly as replay would drop them."""
        if locked:
            self._fh.flush()
        admits: dict[str, dict] = {}
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                lines = f.readlines()
        except OSError:
            return {}
        for line in lines:
            text = line.strip()
            if not text:
                continue
            try:
                rec = json.loads(text)
                kind = rec["rec"]
                job_id = str(rec["job_id"])
                if kind == "admit":
                    admits[job_id] = rec
                elif kind == "state" and rec.get("state") in TERMINAL_STATES:
                    admits.pop(job_id, None)
            except (ValueError, KeyError, TypeError):
                continue
        return admits

    def live_records(self) -> list[dict]:
        """The catch-up snapshot (serve/replicate.py): every live admit
        record, read atomically under the journal lock so a concurrent
        append/compaction can never hand the standby a half state."""
        with self._lock:
            return list(self._parse_live().values())

    def reset_to(self, records: list[dict]) -> None:
        """Standby catch-up apply: atomically replace this journal's
        contents with exactly ``records`` (the primary's live snapshot)
        and GC spills nothing references anymore — the standby's
        equivalent of the primary's compaction, driven by the shipped
        snapshot barrier instead of a local liveness parse."""
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with self._lock:
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in records:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._fh.close()
            self._fh = open(self.path, "ab")
            self._dirty_tail = False
            self._appends_since_compact = 0
            keep = {str(r.get("corpus_sha", "")) for r in records}
            try:
                for name in os.listdir(self._corpus_dir):
                    sha = name[:-4] if name.endswith(".bin") else None
                    if sha is not None and sha not in keep:
                        os.unlink(os.path.join(self._corpus_dir, name))
            except OSError as e:  # pragma: no cover - GC is best-effort
                logger.warning("journal spill GC failed: %s", e)

    def compact(self) -> None:
        """Atomically rewrite the journal down to the LIVE jobs and GC
        unreferenced spills.  Liveness is decided from the journal's own
        records — a job is live iff it has an admit record and no
        terminal state record — computed and rewritten entirely under
        the one journal lock, which also serializes appends and spills:
        an admit fsync'd by a handler thread an instant before (or
        after) this call can therefore never be dropped, and a spill
        landing concurrently can never be swept (the race a
        daemon-snapshot-then-rewrite design would have).  Torn/corrupt
        lines are dropped — replay would skip them anyway.  A crash
        mid-compact leaves either the old or the new journal — tmp +
        ``os.replace``, the same publish protocol as snapshots."""
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with self._lock:
            self._fh.flush()
            try:
                with open(self.path, encoding="utf-8",
                          errors="replace") as f:
                    lines = f.readlines()
            except OSError:
                return
            admits: dict[str, str] = {}   # job_id -> raw admit line
            shas: dict[str, str] = {}     # job_id -> corpus sha
            for line in lines:
                text = line.strip()
                if not text:
                    continue
                try:
                    rec = json.loads(text)
                    kind = rec["rec"]
                    job_id = str(rec["job_id"])
                    if kind == "admit":
                        admits[job_id] = text
                        shas[job_id] = str(rec.get("corpus_sha", ""))
                    elif kind == "state" and rec.get("state") in \
                            TERMINAL_STATES:
                        admits.pop(job_id, None)
                        shas.pop(job_id, None)
                except (ValueError, KeyError, TypeError):
                    continue  # torn/corrupt: replay would skip it too
            with open(tmp, "w", encoding="utf-8") as f:
                for text in admits.values():
                    f.write(text + "\n")
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._fh.close()
            self._fh = open(self.path, "ab")
            self._dirty_tail = False  # the rewrite ends line-clean
            self._appends_since_compact = 0
            self._last_compact_t = time.time()
            keep_shas = set(shas.values())
            try:
                for name in os.listdir(self._corpus_dir):
                    sha = name[:-4] if name.endswith(".bin") else None
                    if sha is not None and sha not in keep_shas:
                        os.unlink(os.path.join(self._corpus_dir, name))
            except OSError as e:  # pragma: no cover - GC is best-effort
                logger.warning("journal spill GC failed: %s", e)

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.flush()
                self._fh.close()
            except OSError:  # pragma: no cover - closing is best-effort
                pass

    def spill_bytes(self) -> int:
        """Aggregate on-disk corpus-spill bytes (operator visibility —
        the journal stats sub-dict; best-effort under races with GC)."""
        total = 0
        try:
            for name in os.listdir(self._corpus_dir):
                if name.endswith(".bin"):
                    try:
                        total += os.path.getsize(
                            os.path.join(self._corpus_dir, name)
                        )
                    except OSError:
                        continue
        except OSError:
            pass
        return total

    def stats(self) -> dict:
        # The live parse + spill sweep run OUTSIDE the journal lock: a
        # monitoring loop polling stats must never stall the admit
        # path's fsync'd append on an O(journal) read.  Lock-free is
        # safe here — compaction publishes via atomic rename (a reader
        # sees the old or the new file, both parseable) and the
        # tolerant parser drops a torn tail exactly as replay would;
        # the count is informational, the CATCH-UP snapshot
        # (live_records) stays under the lock where atomicity is
        # correctness.
        with self._lock:
            try:
                self._fh.flush()
            except (OSError, ValueError):  # pragma: no cover - closing race
                pass
        live = len(self._parse_live(locked=False))
        spill_bytes = self.spill_bytes()
        with self._lock:
            return {
                "path": self.path,
                "appends": self._appends,
                "append_ms_total": round(self._append_ms, 3),
                "append_ms_mean": round(
                    self._append_ms / self._appends, 4
                ) if self._appends else None,
                "spills": self._spills,
                "spill_ms_mean": round(
                    self._spill_ms / self._spills, 4
                ) if self._spills else None,
                "since_compact": self._appends_since_compact,
                # HA operator surface (docs/SERVING.md): what a standby
                # would replay, how much disk the spills hold, and when
                # the log was last squeezed — readable from `serve
                # stats` without stalling admits.
                "live": live,
                "spill_bytes": spill_bytes,
                "last_compact_t": self._last_compact_t,
            }
