"""Job model for the serve tier: specs, runtime records, structured errors.

A JOB is one client-submitted corpus + pipeline configuration; the daemon
(serve/daemon.py) turns it into exactly one of two outcomes — a correct
result table or a STRUCTURED error carrying a reason code from the closed
``ERROR_CODES`` registry below (the chaos-matrix guarantee: never a
silent wrong answer, docs/SERVING.md).  jax-free at import so the
scheduler/cache layers and the client stay importable before backend
selection (same stance as ``locust_tpu.obs``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time

from locust_tpu.config import EngineConfig
from locust_tpu.plan import PlanError, from_doc as plan_from_doc

# Job lifecycle (reported verbatim by the ``status`` command):
#   queued -> running -> done | failed;  queued -> cancelled;
#   running -> retrying -> running (backoff requeue after a failed
#   dispatch, docs/SERVING.md retry ladder) until done | failed.
JOB_STATES = ("queued", "running", "retrying", "done", "failed", "cancelled")

# Closed reason-code registry for every structured error the daemon can
# hand a client (same closed-registry stance as faultplan.SITES and the
# obs NAMES dict): a client can switch on these without parsing prose.
ERROR_CODES = (
    "queue_full",        # admission control: the bounded queue is full
    "tenant_quota",      # admission control: per-tenant pending cap hit
    "shutting_down",     # daemon is stopping; do NOT retry this address
    "bad_spec",          # submit payload failed validation
    "unknown_workload",  # workload name not in WORKLOADS
    "corpus_too_large",  # inline corpus exceeds the daemon's cap
    "fault_injected",    # a serve.* chaos rule rejected/killed the job
    "dispatch_failed",   # the engine dispatch raised; message has detail
    "cancelled",         # the client cancelled the job while queued
    "unknown_job",       # status/result/cancel for an id we don't hold
    "not_done",          # result requested before the job finished
    "result_too_large",  # reply frame would exceed protocol.MAX_FRAME
    "unknown_command",   # command outside the serve command set
    "deadline_exceeded", # the job's deadline_s budget expired (any state)
    "poison_job",        # the job killed max_attempts dispatches; quarantined
    "journal_failed",    # WAL append failed; the accept ack would be a lie
    "not_primary",       # this daemon is a standby; reply names the primary
    "stale_epoch",       # sender's fencing epoch is behind; a newer primary rules
)

# Retry-budget guard rails: a submit may not ask for more attempts than
# the bisection ladder can meaningfully use (log2(max_batch) + retries),
# nor a deadline past what admission control can reason about.
MAX_ATTEMPTS_CAP = 16
DEADLINE_CAP_S = 3600.0

# workload name -> (map_fn import path resolved lazily in cache.py,
# combine).  Lazy: resolving here would pull jax into every importer.
WORKLOADS = {
    "wordcount": ("locust_tpu.ops.map_stage:wordcount_map", "sum"),
}

# Reserved workload name for plan-carrying jobs (docs/PLAN.md): a submit
# with a ``plan`` document runs an arbitrary compiled pipeline instead of
# a named WORKLOADS entry, and its executable identity is the PLAN
# fingerprint (+ config), not a workload string.  Deliberately NOT a
# WORKLOADS row — there is no single map_fn to resolve; every site that
# indexes WORKLOADS by name guards on ``spec.plan`` first.
PLAN_WORKLOAD = "plan"

# Engine-config fields a submit may override; everything else keeps the
# EngineConfig default.  A closed set so a typo'd knob is a loud
# ``bad_spec``, not a silently-ignored key.
SPEC_CONFIG_KEYS = (
    "line_width", "key_width", "emits_per_line", "block_lines",
    "table_size", "sort_mode",
)


def structured_error(code: str, message: str) -> dict:
    """The one shape every daemon-side failure reply takes."""
    if code not in ERROR_CODES:
        raise ValueError(f"unknown serve error code {code!r}")
    return {"status": "error", "code": code, "error": message}


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """What a client asked for: corpus + workload + pipeline config.

    ``fingerprint()`` identifies the EXECUTABLE the job needs (workload +
    full EngineConfig identity) — the result cache key adds the corpus
    digest on top, so "same program" and "same program over the same
    bytes" are distinct cache tiers (docs/SERVING.md).
    """

    tenant: str
    workload: str
    cfg: EngineConfig
    weight: float = 1.0
    invalidate: bool = False  # drop any cached result for this key first
    no_cache: bool = False    # compute fresh AND don't store the result
    # Durability/robustness budgets (docs/SERVING.md): deadline_s bounds
    # the job's whole submit->answer life (None = no deadline) —
    # expiry ANYWHERE (queued, running, retrying) answers the structured
    # ``deadline_exceeded`` code; max_attempts bounds how many dispatches
    # the job may kill before it is quarantined as ``poison_job``.  The
    # default of 4 lets the bisection ladder isolate a poison job out of
    # a full default batch (8 -> 4 -> 2 -> solo).  Neither is part of
    # ``fingerprint()``: budgets do not change the executable.
    deadline_s: float | None = None
    max_attempts: int = 4
    # Composable dataflow plan (docs/PLAN.md): the CANONICAL plan JSON
    # (``Plan.canonical_json()``, validated by parse_spec) for plan
    # jobs, None for named workloads.  A string, not a Plan: the frozen
    # spec stays hashable and journal-serializable, and the fingerprint
    # below hashes exactly these bytes.
    plan: str | None = None

    def plan_fingerprint(self) -> str | None:
        """The plan's content address — sha1 of the canonical JSON,
        identical by construction to ``Plan.fingerprint()`` (the spec
        stores canonical text, so no re-parse is needed)."""
        if self.plan is None:
            return None
        fp = self.__dict__.get("_plan_fp")
        if fp is None:
            fp = hashlib.sha1(self.plan.encode()).hexdigest()[:12]
            object.__setattr__(self, "_plan_fp", fp)
        return fp

    def fingerprint(self) -> str:
        # Memoized like EngineConfig.fingerprint(): the daemon asks at
        # submit, dispatch, demux and invalidate, and the spec is frozen.
        fp = self.__dict__.get("_fingerprint")
        if fp is None:
            if self.plan is not None:
                # Plan jobs: the executable IS the (plan, config) pair —
                # the plan fingerprint keys the result cache, warm
                # cache, shape buckets and batch keys (docs/PLAN.md).
                raw = (
                    f"{PLAN_WORKLOAD}:{self.plan_fingerprint()}:"
                    f"{self.cfg.fingerprint()}"
                )
            else:
                combine = WORKLOADS[self.workload][1]
                raw = f"{self.workload}:{combine}:{self.cfg.fingerprint()}"
            fp = hashlib.sha1(raw.encode()).hexdigest()[:12]
            object.__setattr__(self, "_fingerprint", fp)
        return fp


def parse_spec(
    req: dict, max_corpus_bytes: int | None = None
) -> tuple[JobSpec, bytes]:
    """Validate one ``submit`` request into (JobSpec, corpus bytes).

    Raises ``ValueError`` whose first line is an ERROR_CODES entry — the
    daemon maps it straight onto a structured reply.
    ``max_corpus_bytes`` bounds the path branch's read: the cap must
    hold BEFORE the bytes land in daemon memory, or a path submit
    naming a huge server-side file OOMs the daemon ahead of the
    rejection (inline corpus_b64 is already bounded by the frame cap).
    """
    plan_json = None
    raw_plan = req.get("plan")
    if raw_plan is not None:
        # A plan submit: validate the document end-to-end (structure,
        # registry membership, arity, cycles, dataflow types) BEFORE
        # anything is admitted — every malformation is a structured
        # bad_spec, never a dispatch-time surprise (docs/PLAN.md).
        if isinstance(raw_plan, str):
            try:
                raw_plan = json.loads(raw_plan)
            except ValueError as e:
                raise ValueError(f"bad_spec\nplan JSON does not parse: {e}")
        try:
            p = plan_from_doc(raw_plan)
        except PlanError as e:
            raise ValueError(f"bad_spec\ninvalid plan: {e}")
        # A serve submit carries ONE corpus: a plan whose sources name
        # distinct inputs would feed the same bytes to every source — a
        # silent self-join, the wrong answer this tier forbids.  Reject
        # at admission (run_corpus carries the dispatch-side defense).
        named = sorted({
            n.param("input", "corpus")
            for n in p.nodes if n.kind == "source"
        } - {"corpus"})
        if named:
            raise ValueError(
                f"bad_spec\nplan sources name inputs {named}, but a "
                "submit carries exactly one corpus (name every source "
                "input 'corpus' or split the pipeline)"
            )
        plan_json = p.canonical_json()
        if req.get("workload") not in (None, PLAN_WORKLOAD):
            raise ValueError(
                "bad_spec\nsubmit takes a plan OR a workload name, "
                "not both"
            )
        workload = PLAN_WORKLOAD
    else:
        workload = req.get("workload", "wordcount")
        if workload not in WORKLOADS:
            raise ValueError(
                f"unknown_workload\nworkload {workload!r} not in "
                f"{sorted(WORKLOADS)}"
            )
    corpus_b64 = req.get("corpus_b64")
    path = req.get("path")
    if (corpus_b64 is None) == (path is None):
        raise ValueError(
            "bad_spec\nsubmit needs exactly one of corpus_b64 or path"
        )
    if corpus_b64 is not None:
        import base64
        import binascii

        try:
            corpus = base64.b64decode(corpus_b64, validate=True)
        except (binascii.Error, TypeError, ValueError) as e:
            raise ValueError(f"bad_spec\ncorpus_b64 does not decode: {e}")
    else:
        try:
            with open(path, "rb") as f:
                if max_corpus_bytes is None:
                    corpus = f.read()
                else:
                    corpus = f.read(max_corpus_bytes + 1)
        except OSError as e:
            raise ValueError(f"bad_spec\ncorpus path unreadable: {e}")
        if max_corpus_bytes is not None and len(corpus) > max_corpus_bytes:
            raise ValueError(
                f"corpus_too_large\ncorpus at {path!r} exceeds the "
                f"daemon cap ({max_corpus_bytes} bytes)"
            )
    overrides = req.get("config") or {}
    if not isinstance(overrides, dict):
        raise ValueError("bad_spec\nconfig must be an object of knobs")
    unknown = set(overrides) - set(SPEC_CONFIG_KEYS)
    if unknown:
        raise ValueError(
            f"bad_spec\nunknown config keys {sorted(unknown)} "
            f"(allowed: {SPEC_CONFIG_KEYS})"
        )
    try:
        cfg = EngineConfig(**overrides)
    except (TypeError, ValueError) as e:
        raise ValueError(f"bad_spec\n{e}")
    try:
        weight = float(req.get("weight", 1.0))
    except (TypeError, ValueError):
        raise ValueError("bad_spec\nweight must be a number")
    if not 0.0 < weight <= 100.0:
        raise ValueError(f"bad_spec\nweight must be in (0, 100], got {weight}")
    deadline_s = req.get("deadline_s")
    if deadline_s is not None:
        try:
            deadline_s = float(deadline_s)
        except (TypeError, ValueError):
            raise ValueError("bad_spec\ndeadline_s must be a number")
        if not 0.0 < deadline_s <= DEADLINE_CAP_S:
            raise ValueError(
                f"bad_spec\ndeadline_s must be in (0, {DEADLINE_CAP_S}], "
                f"got {deadline_s}"
            )
    try:
        max_attempts = int(req.get("max_attempts", 4))
    except (TypeError, ValueError):
        raise ValueError("bad_spec\nmax_attempts must be an integer")
    if not 1 <= max_attempts <= MAX_ATTEMPTS_CAP:
        raise ValueError(
            f"bad_spec\nmax_attempts must be in [1, {MAX_ATTEMPTS_CAP}], "
            f"got {max_attempts}"
        )
    tenant = str(req.get("tenant", "default"))[:64] or "default"
    spec = JobSpec(
        tenant=tenant,
        workload=workload,
        cfg=cfg,
        weight=weight,
        invalidate=bool(req.get("invalidate")),
        no_cache=bool(req.get("no_cache")),
        deadline_s=deadline_s,
        max_attempts=max_attempts,
        plan=plan_json,
    )
    return spec, corpus


def pairs_bytes(pairs) -> int:
    """Approximate retained size of a result pairs list: key bytes plus
    a small per-pair constant for tuple/int overhead.  An estimate is
    enough — the byte caps guard against multi-GB retention, not
    byte-exact accounting."""
    return sum(len(k) + 16 for k, _ in pairs)


@dataclasses.dataclass
class Job:
    """Runtime record for one admitted job.

    NOT thread-safe by itself: the daemon mutates jobs only under its own
    lock (submit/cancel handlers) or from the single dispatcher thread
    (running -> done/failed), with the state transitions serialized
    through ``FairScheduler``'s lock.
    """

    job_id: str
    spec: JobSpec
    corpus_digest: str
    n_lines: int
    n_blocks: int
    bucket: int               # shape-bucketed block count (cache.bucket_blocks)
    state: str = "queued"
    submitted_s: float = dataclasses.field(default_factory=time.monotonic)
    started_s: float | None = None
    finished_s: float | None = None
    cache: str = "cold"       # "result" | "warm" | "cold" — how it was served
    result: list | None = None            # [(key bytes, value int), ...]
    result_bytes: int = 0                 # pairs_bytes(result) at finish
    error: dict | None = None             # structured_error() dict
    distinct: int | None = None
    truncated: bool = False
    overflow_tokens: int = 0
    batch_size: int | None = None         # jobs coalesced into its dispatch
    attempts: int = 0                     # dispatches this job has ridden
    # Bisection tag (docs/SERVING.md): jobs from a failed multi-job batch
    # split into halves that must not re-coalesce — the dispatcher's
    # batch_key includes this, so a poison job is isolated in log2(batch)
    # extra dispatches while its innocent neighbors succeed.
    bisect_group: str | None = None
    # Raw submit ``config`` overrides, kept for the write-ahead journal:
    # replay rebuilds the EngineConfig from exactly what the client sent.
    config_overrides: dict | None = None
    # Scale-out placement (docs/SERVING.md "Scale-out dispatch"): where
    # the LAST dispatch ran — "local", a pool worker's "host:port", or
    # "shard" for a fanned-out large job; None until first dispatch.
    placed_on: str | None = None
    shards: int | None = None  # shard count for a fanned-out large job

    def deadline_mono(self) -> float | None:
        """Absolute monotonic deadline, or None.  Anchored at submit
        time — replay re-anchors (a restart restores the job, not the
        wall-clock budget it already burned; docs/SERVING.md)."""
        if self.spec.deadline_s is None:
            return None
        return self.submitted_s + self.spec.deadline_s

    def expired(self, now: float) -> bool:
        d = self.deadline_mono()
        return d is not None and now >= d

    def queue_ms(self) -> float | None:
        if self.started_s is None:
            return None
        return round((self.started_s - self.submitted_s) * 1e3, 3)

    def latency_ms(self) -> float | None:
        if self.finished_s is None:
            return None
        return round((self.finished_s - self.submitted_s) * 1e3, 3)

    def public(self) -> dict:
        """The ``status`` reply body (no result payload — that is the
        ``result`` command's job, results can be MBs)."""
        return {
            "job_id": self.job_id,
            "state": self.state,
            "tenant": self.spec.tenant,
            "workload": self.spec.workload,
            "corpus_digest": self.corpus_digest,
            "n_lines": self.n_lines,
            "n_blocks": self.n_blocks,
            "bucket": self.bucket,
            "cache": self.cache,
            "queue_ms": self.queue_ms(),
            "latency_ms": self.latency_ms(),
            "batch_size": self.batch_size,
            "placed_on": self.placed_on,
            "shards": self.shards,
            "attempts": self.attempts,
            "max_attempts": self.spec.max_attempts,
            "deadline_s": self.spec.deadline_s,
            "error": self.error,
        }
