"""locust_tpu.serve — the persistent multi-tenant job service.

From one-shot CLI to a serving layer (ROADMAP item 1, docs/SERVING.md):
a resident engine daemon serving concurrent jobs over the distributor's
authenticated frame protocol, with admission control + per-tenant
weighted fairness (scheduler), a warm-executable cache + shape-bucketed
batching (cache/batch + engine.run_batch), and a restart-persistent
result cache riding the async snapshot writer (cache.WarmState).

    python -m locust_tpu.serve                     # run the daemon
    python -m locust_tpu.serve submit FILE ...     # submit + wait
    python -m locust_tpu.serve stats|shutdown      # operate it

jax-free at import (the daemon pulls the engine in lazily at first
dispatch), so clients and supervisors import this before — or without —
backend selection.
"""

from locust_tpu.serve.cache import (  # noqa: F401
    ExecutableCache,
    ResultCache,
    WarmState,
    bucket_blocks,
)
from locust_tpu.serve.client import ServeClient, ServeError  # noqa: F401
from locust_tpu.serve.daemon import (  # noqa: F401
    SERVE_COMMANDS,
    ServeConfig,
    ServeDaemon,
)
from locust_tpu.serve.jobs import (  # noqa: F401
    ERROR_CODES,
    JOB_STATES,
    WORKLOADS,
    Job,
    JobSpec,
)
from locust_tpu.serve.journal import JobJournal  # noqa: F401
from locust_tpu.serve.pool import PoolDispatchError, WorkerPool  # noqa: F401
from locust_tpu.serve.scheduler import AdmitReject, FairScheduler  # noqa: F401
