"""Shape-bucketed batching: many compatible jobs, ONE engine dispatch.

The dispatcher hands this module a batch of jobs that FairScheduler
already proved compatible — same executable key (workload + EngineConfig
fingerprint) and same shape bucket — and it:

  1. stages every job's corpus into one ``[njobs, bucket, block_lines,
     line_width]`` uint8 stack (job axis padded up the same power-of-two
     ladder as the block axis, so the batched executable compiles for a
     small closed set of shapes, not one per queue occupancy);
  2. runs ``MapReduceEngine.run_batch`` — the vmapped whole-corpus scan,
     one device dispatch for the lot;
  3. demultiplexes per-job tables back into host (key, count) pairs,
     dropping the padded job slots.

Padding is correct by the engine's existing semantics: all-NUL rows
tokenize to nothing, and a zero-filled job slot folds to an empty table
that is simply discarded here.
"""

from __future__ import annotations

import numpy as np

from locust_tpu.serve.cache import bucket_blocks
from locust_tpu.serve.jobs import Job


def split_lines(corpus: bytes) -> list[bytes]:
    """Corpus bytes -> lines, the same way the CLI ingests files."""
    return corpus.splitlines()


def count_lines(corpus: bytes) -> int:
    """``len(corpus.splitlines())`` WITHOUT materializing the list —
    admission only needs the count, and splitting a max-size inline
    corpus on a handler thread just to len() it doubles the per-job
    split work.  bytes.splitlines breaks on \\n, \\r and \\r\\n (one
    break each), plus a trailing partial line."""
    if not corpus:
        return 0
    n = (
        corpus.count(b"\n") + corpus.count(b"\r") - corpus.count(b"\r\n")
    )
    if not corpus.endswith((b"\n", b"\r")):
        n += 1
    return n


def job_shape(n_lines: int, cfg) -> tuple[int, int]:
    """(n_blocks, bucket) for a corpus under ``cfg`` — the shape half of
    the warm-cache key, computed once at admission."""
    n_blocks = max(1, -(-n_lines // cfg.block_lines))
    return n_blocks, bucket_blocks(n_blocks)


def stage_batch(engine, jobs: list[Job], corpora: dict):
    """Build the ``[padded_jobs, bucket, block_lines, width]`` stack.

    ``corpora`` maps corpus digest -> raw bytes OR a pre-split line list
    (the pool worker's shard path slices lines once and stages them
    directly — re-joining just to re-split would double the work).
    Returns the device-put stack; the job axis pads to
    ``bucket_blocks(len(jobs))`` so batch sizes share compiled shapes
    exactly like block counts do.
    """
    import jax

    cfg = engine.cfg
    bucket = jobs[0].bucket
    bl, w = cfg.block_lines, cfg.line_width
    njobs = bucket_blocks(len(jobs))
    stack = np.zeros((njobs, bucket, bl, w), dtype=np.uint8)
    for j, job in enumerate(jobs):
        data = corpora[job.corpus_digest]
        lines = data if isinstance(data, list) else split_lines(data)
        rows = engine.rows_from_lines(lines)
        n = rows.shape[0]
        flat = stack[j].reshape(bucket * bl, w)
        if n > flat.shape[0]:
            raise ValueError(
                f"job {job.job_id}: {n} staged lines exceed the batch "
                f"shape ({bucket} blocks x {bl} lines)"
            )
        flat[:n] = rows[:, :w]
    return jax.device_put(stack)


def dispatch_batch(engine, jobs: list[Job], corpora: dict):
    """Stage + run one coalesced dispatch; returns the per-job RunResults
    (padded job slots dropped).  Pure compute — spans/accounting are the
    daemon's (serve/daemon.py keeps the obs emission sites literal)."""
    blocks = stage_batch(engine, jobs, corpora)
    results = engine.run_batch(blocks)
    return results[: len(jobs)]


def merge_shard_results(
    shard_results: list[dict], cfg, combine: str = "sum"
) -> tuple[list[tuple[bytes, int]], int, bool, int]:
    """Merge per-shard tables through the engine's own combine.

    ``shard_results`` are the pool workers' per-shard replies
    (``pairs`` as (key bytes, value) tuples plus the
    truncated/overflow flags).  The merge is the SAME primitive the
    hierarchical mesh and the CLI reduce stage trust — concatenate the
    shard tables as an emit batch, ``sort_and_compact`` +
    ``segment_reduce`` them on device, decode exactly — so a sharded
    job's table equals the unsharded fold's table whenever the merged
    distinct count fits the configured table (the non-truncated regime;
    a shard CAN only see fewer distinct keys than the whole corpus, so
    sharding never truncates more than the local fold would).

    Returns ``(pairs, distinct, truncated, overflow_tokens)``.
    """
    import jax.numpy as jnp

    from locust_tpu.core.kv import KVBatch
    from locust_tpu.engine import finalize_host_pairs
    from locust_tpu.ops import segment_reduce, sort_and_compact

    kw = cfg.key_width
    all_pairs = [p for res in shard_results for p in res["pairs"]]
    overflow = sum(int(res.get("overflow_tokens", 0)) for res in shard_results)
    shard_truncated = any(bool(res.get("truncated")) for res in shard_results)
    if not all_pairs:
        return [], 0, shard_truncated, overflow
    keys = np.zeros((len(all_pairs), kw), dtype=np.uint8)
    values = np.zeros(len(all_pairs), dtype=np.int32)
    for i, (k, v) in enumerate(all_pairs):
        kb = k[:kw]
        keys[i, : len(kb)] = np.frombuffer(kb, dtype=np.uint8)
        values[i] = v
    batch = KVBatch.from_bytes(
        jnp.asarray(keys), jnp.asarray(values),
        jnp.ones(len(all_pairs), bool),
    )
    table = segment_reduce(sort_and_compact(batch, cfg.sort_mode), combine)
    pairs = finalize_host_pairs(table, combine)
    distinct = len(pairs)
    truncated = shard_truncated or distinct > cfg.resolved_table_size
    return pairs, distinct, truncated, overflow
