"""Shape-bucketed batching: many compatible jobs, ONE engine dispatch.

The dispatcher hands this module a batch of jobs that FairScheduler
already proved compatible — same executable key (workload + EngineConfig
fingerprint) and same shape bucket — and it:

  1. stages every job's corpus into one ``[njobs, bucket, block_lines,
     line_width]`` uint8 stack (job axis padded up the same power-of-two
     ladder as the block axis, so the batched executable compiles for a
     small closed set of shapes, not one per queue occupancy);
  2. runs ``MapReduceEngine.run_batch`` — the vmapped whole-corpus scan,
     one device dispatch for the lot;
  3. demultiplexes per-job tables back into host (key, count) pairs,
     dropping the padded job slots.

Padding is correct by the engine's existing semantics: all-NUL rows
tokenize to nothing, and a zero-filled job slot folds to an empty table
that is simply discarded here.
"""

from __future__ import annotations

import numpy as np

from locust_tpu.serve.cache import bucket_blocks
from locust_tpu.serve.jobs import Job


def split_lines(corpus: bytes) -> list[bytes]:
    """Corpus bytes -> lines, the same way the CLI ingests files."""
    return corpus.splitlines()


def count_lines(corpus: bytes) -> int:
    """``len(corpus.splitlines())`` WITHOUT materializing the list —
    admission only needs the count, and splitting a max-size inline
    corpus on a handler thread just to len() it doubles the per-job
    split work.  bytes.splitlines breaks on \\n, \\r and \\r\\n (one
    break each), plus a trailing partial line."""
    if not corpus:
        return 0
    n = (
        corpus.count(b"\n") + corpus.count(b"\r") - corpus.count(b"\r\n")
    )
    if not corpus.endswith((b"\n", b"\r")):
        n += 1
    return n


def job_shape(n_lines: int, cfg) -> tuple[int, int]:
    """(n_blocks, bucket) for a corpus under ``cfg`` — the shape half of
    the warm-cache key, computed once at admission."""
    n_blocks = max(1, -(-n_lines // cfg.block_lines))
    return n_blocks, bucket_blocks(n_blocks)


def stage_batch(engine, jobs: list[Job], corpora: dict[str, bytes]):
    """Build the ``[padded_jobs, bucket, block_lines, width]`` stack.

    ``corpora`` maps corpus digest -> raw bytes (the daemon holds bytes
    only while the job is in flight).  Returns the device-put stack; the
    job axis pads to ``bucket_blocks(len(jobs))`` so batch sizes share
    compiled shapes exactly like block counts do.
    """
    import jax

    cfg = engine.cfg
    bucket = jobs[0].bucket
    bl, w = cfg.block_lines, cfg.line_width
    njobs = bucket_blocks(len(jobs))
    stack = np.zeros((njobs, bucket, bl, w), dtype=np.uint8)
    for j, job in enumerate(jobs):
        rows = engine.rows_from_lines(
            split_lines(corpora[job.corpus_digest])
        )
        n = rows.shape[0]
        flat = stack[j].reshape(bucket * bl, w)
        flat[:n] = rows[:, :w]
    return jax.device_put(stack)


def dispatch_batch(engine, jobs: list[Job], corpora: dict[str, bytes]):
    """Stage + run one coalesced dispatch; returns the per-job RunResults
    (padded job slots dropped).  Pure compute — spans/accounting are the
    daemon's (serve/daemon.py keeps the obs emission sites literal)."""
    blocks = stage_batch(engine, jobs, corpora)
    results = engine.run_batch(blocks)
    return results[: len(jobs)]
