"""High-availability control plane: WAL shipping to a hot standby.

PR 9 made acked jobs survive *process* death (fsync'd WAL + replay) and
the worker pool made dispatch survive *worker* death, but the daemon
itself was still a single point of failure: if its machine dies, every
acked job is unreachable until an operator rebuilds state by hand.  This
module extends "never lost work" to MACHINE death (docs/SERVING.md
"High availability") — Dean & Ghemawat's re-execution thesis applied to
the control plane, with the fencing discipline of primary-backup
replicated logs so a partition can never produce two daemons answering
for the same jobs:

  * **shipping** (``ReplicationShipper``, primary side): every record
    the journal durably appends is enqueued (``JobJournal.on_append``)
    and shipped to the standby over the distributor's authenticated
    frame protocol — sequence-numbered, checksummed, acked.  Shipping is
    ASYNCHRONOUS off the admit path: a dead or slow standby degrades to
    a logged warning plus a lag gauge (``serve.ship_lag``), never a slow
    or failed admit.  Corpus spills ship by sha REFERENCE; the standby
    pulls missing bytes on demand (``ship_spill``).
  * **catch-up**: a standby that connects late, falls behind (queue
    overflow), or detects a sequence gap converges through a full
    live-journal snapshot (``ship_catchup``) taken atomically under the
    journal lock.  The primary's journal COMPACTION ships as the same
    snapshot barrier — a standby mid-catch-up can race a compaction's
    spill GC and still converge, because every GC'd spill belongs to a
    job whose terminal record is already in the ship stream.
  * **application** (``ShipReceiver``, standby side): records append
    into the standby's OWN journal (admits fsync'd — the standby's copy
    is what promotion replays), verbatim and in order.  A checksum
    mismatch (the ``serve.ship`` "corrupt" chaos action) is NEVER
    applied: the standby answers resync and the primary re-snapshots.
  * **fencing** (``load_epoch``/``store_epoch``/``stale_reply``): every
    shipped frame and every pool-worker RPC carries the sender's
    promotion epoch (``protocol.EPOCH_KEY``).  Promotion bumps the
    epoch and persists it; receivers reject lower epochs with the
    structured ``stale_epoch`` code — a zombie primary's first ship
    after a partition is refused, and it demotes itself to standby
    instead of split-braining.

jax-free at import, like the rest of the serve control plane.
"""

from __future__ import annotations

import base64
import collections
import hashlib
import json
import logging
import os
import socket
import threading
import time

from locust_tpu import obs
from locust_tpu.distributor import protocol
from locust_tpu.serve.jobs import structured_error
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")

EPOCH_FILE = "epoch"

SHIP_BATCH_MAX = 64      # records per ship frame
SHIP_QUEUE_MAX = 4096    # queued records before a forced snapshot resync
SHIP_CONNECT_TIMEOUT = 5.0
SHIP_RPC_TIMEOUT = 30.0
SHIP_BACKOFF_MAX_S = 5.0


def load_epoch(journal_dir: str) -> int:
    """The persisted fencing epoch (>= 1).  A fresh journal dir starts
    at epoch 1; damage reads as 1 — the first PROMOTION anywhere in the
    pair bumps past it, so a lost epoch file can only make this daemon
    easier to fence, never harder."""
    try:
        with open(os.path.join(journal_dir, EPOCH_FILE),
                  encoding="utf-8") as f:
            return max(1, int(f.read().strip()))
    except (OSError, ValueError):
        return 1


def store_epoch(journal_dir: str, epoch: int) -> None:
    """Durably persist the fencing epoch (tmp + atomic rename + fsync):
    a promoted standby that restarts must come back ABOVE the zombie it
    fenced, or the fence would evaporate with the process."""
    path = os.path.join(journal_dir, EPOCH_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(str(int(epoch)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def stale_reply(epoch: int, primary: str | None) -> dict:
    """The ONE shape every fencing rejection takes: the structured
    ``stale_epoch`` code plus the rejecting side's epoch (so the fenced
    sender can persist what it must now exceed) and, when known, the
    address the sender should treat as primary."""
    reply = structured_error(
        "stale_epoch",
        f"fencing epoch is behind this daemon's epoch {epoch}; a newer "
        "primary owns these jobs — demote to standby",
    )
    reply["epoch"] = int(epoch)
    if primary:
        reply["primary"] = primary
    return reply


def records_blob(records: list[dict]) -> tuple[str, str]:
    """Serialize a record batch for the wire: (canonical JSON text, its
    sha256).  The checksum is computed BEFORE the ``serve.ship``
    "corrupt" chaos action can touch the text, so rot between the
    journal and the frame — inside the HMAC boundary — is detected by
    the standby and the record is never applied."""
    text = json.dumps(records, sort_keys=True, separators=(",", ":"))
    return text, hashlib.sha256(text.encode()).hexdigest()


def decode_blob(text: str, checksum: str) -> list[dict] | None:
    """Verify + parse a shipped record batch; None = corrupt (the
    caller answers resync and applies NOTHING)."""
    if hashlib.sha256(text.encode("utf-8", "replace")).hexdigest() \
            != checksum:
        return None
    try:
        records = json.loads(text)
    except ValueError:
        return None
    if not isinstance(records, list) or not all(
        isinstance(r, dict) for r in records
    ):
        return None
    return records


class ReplicationShipper:
    """Primary-side WAL shipping thread.

    One persistent authenticated connection to the standby; records
    enqueue from the journal's append path (handler threads + the
    dispatcher) and drain here.  All shared state mutates under one
    condition variable (R001); every blocking wait is bounded (R013)
    and the thread is daemonized AND joined, bounded, in ``stop()``
    (R012).
    """

    def __init__(
        self,
        target: tuple[str, int],
        secret: bytes,
        journal,
        epoch_fn,
        advertise: str,
        on_fenced=None,
        heartbeat_s: float = 2.0,
    ):
        self.target = (str(target[0]), int(target[1]))
        self.name = f"{self.target[0]}:{self.target[1]}"
        self.secret = secret
        self.journal = journal
        self._epoch_fn = epoch_fn      # () -> current fencing epoch
        self._advertise = advertise    # this primary's "host:port"
        self._on_fenced = on_fenced    # (higher_epoch, primary|None) -> None
        self._heartbeat_s = max(0.2, float(heartbeat_s))
        self._cond = threading.Condition()
        self._records: collections.deque = collections.deque()
        self._seq = 0              # last seq ENQUEUED
        self._acked_seq = 0        # last seq the standby confirmed applied
        self._lag_bytes = 0        # serialized bytes of queued records
        self._need_catchup = True  # first contact always snapshots
        self._connected = False
        self._last_contact_t: float | None = None
        self._last_catchup_t: float | None = None
        self._ship_errors = 0
        self._resyncs = 0
        self._drops = 0            # records discarded to queue overflow
        self._enqueues = 0         # admit-path cost accounting: the
        self._enqueue_ms = 0.0     # synchronous part shipping adds
        self._stop = threading.Event()
        self._conn: socket.socket | None = None
        self._thread = threading.Thread(
            target=self._run, name="serve-ship", daemon=True
        )

    # -------------------------------------------------------------- intake

    def start(self) -> None:
        self._thread.start()

    def enqueue(self, rec: dict) -> None:
        """``JobJournal.on_append`` callback: O(1), lock-bounded, never
        raises — the admit path must not observe the standby's health.
        Its wall cost is accounted (``stats().enqueue_ms_mean``): this
        is the ONLY synchronous cost shipping adds to an admit, and the
        bench recovery sub-dict pins it under 5% of admit latency."""
        t0 = time.perf_counter()
        size = len(json.dumps(rec, separators=(",", ":")))
        with self._cond:
            self._seq += 1
            if len(self._records) >= SHIP_QUEUE_MAX:
                # Overflow: drop the whole backlog and resync through a
                # snapshot — bounded memory beats a faithful-but-
                # unbounded queue, and the snapshot is exactly as
                # convergent.
                self._drops += len(self._records)
                self._records.clear()
                self._lag_bytes = 0
                self._need_catchup = True
            self._records.append((self._seq, rec))
            self._lag_bytes += size
            self._enqueues += 1
            self._enqueue_ms += (time.perf_counter() - t0) * 1e3
            self._cond.notify_all()

    def barrier(self) -> None:
        """Journal-compaction barrier: the next ship is a full snapshot,
        so the standby compacts to the same live set and can never be
        stranded chasing spills the primary's GC removed."""
        with self._cond:
            self._need_catchup = True
            self._cond.notify_all()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if threading.current_thread() is not self._thread:
            # The fenced path calls stop() FROM the shipping thread
            # (on_fenced -> daemon demote -> here): it is already past
            # its loop and about to return, so only a foreign caller
            # needs the bounded join.
            self._thread.join(timeout=timeout)
        with self._cond:
            conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def lag(self) -> int:
        with self._cond:
            return self._seq - self._acked_seq

    def stats(self) -> dict:
        with self._cond:
            return {
                "standby": self.name,
                "connected": self._connected,
                "shipped_seq": self._seq,
                "acked_seq": self._acked_seq,
                "lag_records": self._seq - self._acked_seq,
                "lag_bytes": self._lag_bytes,
                "ship_errors": self._ship_errors,
                "resyncs": self._resyncs,
                "dropped_records": self._drops,
                "enqueue_ms_mean": round(
                    self._enqueue_ms / self._enqueues, 5
                ) if self._enqueues else None,
                "last_contact_t": self._last_contact_t,
                "last_catchup_t": self._last_catchup_t,
            }

    # ------------------------------------------------------------ transport

    def _rpc(self, req: dict) -> dict:
        """One request/reply on the persistent standby connection.
        Bounded everywhere: connect and per-frame socket timeouts."""
        with self._cond:
            conn = self._conn
        if conn is None:
            faultplan.check_connect(self.target[0], self.target[1])
            conn = socket.create_connection(
                self.target, timeout=SHIP_CONNECT_TIMEOUT
            )
            with self._cond:
                self._conn = conn
        try:
            conn.settimeout(SHIP_RPC_TIMEOUT)
            protocol.send_frame(conn, req, self.secret)
            return protocol.recv_frame(conn, self.secret)
        except Exception:
            self._drop_conn()
            raise

    def _drop_conn(self) -> None:
        with self._cond:
            conn, self._conn = self._conn, None
            self._connected = False
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    # -------------------------------------------------------------- loop

    def _run(self) -> None:
        backoff = 0.2
        warned = False
        while not self._stop.is_set():
            with self._cond:
                due = (
                    self._records
                    or self._need_catchup
                    or self._last_contact_t is None
                    or time.monotonic() - self._last_contact_t
                    >= self._heartbeat_s
                )
                if not due:
                    self._cond.wait(timeout=self._heartbeat_s / 2.0)
                    continue
            if self._stop.is_set():
                break
            try:
                self._ship_once()
                backoff = 0.2
                if warned:
                    logger.info(
                        "replication to standby %s recovered", self.name
                    )
                    warned = False
            except _Fenced as e:
                logger.warning(
                    "replication fenced by epoch %d (primary %s) — "
                    "demoting", e.epoch, e.primary or "unknown",
                )
                if self._on_fenced is not None:
                    self._on_fenced(e.epoch, e.primary)
                return  # the demoted daemon stops this shipper
            except Exception as e:  # noqa: BLE001 - a dead standby must
                # never hurt the primary: log once per outage, back off,
                # let lag accrue (the stats/lag gauge is the operator
                # signal).
                with self._cond:
                    self._ship_errors += 1
                    self._need_catchup = True
                if not warned:
                    logger.warning(
                        "replication to standby %s failing (%s: %s); "
                        "admits are unaffected, lag will accrue",
                        self.name, type(e).__name__, e,
                    )
                    warned = True
                self._stop.wait(timeout=backoff)
                backoff = min(backoff * 2.0, SHIP_BACKOFF_MAX_S)
        self._drop_conn()

    def _mark_contact(self, acked_seq=None, catchup: bool = False) -> None:
        with self._cond:
            self._connected = True
            self._last_contact_t = time.monotonic()
            if catchup:
                self._last_catchup_t = time.time()
            if acked_seq is not None:
                self._acked_seq = max(self._acked_seq, int(acked_seq))
            lag = self._seq - self._acked_seq
        obs.metric_set("serve.ship_lag", lag)

    def _chaos(self, cmd: str, seq: int, n: int, text: str):
        """The ``serve.ship`` site: (possibly mangled text, dropped?).
        Fires AFTER the snapshot/batch is final and its checksum is
        computed — the standby's integrity check is what keeps a
        corrupt record from ever being applied."""
        rule = faultplan.fire("serve.ship", cmd=cmd, seq=seq, n=n)
        if rule is None:
            return text, False
        if rule.action == "delay":
            time.sleep(rule.delay_s)
            return text, False
        if rule.action == "drop":
            return text, True
        plan = faultplan.active()
        mangled = plan.mutate(rule, text.encode())
        return mangled.decode("utf-8", "replace"), False

    def _chaos_spill(self, sha: str, data):
        """The ``serve.ship`` site on the SPILL path (cmd="spill"):
        (possibly mangled bytes, dropped?).  A mangled spill fails the
        standby's verify-then-write sha check (journal.store_spill), so
        it stays in ``need_spills`` and is re-asked on the next ship —
        corruption converges through re-request, never a bad write."""
        rule = faultplan.fire("serve.ship", cmd="spill", sha=sha, n=1)
        if rule is None:
            return data, False
        if rule.action == "delay":
            time.sleep(rule.delay_s)
            return data, False
        if rule.action == "drop":
            return data, True
        if data is None:
            return data, False
        plan = faultplan.active()
        return plan.mutate(rule, data), False

    def _ship_once(self) -> None:
        if self._catchup_due():
            self._catchup()
        while not self._stop.is_set():
            with self._cond:
                if self._need_catchup:
                    return  # a resync was requested mid-stream
                batch = []
                size = 0
                while self._records and len(batch) < SHIP_BATCH_MAX:
                    seq, rec = self._records.popleft()
                    batch.append((seq, rec))
                    size += len(json.dumps(rec, separators=(",", ":")))
                self._lag_bytes = max(0, self._lag_bytes - size)
            if not batch:
                with self._cond:
                    stale = (
                        self._last_contact_t is None
                        or time.monotonic() - self._last_contact_t
                        >= self._heartbeat_s
                    )
                    next_seq = self._seq + 1
                if stale:
                    # Heartbeat: an empty ship keeps the standby's lease
                    # fresh and collects the current ack.
                    self._send_ship(next_seq, [])
                return
            seq_from = batch[0][0]
            self._send_ship(seq_from, [rec for _, rec in batch])

    def _send_ship(self, seq_from: int, records: list[dict]) -> None:
        text, checksum = records_blob(records)
        text, dropped = self._chaos("ship", seq_from, len(records), text)
        if dropped:
            # The batch vanishes in flight: the next ship's sequence gap
            # makes the standby ask for a resync — convergence through
            # the snapshot, never silent divergence.
            return
        with obs.span("serve.ship", cmd="ship", n=len(records)):
            reply = self._rpc({
                "cmd": "ship",
                protocol.EPOCH_KEY: int(self._epoch_fn()),
                "from": self._advertise,
                "seq_from": int(seq_from),
                "records": text,
                "sum": checksum,
            })
        self._check_fenced(reply)
        if reply.get("status") != "ok":
            raise RuntimeError(f"standby answered: {reply.get('error')}")
        self._mark_contact(acked_seq=reply.get("acked_seq"))
        if reply.get("resync"):
            with self._cond:
                self._resyncs += 1
                self._need_catchup = True
            return
        self._send_spills(reply.get("need_spills") or ())

    def _catchup_due(self) -> bool:
        with self._cond:
            return self._need_catchup

    def _catchup(self) -> None:
        # Drain the incremental queue FIRST, then snapshot: anything
        # enqueued before the snapshot read is inside it (duplicates
        # with later enqueues are harmless — replay dedups by job id),
        # and the snapshot seq restarts the contiguous stream.
        with self._cond:
            self._records.clear()
            self._lag_bytes = 0
        records = self.journal.live_records()
        with self._cond:
            snapshot_seq = self._seq
        text, checksum = records_blob(records)
        text, dropped = self._chaos(
            "catchup", snapshot_seq, len(records), text
        )
        if dropped:
            return  # still flagged need_catchup: the next pass retries
        with obs.span("serve.ship", cmd="catchup", n=len(records)):
            reply = self._rpc({
                "cmd": "ship_catchup",
                protocol.EPOCH_KEY: int(self._epoch_fn()),
                "from": self._advertise,
                "seq": int(snapshot_seq),
                "records": text,
                "sum": checksum,
            })
        self._check_fenced(reply)
        if reply.get("status") != "ok":
            raise RuntimeError(f"standby answered: {reply.get('error')}")
        if reply.get("resync"):
            # The snapshot itself arrived damaged (chaos corrupt):
            # retry on the next pass, nothing was applied.
            with self._cond:
                self._resyncs += 1
            self._mark_contact()
            return
        self._send_spills(reply.get("need_spills") or ())
        self._mark_contact(acked_seq=reply.get("acked_seq"), catchup=True)
        with self._cond:
            self._need_catchup = False

    def _send_spills(self, shas) -> None:
        """On-demand spill transfer: the standby asked for corpus bytes
        it lacks.  A spill the primary's compaction already GC'd ships
        as ``gone`` — its job went terminal, and the terminal record
        (already in the stream, behind the snapshot the standby asked
        from) retires the job before promotion could miss the bytes."""
        for sha in shas:
            sha = str(sha)
            data = self.journal.read_spill(sha)
            data, dropped = self._chaos_spill(sha, data)
            if dropped:
                # In-flight loss must not be silent: raising sends _run
                # through its retry path (need_catchup + backoff), and
                # the standby re-asks for the sha it still lacks.
                raise RuntimeError(
                    f"spill {sha[:12]} dropped in flight; standby still "
                    "awaits it"
                )
            req = {
                "cmd": "ship_spill",
                protocol.EPOCH_KEY: int(self._epoch_fn()),
                "from": self._advertise,
                "sha": sha,
            }
            if data is None:
                req["gone"] = True
            else:
                req["data_b64"] = base64.b64encode(data).decode()
            with obs.span("serve.ship", cmd="spill", n=1):
                reply = self._rpc(req)
            self._check_fenced(reply)
            if reply.get("status") != "ok":
                raise RuntimeError(
                    f"standby refused spill {sha[:12]}: {reply.get('error')}"
                )

    def _check_fenced(self, reply: dict) -> None:
        if reply.get("code") == "stale_epoch":
            raise _Fenced(
                int(reply.get("epoch") or 0), reply.get("primary")
            )


class _Fenced(Exception):
    """A receiver rejected our epoch: a newer primary exists."""

    def __init__(self, epoch: int, primary: str | None):
        self.epoch = epoch
        self.primary = primary
        super().__init__(f"fenced by epoch {epoch}")


class ShipReceiver:
    """Standby-side record application (the daemon routes ship commands
    here after fencing).  Applies records into the standby's OWN journal
    — verbatim, in order, admits fsync'd — and tracks the sequence
    high-water mark for gap detection.  Thread-safe: connection handler
    threads apply concurrently in principle (one primary sends serially,
    but the lock keeps a reconnect race ordered)."""

    def __init__(self, journal):
        self.journal = journal
        self._lock = threading.Lock()
        self._applied_seq = 0
        self._applied_records = 0
        self._resyncs_answered = 0
        self._catchups = 0
        self._last_contact_t: float | None = None
        self._primary: str | None = None
        # Spill shas this standby has ASKED for but not yet received:
        # an applied admit is only failover-SAFE once its corpus bytes
        # landed too, so "replication caught up" for an operator (and
        # the drills) is lag == 0 AND missing_spills == 0 — the ship
        # ack alone leaves a window where a dying primary strands an
        # acked job on a spill still in flight.
        self._awaiting_spills: set[str] = set()

    # ------------------------------------------------------------ queries

    def primary(self) -> str | None:
        """The primary's advertised address, learned from ship traffic
        (fresher than any static seed after a chain of failovers)."""
        with self._lock:
            return self._primary

    def contact_age_s(self) -> float | None:
        with self._lock:
            if self._last_contact_t is None:
                return None
            return time.monotonic() - self._last_contact_t

    def touch(self) -> None:
        """Reset the lease clock (daemon start / promotion reversal)."""
        with self._lock:
            self._last_contact_t = time.monotonic()

    def stats(self) -> dict:
        with self._lock:
            return {
                "applied_seq": self._applied_seq,
                "applied_records": self._applied_records,
                "resyncs_answered": self._resyncs_answered,
                "catchups": self._catchups,
                "missing_spills": len(self._awaiting_spills),
                "primary": self._primary,
                "contact_age_s": (
                    round(time.monotonic() - self._last_contact_t, 3)
                    if self._last_contact_t is not None else None
                ),
            }

    # ----------------------------------------------------------- handlers

    def _note_contact(self, req: dict) -> None:
        with self._lock:
            self._last_contact_t = time.monotonic()
            if req.get("from"):
                self._primary = str(req["from"])

    def _missing_spills(self, records: list[dict]) -> list[str]:
        shas = []
        for rec in records:
            sha = str(rec.get("corpus_sha") or "")
            if (
                rec.get("rec") == "admit" and sha
                and not self.journal.spill_exists(sha)
                and sha not in shas
            ):
                shas.append(sha)
        with self._lock:
            self._awaiting_spills.update(shas)
        return shas

    def handle_ship(self, req: dict) -> dict:
        self._note_contact(req)
        records = decode_blob(
            str(req.get("records", "")), str(req.get("sum", ""))
        )
        with self._lock:
            acked = self._applied_seq
        if records is None:
            # Corrupt in flight (the serve.ship chaos contract): apply
            # NOTHING, ask the primary to resync through a snapshot.
            with self._lock:
                self._resyncs_answered += 1
            return {"status": "ok", "acked_seq": acked, "resync": True,
                    "why": "checksum"}
        seq_from = int(req.get("seq_from") or 0)
        if seq_from > acked + 1:
            # Gap: a dropped ship (or a primary restart's fresh seq
            # space).  Nothing is applied out of order — the snapshot
            # catch-up converges.  Checked BEFORE the heartbeat
            # early-return: a heartbeat carries seq_from = last+1, so a
            # drop followed by a quiescent stream is detected by the
            # very next heartbeat instead of never (the records the
            # standby missed may have been the last ones for hours).
            with self._lock:
                self._resyncs_answered += 1
            return {"status": "ok", "acked_seq": acked, "resync": True,
                    "why": "gap"}
        if not records:
            return {"status": "ok", "acked_seq": acked}  # heartbeat
        applied = 0
        for rec in records:
            if not self._valid_record(rec):
                with self._lock:
                    self._resyncs_answered += 1
                return {"status": "ok", "acked_seq": acked,
                        "resync": True, "why": "malformed"}
            self.journal.apply_record(rec)
            applied += 1
        with self._lock:
            self._applied_seq = max(
                self._applied_seq, seq_from + len(records) - 1
            )
            self._applied_records += applied
            acked = self._applied_seq
        return {
            "status": "ok",
            "acked_seq": acked,
            "need_spills": self._missing_spills(records),
        }

    def handle_catchup(self, req: dict) -> dict:
        self._note_contact(req)
        records = decode_blob(
            str(req.get("records", "")), str(req.get("sum", ""))
        )
        with self._lock:
            acked = self._applied_seq
        if records is None or not all(
            self._valid_record(r) for r in records
        ):
            with self._lock:
                self._resyncs_answered += 1
            return {"status": "ok", "acked_seq": acked, "resync": True,
                    "why": "checksum"}
        self.journal.reset_to(records)
        with self._lock:
            self._applied_seq = int(req.get("seq") or 0)
            self._applied_records += len(records)
            self._catchups += 1
            acked = self._applied_seq
            # The snapshot defines a fresh live universe: spill debts
            # from before the reset must not linger as phantom
            # missing_spills after their jobs were compacted away.
            self._awaiting_spills.clear()
        return {
            "status": "ok",
            "acked_seq": acked,
            "need_spills": self._missing_spills(records),
        }

    def handle_spill(self, req: dict) -> dict:
        self._note_contact(req)
        sha = str(req.get("sha") or "")
        if not sha:
            return structured_error("bad_spec", "ship_spill without a sha")
        if req.get("gone"):
            # The primary's compaction GC'd it: the job went terminal,
            # and its terminal record retires the admit before this
            # standby would ever need the bytes.  Log and move on — the
            # compaction-vs-catch-up race must not strand us.
            logger.info(
                "standby: spill %s is gone on the primary (job went "
                "terminal); continuing", sha[:12],
            )
            with self._lock:
                self._awaiting_spills.discard(sha)
            return {"status": "ok", "stored": False}
        try:
            data = base64.b64decode(str(req.get("data_b64", "")))
        except (ValueError, TypeError):
            return structured_error("bad_spec", "ship_spill bad payload")
        stored = self.journal.store_spill(sha, data)
        if stored:
            with self._lock:
                self._awaiting_spills.discard(sha)
        return {"status": "ok", "stored": stored}

    @staticmethod
    def _valid_record(rec: dict) -> bool:
        """Shape gate before a shipped record touches the standby's
        journal: the wire-level checksum already matched, so this only
        screens records a buggy (not corrupt) sender could form."""
        kind = rec.get("rec")
        if kind == "admit":
            return bool(rec.get("job_id"))
        if kind == "state":
            return bool(rec.get("job_id")) and isinstance(
                rec.get("state"), str
            )
        return False
