"""Locust Serve: the persistent multi-tenant engine daemon.

The one-shot CLI pays full cold start on every run — process spawn,
backend probe, 20-40 s TPU compile, cold caches (CLAUDE.md).  This daemon
keeps ONE process resident and serves many concurrent jobs against warm
compiled executables (docs/SERVING.md):

  * **protocol**: the distributor's authenticated length-prefixed frames
    (distributor/protocol.py — HMAC, replay guard, the same negotiation
    stance), with a serve-specific closed command set::

        submit | status | result | cancel | invalidate | stats
        | ping | shutdown

  * **admission + fairness**: a bounded queue that rejects-with-reason
    when full and a per-tenant weighted fair scheduler
    (serve/scheduler.py) so one heavy tenant cannot starve the rest;
  * **warm-executable cache**: compiled programs keyed by (workload,
    EngineConfig fingerprint, shape bucket) — repeat jobs skip
    compilation (serve/cache.py);
  * **shape-bucketed batching**: compatible queued jobs coalesce into one
    vmapped engine dispatch and demultiplex per-job results
    (serve/batch.py, engine.run_batch);
  * **result cache**: (corpus digest, job spec) -> finished table, with
    explicit invalidation, persisted across restarts through the async
    snapshot writer (serve/cache.WarmState -> io/snapshot.py).

Error discipline (pinned by the chaos matrix, tests/test_faults.py): a
client observes either a correct result or a STRUCTURED error carrying a
``jobs.ERROR_CODES`` reason — never a silent wrong answer.  The
``serve.admit`` and ``serve.dispatch`` fault sites (utils/faultplan.py)
inject failures at the admission and dispatch boundaries to keep that
claim honest.

Telemetry (docs/OBSERVABILITY.md): per-job phases land as ``serve.*``
spans — queue wait, compile-or-hit, dispatch, demux — plus admission
events and latency/cache metrics, all in the closed obs registry (R009).
"""

from __future__ import annotations

import base64
import concurrent.futures
import contextlib
import dataclasses
import hashlib
import logging
import os
import shutil
import socket
import tempfile
import threading
import time
import uuid

from locust_tpu import obs
from locust_tpu.distributor import protocol
from locust_tpu.serve import batch as batching
from locust_tpu.serve.cache import (
    ExecutableCache,
    ResultCache,
    SubPlanCache,
    WarmState,
)
from locust_tpu.config import EngineConfig
from locust_tpu.serve.jobs import (
    WORKLOADS,
    Job,
    JobSpec,
    parse_spec,
    structured_error,
)
from locust_tpu.serve.jobs import pairs_bytes as jobs_pairs_bytes
from locust_tpu.plan import PlanError
from locust_tpu.serve import replicate
from locust_tpu.serve.journal import JobJournal
from locust_tpu.serve.pool import PoolDispatchError
from locust_tpu.serve.scheduler import AdmitReject, FairScheduler
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")

SERVE_COMMANDS = (
    "ping", "submit", "status", "result", "cancel", "invalidate",
    "stats", "shutdown",
    # High availability (docs/SERVING.md): "promote" flips a standby to
    # primary (fenced epoch bump + journal replay); the ship commands
    # are the primary->standby WAL replication stream
    # (serve/replicate.py; protocol.SHIP_COMMANDS).
    "promote", "ship", "ship_catchup", "ship_spill",
)

# Job-plane commands a STANDBY refuses with the structured not_primary
# code (naming the primary so roster clients redirect transparently).
# stats/ping/promote/ship* stay answerable — that is what "hot" means.
_PRIMARY_ONLY_COMMANDS = (
    "submit", "status", "result", "cancel", "invalidate",
)


class _PlanSolo(Exception):
    """Internal control flow for the plan coordinator: demote this plan
    job to the solo local engine, with a named reason.  Raised by the
    distributed path's safety gates (unrecognized shape raced in, too
    few placeable workers, a fold that would truncate where the solo
    evaluator's accounting differs) — the handler releases placements,
    counts ``plan_solo_fallbacks`` and runs the solo floor.  Never
    silent (docs/PLAN.md "Distributed execution")."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


@dataclasses.dataclass
class ServeConfig:
    """Daemon capacity/policy knobs (docs/SERVING.md)."""

    max_queue: int = 64          # admission bound: pending jobs, global
    max_batch: int = 8           # jobs coalesced into one dispatch
    tenant_quota: int | None = 32  # pending jobs per tenant (None = off)
    max_engines: int = 4         # warm engines kept (LRU)
    max_results: int = 256       # result-cache entries kept (LRU)
    max_result_bytes: int = 256 << 20  # result-cache aggregate byte cap
    # Sub-plan (per-edge) result cache byte cap — plan fold values
    # shared across tenants by closure fingerprint (docs/PLAN.md
    # "Optimizer"); entry count rides max_results.
    max_subplan_bytes: int = 128 << 20
    # Aggregate cap on result payloads retained by FINISHED job records
    # (max_history bounds record COUNT; 1024 records of multi-MB pairs
    # would be GBs of RSS).  Past it the oldest finished records are
    # evicted whole — a later result fetch reads unknown_job, exactly
    # like the existing count-cap eviction.
    max_history_bytes: int = 256 << 20
    max_corpus_bytes: int = 16 << 20  # inline submit payload cap
    # Aggregate cap on ALL buffered in-flight corpora: max_queue bounds
    # job COUNT, but max_queue * max_corpus_bytes of buffered bytes
    # (1 GiB at defaults) is an OOM, and overload must become a
    # structured rejection, not a dead daemon.
    max_queue_bytes: int = 256 << 20
    warm_dir: str | None = None  # persist warm state here (None = off)
    warm_every: int = 8          # warm-state generation cadence (jobs)
    max_history: int = 1024      # finished jobs kept for status/result
    conn_timeout: float = 30.0
    max_connections: int = 32
    dispatch_poll_s: float = 0.25  # dispatcher wake cadence when idle
    # Durability (docs/SERVING.md): the write-ahead job journal.  With a
    # journal_dir set, every accepted job is fsync'd to disk BEFORE its
    # accept ack, and a restart replays unfinished jobs under their
    # original ids — kill -9 mid-batch loses no acked work.
    journal_dir: str | None = None
    journal_fsync: bool = True       # False trades the kill -9 window for speed
    journal_compact_every: int = 512  # appends between journal compactions
    # Retry ladder (docs/SERVING.md): exponential backoff base/cap for
    # failed dispatches.  Attempts per job are bounded by the SPEC's
    # max_attempts; these bound how long each wait between them is.
    retry_base_s: float = 0.2
    retry_cap_s: float = 5.0
    # Scale-out dispatch (docs/SERVING.md): place batches across a pool
    # of serve-capable distributor workers ("host:port" roster; empty =
    # every batch folds on the daemon's local engine, exactly the
    # pre-pool behavior).  The local engine stays the FLOOR: a saturated
    # or dead pool degrades to local dispatch, never to a dead daemon.
    workers: tuple = ()
    pool_inflight: int = 1           # concurrent batches per worker
    pool_rpc_timeout: float = 600.0  # bound on one worker dispatch RPC
    # Content-addressed corpus spill the pool workers read (<sha>.bin):
    # defaults to the journal's spill dir when journaling, else a
    # daemon-owned temp dir.  Workers must share this filesystem.
    pool_spill_dir: str | None = None
    # Large-job sharding: a job of >= shard_min_blocks blocks fans out
    # over up to shard_max workers (contiguous block-aligned line
    # ranges) and merges through the engine's combine; fewer than 2
    # placeable workers = the whole job folds locally.
    shard_min_blocks: int = 64
    shard_max: int = 4
    # Distributed plan execution (docs/PLAN.md "Distributed execution"):
    # a map/reduce stage attempt still unfinished this many seconds
    # after launch gets ONE speculative backup attempt on another held
    # worker — first finisher wins, the loser's partitions are ignored
    # (attempt-suffixed filenames keep them from colliding).
    plan_speculate_s: float = 30.0
    # High availability (docs/SERVING.md "High availability"): with
    # ship_to set ("host:port" of a hot standby) the primary ships
    # every fsync'd WAL record there asynchronously (serve/replicate.py)
    # — a dead standby degrades to a logged warning + lag gauge, never a
    # slow admit.  With standby_of set ("host:port" of the primary, the
    # address not_primary rejections name until ship traffic refines
    # it) the daemon starts as a WARM STANDBY: it applies shipped
    # records into its own journal, answers stats/ping only, and
    # refuses the job plane until promoted — by the explicit `promote`
    # command, or automatically when lease_s passes with no primary
    # contact (None = manual promotion only).  Both require journal_dir
    # (the WAL is what ships).
    ship_to: str | None = None
    standby_of: str | None = None
    lease_s: float | None = None
    ship_heartbeat_s: float = 2.0


class ServeDaemon:
    """One serve daemon: accept loop + single dispatcher thread.

    Maps serialize through the ONE dispatcher (the node has one
    accelerator — same stance as the distributor worker's map lock);
    handler threads only touch the queue, the caches, and job records.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: bytes = b"",
        cfg: ServeConfig | None = None,
    ):
        if not secret:
            raise ValueError("serve daemon requires a shared secret "
                             "(same Q8 stance as the distributor)")
        self.secret = secret
        self.cfg = cfg or ServeConfig()
        self.scheduler = FairScheduler(
            max_queue=self.cfg.max_queue,
            max_batch=self.cfg.max_batch,
            tenant_quota=self.cfg.tenant_quota,
        )
        self.executables = ExecutableCache(max_engines=self.cfg.max_engines)
        self.results = ResultCache(
            max_entries=self.cfg.max_results,
            max_bytes=self.cfg.max_result_bytes,
        )
        # Per-edge fold results for plan jobs (the optimizer's CSE +
        # incremental-refold substrate, docs/PLAN.md "Optimizer").
        # In-memory only: WAL replay recomputes from cold, identically.
        self.subplans = SubPlanCache(
            max_entries=self.cfg.max_results,
            max_bytes=self.cfg.max_subplan_bytes,
        )
        self.warm = (
            WarmState(self.cfg.warm_dir, self.results)
            if self.cfg.warm_dir
            else None
        )
        if self.warm is not None:
            self.warm.load()
        self.journal = (
            JobJournal(
                self.cfg.journal_dir,
                fsync=self.cfg.journal_fsync,
                compact_every=self.cfg.journal_compact_every,
            )
            if self.cfg.journal_dir
            else None
        )
        # High availability (docs/SERVING.md): roles, fencing epoch, and
        # the replication endpoints.  Both sides of the pair need the
        # WAL — it is the thing that ships.
        if (self.cfg.ship_to or self.cfg.standby_of) \
                and self.journal is None:
            raise ValueError(
                "--ship-to / --standby-of require --journal-dir: the "
                "write-ahead journal is what replication ships"
            )
        self.role = "standby" if self.cfg.standby_of else "primary"
        self.epoch = (
            replicate.load_epoch(self.cfg.journal_dir)
            if self.journal is not None else 1
        )
        self._seen_epoch = self.epoch   # highest epoch observed anywhere
        self._primary_hint = self.cfg.standby_of  # who not_primary names
        self._fenced_by: int | None = None  # epoch that demoted us, if any
        self._promote_lock = threading.Lock()  # serializes role flips
        self.receiver = (
            replicate.ShipReceiver(self.journal)
            if self.journal is not None else None
        )
        if self.receiver is not None:
            self.receiver.touch()  # the lease clock starts now
        self.shipper = None
        self.pool = None
        self._pool_spill_owned: str | None = None
        if self.cfg.workers:
            from locust_tpu.serve.pool import WorkerPool

            spill_dir = self.cfg.pool_spill_dir
            if spill_dir is None and self.journal is not None:
                # Share the journal's content-addressed spill: admitted
                # corpora are already on disk there, so pool dispatches
                # re-serialize nothing.
                spill_dir = self.journal.corpus_dir
            if spill_dir is None:
                spill_dir = tempfile.mkdtemp(prefix="locust-serve-pool-")
                self._pool_spill_owned = spill_dir
            self.pool = WorkerPool(
                self.cfg.workers,
                secret,
                spill_dir=spill_dir,
                max_inflight=self.cfg.pool_inflight,
                rpc_timeout=self.cfg.pool_rpc_timeout,
                # Fencing: every serve_batch RPC carries this daemon's
                # promotion epoch; a worker that has seen a newer
                # primary answers structured stale_epoch and the zombie
                # demotes instead of split-braining (docs/SERVING.md).
                epoch_fn=lambda: self.epoch,
                # A pool-owned dir has no journal compaction behind it:
                # cap it so a long-running distinct-corpus stream cannot
                # fill the disk (evicted spills re-spill on retry).
                spill_cap_bytes=(
                    2 * self.cfg.max_queue_bytes
                    if self._pool_spill_owned else None
                ),
            )
            # Warm-cache RPC: re-learn which worker already holds which
            # compiled shapes (a daemon restart against a warm fleet
            # must not cold-spray its first batches).  Best-effort.
            for w in self.pool.workers:
                self.pool.seed_affinity(w)
            # Shard coordinators run OFF the dispatcher thread: a
            # coordinator blocks (bounded) on its shard futures, and
            # parking the single dispatcher there would stall every
            # other tenant's dispatch and the deadline sweep for up to
            # pool_rpc_timeout.  Dedicated and small on purpose —
            # coordinators submit shard RPCs to the POOL executor, so
            # sharing that executor could deadlock with every thread a
            # waiting coordinator.
            self._shard_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="serve-shard"
            )
        self._lock = threading.Lock()
        # The node has ONE accelerator (the worker daemon's _map_lock
        # stance): every LOCAL device touch — engine folds, and the
        # shard coordinators' merge/local-fallback paths, which run on
        # their own executor — serializes here.  Remote RPC waits are
        # just sockets and never take it.
        self._engine_lock = threading.Lock()
        self._jobs: dict[str, Job] = {}       # insertion order = age
        # Distributed-plan coordinator state (docs/PLAN.md "Distributed
        # execution"), both under self._lock: stage/recompute counters
        # surfaced in the stats "pool" sub-dict, and WAL-replayed stage
        # progress (job_id -> completed map-split records) so a restart
        # reuses surviving shuffle partitions instead of remapping.
        self._plan_counters = {
            "stages": 0, "recomputes": 0,
            "speculated": 0, "partitions_reused": 0,
            # Satellite of the plan-surface-v2 round: a pool-eligible
            # plan job demoted to the solo engine is NEVER silent (the
            # fused_demoted stance) — counted here, logged once per
            # reason (_count_plan_solo).
            "plan_solo_fallbacks": 0,
            # Distributed map splits that landed on a worker's warm
            # fold-node executable (cache.fold_node_key): a repeat
            # distributed plan should push this up while the workers'
            # ``compiles`` stay flat.
            "map_warm_hits": 0,
        }
        self._plan_solo_logged: set[str] = set()
        self._plan_progress: dict[str, list] = {}
        self._corpus_bytes: dict[str, bytes] = {}  # job_id -> in-flight bytes
        self._corpus_total = 0  # sum of _corpus_bytes values (admission cap)
        self._result_bytes = 0  # sum of retained job.result_bytes (history cap)
        self._completed = 0
        self._warm_marked = 0  # completed-count at the last warm mark
        self._started_s = time.monotonic()
        self._replay_guard = protocol.ReplayGuard()
        self._conn_slots = threading.BoundedSemaphore(self.cfg.max_connections)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(5)
        self.addr = self._sock.getsockname()
        self._shutdown = threading.Event()
        self._closed = False
        # Replay BEFORE the dispatcher exists: re-enqueued jobs must be
        # fully staged (record + corpus) before anything can pop them —
        # the same record-before-admit ordering the submit path keeps.
        # A STANDBY deliberately skips replay: its journal mirrors the
        # primary's live set via shipping, and promotion is the moment
        # replay (and dispatch) begins.
        if self.journal is not None and self.role == "primary":
            self._replay_journal()
        if self.cfg.ship_to and self.role == "primary":
            self._start_shipper()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatch", daemon=True
        )
        self._dispatcher.start()

    def _start_shipper(self) -> None:
        """Wire the async WAL shipper to the standby (primary role only;
        replay has already run, so the first catch-up snapshot carries
        exactly the live set)."""
        from locust_tpu.serve.pool import parse_worker_addr

        self.shipper = replicate.ReplicationShipper(
            parse_worker_addr(self.cfg.ship_to),
            self.secret,
            self.journal,
            epoch_fn=lambda: self.epoch,
            advertise=f"{self.addr[0]}:{self.addr[1]}",
            on_fenced=self._demote,
            heartbeat_s=self.cfg.ship_heartbeat_s,
        )
        self.journal.on_append = self.shipper.enqueue
        self.shipper.start()

    # --------------------------------------------------------- accept loop

    def serve_forever(self) -> None:
        # try/finally, not loop-exit cleanup: a KeyboardInterrupt in the
        # foreground CLI lands inside accept() and would otherwise skip
        # close() — losing the final warm-state flush the --warm-dir
        # flag promises (close() is idempotent, so the shutdown-command
        # path calling through here again is safe).
        try:
            while not self._shutdown.is_set():
                try:
                    self._sock.settimeout(0.5)
                    conn, _peer = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                # Bounded acquire: a plain acquire() with all slots held
                # by slow peers would wedge this loop PAST the shutdown
                # check — neither a shutdown command nor close() could
                # ever land.
                acquired = False
                while not self._shutdown.is_set():
                    if self._conn_slots.acquire(timeout=0.5):
                        acquired = True
                        break
                if not acquired:
                    conn.close()
                    continue
                threading.Thread(
                    target=self._serve_one, args=(conn,), daemon=True
                ).start()
        finally:
            self._sock.close()
            self.close()

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def close(self) -> None:
        """Stop the dispatcher and flush warm state.  Idempotent and
        race-safe: the accept loop's exit path and an operator teardown
        may both call it (first caller wins the warm flush)."""
        self._shutdown.set()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            gen = self._completed
        self.scheduler.stop()
        # Local snapshot: a concurrent fenced _demote() nulls the
        # attribute, and `if self.shipper ...: self.shipper.stop()`
        # would re-read it after the check.
        shipper = self.shipper
        if shipper is not None:
            # Before the dispatcher join: the shipper only reads the
            # journal and its own queue, and stopping it first means the
            # final terminal records below are the last thing it could
            # have shipped anyway (the standby's replay recomputes
            # whatever a lost flush-only record would have said).
            shipper.stop()
        # The join must outlive one TPU cold compile (20-40s per
        # CLAUDE.md): a shorter timeout lets close() flush + close the
        # warm writer while a dispatch is mid-compile, so that batch's
        # late warm.mark hits a closed writer and its jobs silently
        # miss the persisted state.
        self._dispatcher.join(timeout=90.0)
        if self._dispatcher.is_alive():
            logger.warning(
                "serve dispatcher still busy after 90s at close; jobs "
                "finishing after this point will not reach warm state"
            )
        if self.pool is not None:
            # Pool teardown ordering (docs/SERVING.md): stop placements
            # and join inflight worker RPCs (bounded) BEFORE the
            # stranded-job drain and the warm flush — a remote batch
            # landing during the drain still publishes its results, and
            # a batch that dies with its worker requeues onto the
            # stopped scheduler, fails structured shutting_down below.
            self.pool.close(timeout=30.0)
            # After the pool's sockets close, any coordinator still
            # waiting sees its shard futures fail fast and routes its
            # job through the stopped scheduler to a structured
            # shutting_down — nothing left is worth blocking on.
            self._shard_executor.shutdown(wait=False, cancel_futures=True)
            if self._pool_spill_owned:
                shutil.rmtree(self._pool_spill_owned, ignore_errors=True)
        # The stopped scheduler answers next_batch with None forever, so
        # jobs still queued here can never dispatch: fail them with the
        # structured shutdown code and free their buffered corpora
        # instead of abandoning them in state "queued" — an accepted job
        # must end in a result or a reason code, even at teardown.
        stranded = self.scheduler.drain()
        if stranded:
            with self._lock:
                for job in stranded:
                    self._corpus_pop(job.job_id)
            self._fail_batch(stranded, structured_error(
                "shutting_down",
                "daemon shut down before this job was dispatched; "
                "resubmit after it returns",
            ))
        if self.warm is not None:
            try:
                self.warm.mark(gen + 1)  # final generation: latest results
            except Exception:  # noqa: BLE001 - a failed PRIOR background
                # write re-raises at the next submit (io/snapshot.py);
                # the flush is best-effort at shutdown and must not
                # leave the writer thread unjoined (close is guarded by
                # _closed, so an escape here is permanently unretryable).
                logger.exception("serve final warm mark failed")
            self.warm.close()
        if self.journal is not None:
            # Clean shutdown leaves a compact journal: stranded jobs were
            # just failed structured above, so nothing is live and the
            # next start replays an (almost) empty log.
            try:
                self._compact_journal()
            except Exception:  # noqa: BLE001 - best-effort at teardown
                logger.exception("serve journal compaction failed at close")
            self.journal.close()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            self._serve_conn(conn)
        finally:
            self._conn_slots.release()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._shutdown.is_set():
                    try:
                        conn.settimeout(self.cfg.conn_timeout)
                        req = protocol.recv_frame(conn, self.secret)
                    except PermissionError:
                        return  # unauthenticated peer: drop silently
                    except (ConnectionError, socket.timeout, OSError):
                        return  # peer closed / idled out
                    except Exception as e:
                        self._try_reply(
                            conn, structured_error("bad_spec", str(e))
                        )
                        return
                    try:
                        self._replay_guard.check(req)
                        resp = self._handle(req)
                    except PermissionError:
                        return  # replayed frame: drop silently
                    except Exception as e:  # noqa: BLE001 - daemon survives
                        resp = structured_error(
                            "dispatch_failed", f"{type(e).__name__}: {e}"
                        )
                    if not self._try_reply(conn, resp):
                        return
        except Exception:  # noqa: BLE001 - connection threads never die loud
            logger.exception("serve connection handler failed")

    def _try_reply(self, conn: socket.socket, resp: dict) -> bool:
        try:
            protocol.send_frame(conn, resp, self.secret, sign_fresh=False)
            return True
        except protocol.FrameTooLarge as e:
            # Raised BEFORE any bytes hit the wire (send_frame sizes the
            # whole frame first), so the connection is still clean:
            # answer with a small structured error instead of dropping
            # the peer — a completed job whose result JSON exceeds
            # MAX_FRAME would otherwise be permanently unfetchable
            # through bare ConnectionErrors, against the tier's
            # correct-result-or-structured-error guarantee.
            err = structured_error(
                "result_too_large",
                f"reply frame exceeds protocol.MAX_FRAME "
                f"({protocol.MAX_FRAME} bytes): {e}; lower table_size "
                "or split the corpus",
            )
            try:
                protocol.send_frame(
                    conn, err, self.secret, sign_fresh=False
                )
                return True
            except (protocol.ProtocolError, OSError):
                return False
        except OSError:
            return False

    # ----------------------------------------------------------- commands

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd not in SERVE_COMMANDS:
            return structured_error(
                "unknown_command",
                f"unknown command {cmd!r} (serve speaks {SERVE_COMMANDS})",
            )
        if cmd == "ping":
            return {"status": "ok", "pong": True, "service": "locust-serve"}
        if cmd == "shutdown":
            self._shutdown.set()
            return {"status": "ok", "bye": True}
        if cmd == "promote":
            return self._cmd_promote()
        if cmd in protocol.SHIP_COMMANDS:
            return self._cmd_ship(cmd, req)
        if cmd in _PRIMARY_ONLY_COMMANDS:
            not_primary = self._not_primary_reply()
            if not_primary is not None:
                return not_primary
        if cmd == "submit":
            return self._cmd_submit(req)
        if cmd == "status":
            return self._cmd_status(req)
        if cmd == "result":
            return self._cmd_result(req)
        if cmd == "cancel":
            return self._cmd_cancel(req)
        if cmd == "invalidate":
            return self._cmd_invalidate(req)
        return self._cmd_stats()

    def _cmd_submit(self, req: dict) -> dict:
        try:
            spec, corpus = parse_spec(
                req, max_corpus_bytes=self.cfg.max_corpus_bytes
            )
        except ValueError as e:
            code, _, msg = str(e).partition("\n")
            obs.event("serve.reject", code=code)
            return structured_error(code, msg or code)
        if len(corpus) > self.cfg.max_corpus_bytes:
            obs.event("serve.reject", code="corpus_too_large")
            return structured_error(
                "corpus_too_large",
                f"inline corpus of {len(corpus)} bytes exceeds the "
                f"daemon cap ({self.cfg.max_corpus_bytes}); stream it "
                "through a server-side path instead",
            )
        # Chaos: the admission boundary (docs/FAULTS.md).  "error" models
        # an admission subsystem failure — the client gets a structured
        # rejection and may retry; "delay" models admission contention.
        rule = faultplan.fire(
            "serve.admit", tenant=spec.tenant, workload=spec.workload
        )
        if rule is not None:
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            else:
                obs.event("serve.reject", code="fault_injected")
                return structured_error(
                    "fault_injected",
                    "[faultplan] injected admission failure — retry",
                )
        digest = hashlib.sha256(corpus).hexdigest()
        spec_fp = spec.fingerprint()
        n_lines = batching.count_lines(corpus)
        n_blocks, bucket = batching.job_shape(n_lines, spec.cfg)
        job = Job(
            job_id=uuid.uuid4().hex[:12],
            spec=spec,
            corpus_digest=digest,
            n_lines=n_lines,
            n_blocks=n_blocks,
            bucket=bucket,
            config_overrides=dict(req.get("config") or {}),
        )
        if not spec.no_cache and not spec.invalidate:
            hit = self.results.get_with_meta(digest, spec_fp)
            if hit is not None:
                # Served straight from the result cache: no queue, no
                # engine.  The job record still exists so status/result
                # work uniformly.  The ORIGINAL run's truncation flags
                # replay with the pairs — a lossy result must stay
                # flagged lossy on every replay, or the cache hit would
                # be the silent wrong answer this tier forbids.
                pairs, meta = hit
                job.state = "done"
                job.cache = "result"
                job.started_s = job.submitted_s
                job.finished_s = time.monotonic()
                job.result = pairs
                job.result_bytes = jobs_pairs_bytes(pairs)
                job.distinct = int(meta.get("distinct", len(pairs)))
                job.truncated = bool(meta.get("truncated", False))
                job.overflow_tokens = int(meta.get("overflow_tokens", 0))
                with self._lock:
                    self._result_bytes += job.result_bytes
                    self._remember(job)
                    self._completed += 1
                obs.metric_inc("serve.result_cache_hits")
                obs.metric_inc("serve.jobs")
                obs.metric_observe("serve.latency_ms", job.latency_ms())
                return {
                    "status": "ok", "job_id": job.job_id,
                    "state": "done", "cached": True,
                }
        # Record the job + its bytes BEFORE admit: admit() wakes the
        # dispatcher, which may pop the job immediately — if the corpus
        # landed after, the dispatch would fold an empty stack and hand
        # the client a silently-empty "done" (the exact wrong answer
        # this tier promises never to produce).
        with self._lock:
            over = (
                self._corpus_total + len(corpus)
                > self.cfg.max_queue_bytes
            )
            if not over:
                self._remember(job)
                self._corpus_put(job.job_id, corpus)
        if over:
            self.scheduler.count_rejection()
            obs.event("serve.reject", code="queue_full")
            return structured_error(
                "queue_full",
                f"buffered corpus bytes at cap "
                f"({self.cfg.max_queue_bytes}); retry with backoff",
            )
        # Write-ahead append BEFORE the scheduler sees the job and BEFORE
        # the ack leaves: the record is what makes the ack a durable
        # promise (docs/SERVING.md).  An append that fails must become a
        # structured rejection — acking unjournaled work would silently
        # demote the durability guarantee.
        if self.journal is not None:
            try:
                self.journal.append_admit(job, corpus)
            except faultplan.FaultInjected:
                with self._lock:
                    self._jobs.pop(job.job_id, None)
                    self._corpus_pop(job.job_id)
                obs.event("serve.reject", code="fault_injected")
                return structured_error(
                    "fault_injected",
                    "[faultplan] injected journal crash at append — "
                    "the job was never acked; retry",
                )
            except Exception as e:  # noqa: BLE001 - disk full/permission
                logger.exception("serve journal append failed")
                with self._lock:
                    self._jobs.pop(job.job_id, None)
                    self._corpus_pop(job.job_id)
                obs.event("serve.reject", code="journal_failed")
                return structured_error(
                    "journal_failed",
                    f"write-ahead journal append failed "
                    f"({type(e).__name__}: {e}); the accept ack would "
                    "not be durable — fix the journal volume and retry",
                )
        try:
            self.scheduler.admit(job)
        except AdmitReject as e:
            with self._lock:
                self._jobs.pop(job.job_id, None)
                self._corpus_pop(job.job_id)
            if self.journal is not None:
                # Tombstone so replay cannot resurrect a job the client
                # was told is NOT in the system.
                self.journal.append_state(job.job_id, "rejected")
            obs.event("serve.reject", code=e.code)
            return structured_error(e.code, str(e))
        if spec.invalidate:
            # Only AFTER admission succeeds: a rejected submit must have
            # no side effects — wiping before admission let one tenant's
            # queue_full request destroy the cached entry every other
            # tenant was being served from.  (The cache-hit check above
            # already skips lookups for invalidate submits, so this job
            # recomputes either way.)
            self.results.invalidate(digest=digest, spec_fp=spec_fp)
            # A fresh-recompute request must not be answered from the
            # per-edge cache either (same post-admission discipline).
            self.subplans.invalidate(corpus_sha=digest)
        obs.event(
            "serve.admit",
            job=job.job_id, tenant=spec.tenant, bucket=bucket,
        )
        return {
            "status": "ok", "job_id": job.job_id,
            "state": "queued", "cached": False,
        }

    def _remember(self, job: Job) -> None:
        """Record a job, then evict past the history caps.  Caller
        holds self._lock."""
        self._jobs[job.job_id] = job
        self._evict_history(keep=job.job_id)

    def _evict_history(self, keep: str | None = None) -> None:
        """Evict the OLDEST FINISHED records while over the history
        count cap OR the aggregate retained-result byte cap
        (queued/running records are live state, never evicted).
        ``keep`` is the job whose completion triggered this call: it
        must survive even when its result alone overflows the byte cap,
        or a job could be evicted between its own done-ack and the
        client's result fetch (same stance as ResultCache keeping a
        single oversized entry).  Caller holds self._lock."""

        def over() -> bool:
            return (len(self._jobs) > self.cfg.max_history
                    or self._result_bytes > self.cfg.max_history_bytes)

        if not over():
            return
        for jid, j in list(self._jobs.items()):
            if not over():
                break
            if jid != keep and j.state in ("done", "failed", "cancelled"):
                del self._jobs[jid]
                self._corpus_pop(jid)
                self._result_bytes -= j.result_bytes

    def _job(self, req: dict) -> Job | None:
        with self._lock:
            return self._jobs.get(str(req.get("job_id", "")))

    def _corpus_put(self, job_id: str, data: bytes) -> None:
        """Buffer one job's corpus; caller holds self._lock."""
        self._corpus_bytes[job_id] = data
        self._corpus_total += len(data)

    def _corpus_pop(self, job_id: str) -> bytes | None:
        """Drop one job's buffered corpus; caller holds self._lock."""
        data = self._corpus_bytes.pop(job_id, None)
        if data is not None:
            self._corpus_total -= len(data)
        return data

    def _cmd_status(self, req: dict) -> dict:
        job = self._job(req)
        if job is None:
            return structured_error(
                "unknown_job", f"no job {req.get('job_id')!r}"
            )
        return {"status": "ok", **job.public()}

    def _cmd_result(self, req: dict) -> dict:
        job = self._job(req)
        if job is None:
            return structured_error(
                "unknown_job", f"no job {req.get('job_id')!r}"
            )
        if job.state == "failed":
            err = job.error or structured_error(
                "dispatch_failed", "job failed"
            )
            return dict(err, job_id=job.job_id, state="failed")
        if job.state == "cancelled":
            return structured_error(
                "cancelled", f"job {job.job_id} was cancelled"
            )
        if job.state != "done":
            return dict(
                structured_error(
                    "not_done", f"job {job.job_id} is {job.state}"
                ),
                state=job.state,
            )
        return {
            "status": "ok",
            "job_id": job.job_id,
            "state": "done",
            "cache": job.cache,
            # Plan results are ONE (rendered-output-bytes, 0) pair; the
            # flag tells clients to print the key raw instead of as a
            # key<TAB>count table (docs/PLAN.md).
            "plan": job.spec.plan is not None,
            "distinct": job.distinct,
            "truncated": job.truncated,
            "overflow_tokens": job.overflow_tokens,
            "latency_ms": job.latency_ms(),
            "pairs": [
                [base64.b64encode(k).decode(), int(v)]
                for k, v in (job.result or [])
            ],
        }

    def _cmd_cancel(self, req: dict) -> dict:
        job = self._job(req)
        if job is None:
            return structured_error(
                "unknown_job", f"no job {req.get('job_id')!r}"
            )
        popped = self.scheduler.cancel(job.job_id)
        if popped is not None:
            with self._lock:
                job.state = "cancelled"
                job.finished_s = time.monotonic()
                job.error = structured_error(
                    "cancelled", "cancelled while queued"
                )
                self._corpus_pop(job.job_id)
            if self.journal is not None:
                # The error payload rides the record: replay restores the
                # job's structured code as "cancelled", not a generic
                # failure a client's .code switch would mishandle.
                self.journal.append_state(
                    job.job_id, "cancelled", error=job.error
                )
            return {"status": "ok", "cancelled": True, "state": "cancelled"}
        # Running/finished jobs are past the point of no return — report
        # the state, don't pretend.
        return {"status": "ok", "cancelled": False, "state": job.state}

    def _cmd_invalidate(self, req: dict) -> dict:
        digest = req.get("digest")
        spec_fp = req.get("spec_fp")
        if req.get("job_id"):
            job = self._job(req)
            if job is None:
                # Falling through with (digest, spec_fp) both None hits
                # ResultCache's wipe-everything match: a typo'd or
                # history-evicted id would silently destroy EVERY
                # tenant's cached results and still answer "ok".
                return structured_error(
                    "unknown_job", f"no job {req.get('job_id')!r}"
                )
            digest = job.corpus_digest
            spec_fp = job.spec.fingerprint()
        n = self.results.invalidate(
            digest=str(digest) if digest else None,
            spec_fp=str(spec_fp) if spec_fp else None,
        )
        # Per-edge entries for the same corpus go too (a spec_fp-only
        # invalidation keeps them: closure fingerprints are shared
        # across specs, and other tenants' edges stay warm).
        if digest or not spec_fp:
            n += self.subplans.invalidate(
                corpus_sha=str(digest) if digest else None
            )
        return {"status": "ok", "invalidated": n}

    def _cmd_stats(self) -> dict:
        with self._lock:
            states: dict[str, int] = {}
            for j in self._jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            completed = self._completed
            corpus_total = self._corpus_total
            result_bytes = self._result_bytes
            plan_counters = dict(self._plan_counters)
        return {
            "status": "ok",
            "service": "locust-serve",
            "uptime_s": round(time.monotonic() - self._started_s, 3),
            "completed": completed,
            "jobs_by_state": states,
            "queued_corpus_bytes": corpus_total,
            "history_result_bytes": result_bytes,
            "queue": self.scheduler.stats(),
            # The pool sub-dict carries the distributed-plan coordinator
            # counters (stage RPCs run, recomputes, speculative backups,
            # WAL-replay partition reuse — docs/PLAN.md).
            "pool": (
                dict(self.pool.stats(), plan=plan_counters)
                if self.pool is not None else None
            ),
            "exec_cache": self.executables.stats(),
            "result_cache": self.results.stats(),
            "subplan_cache": self.subplans.stats(),
            "warm": self.warm.stats() if self.warm is not None else None,
            "journal": (
                self.journal.stats() if self.journal is not None else None
            ),
            # HA operator surface (docs/SERVING.md "High availability"):
            # role, fencing epoch, shipping lag / standby application
            # state — readable without touching logs.
            "replication": self._replication_stats(),
        }

    # ---------------------------------------------------- high availability

    def _not_primary_reply(self) -> dict | None:
        """The structured standby refusal for job-plane commands, naming
        the primary so roster clients redirect transparently — or None
        when this daemon IS the primary."""
        with self._lock:
            if self.role == "primary":
                return None
            primary = self._primary_hint
        if self.receiver is not None:
            # Ship traffic carries the primary's advertised address —
            # fresher than any static seed after a chain of failovers.
            primary = self.receiver.primary() or primary
        reply = structured_error(
            "not_primary",
            f"this daemon is a standby; submit to the primary"
            + (f" at {primary}" if primary else ""),
        )
        if primary:
            reply["primary"] = primary
        return reply

    def _cmd_promote(self) -> dict:
        """Operator-driven takeover.  Refused on a daemon that is
        already primary (the double-promotion guard): promoting twice —
        or promoting the live primary by mistake — must be a loud no,
        not a silent epoch bump that fences a healthy peer."""
        with self._lock:
            already = self.role == "primary"
            epoch = self.epoch
        if already:
            return structured_error(
                "bad_spec",
                f"promote refused: this daemon is already the primary "
                f"(epoch {epoch})",
            )
        self._promote(reason="command")
        with self._lock:
            return {"status": "ok", "role": self.role, "epoch": self.epoch}

    def _cmd_ship(self, cmd: str, req: dict) -> dict:
        """Route one replication frame (docs/SERVING.md): fence first,
        then apply.  A primary receiving a VALID (>= epoch) ship has
        been superseded — it demotes and applies, the split-brain
        resolution arm of the fencing protocol."""
        if self.receiver is None:
            return structured_error(
                "bad_spec",
                "this daemon has no journal; start it with --journal-dir "
                "to receive replication",
            )
        incoming = int(req.get(protocol.EPOCH_KEY) or 0)
        with self._lock:
            epoch = self.epoch
            role = self.role
            self._seen_epoch = max(self._seen_epoch, incoming)
        if incoming < epoch:
            # The zombie-primary fence: an old epoch's ship is rejected
            # structured, and the reply names US as the address to
            # follow — the zombie demotes instead of split-braining.
            return replicate.stale_reply(
                epoch, f"{self.addr[0]}:{self.addr[1]}"
                if role == "primary" else self._primary_hint,
            )
        if role == "primary":
            if incoming > epoch:
                # A genuinely newer primary: we are the zombie.
                self._demote(incoming, req.get("from"))
            else:
                # EQUAL epochs: two daemons both believe they are
                # primary (a misconfigured ring, or a partition healing
                # before any promotion).  Deterministic tie-break — the
                # lexicographically smaller advertised address keeps
                # primaryship — so exactly ONE side demotes; without it
                # a mutual first-ship race demotes both and the pair
                # deadlocks with no primary at all.
                mine = f"{self.addr[0]}:{self.addr[1]}"
                sender = str(req.get("from") or "")
                if sender and sender < mine:
                    self._demote(incoming, sender)
                else:
                    return replicate.stale_reply(epoch, mine)
        # Apply under the promotion lock: a promote() that lands while
        # this frame is in flight bumps the epoch first, so re-checking
        # here keeps a just-promoted daemon from applying a stale ship
        # concurrently with its own replay.
        with self._promote_lock:
            with self._lock:
                if incoming < self.epoch:
                    return replicate.stale_reply(
                        self.epoch, f"{self.addr[0]}:{self.addr[1]}"
                        if self.role == "primary" else self._primary_hint,
                    )
            if cmd == "ship":
                return self.receiver.handle_ship(req)
            if cmd == "ship_catchup":
                return self.receiver.handle_catchup(req)
            return self.receiver.handle_spill(req)

    def _promote(self, reason: str) -> None:
        """Fenced takeover: bump + persist the epoch past everything
        ever observed, become primary, then replay the replicated
        journal exactly like PR 9's restart path — unfinished jobs
        re-enqueue under their ORIGINAL ids and recompute
        byte-identically.  Serialized against demotion and concurrent
        promotes; ship frames arriving after the flip carry the old
        epoch and bounce off the fence."""
        with self._promote_lock:
            with self._lock:
                if self.role == "primary":
                    return
                self.epoch = max(self.epoch, self._seen_epoch) + 1
                self._seen_epoch = self.epoch
                self.role = "primary"
                self._fenced_by = None
                epoch = self.epoch
            replicate.store_epoch(self.cfg.journal_dir, epoch)
            obs.event("serve.takeover", role="primary", epoch=epoch,
                      reason=reason)
            logger.warning(
                "serve daemon promoted to PRIMARY (epoch %d, %s); "
                "replaying the replicated journal", epoch, reason,
            )
            self._replay_journal()
            if self.cfg.ship_to and self.shipper is None:
                # Symmetric pair: a promoted standby configured with
                # --ship-to starts replicating BACK, so the demoted old
                # primary becomes the new hot standby (ring failover).
                self._start_shipper()

    def _demote(self, higher_epoch: int, primary=None) -> None:
        """A newer primary exists (our ship or worker RPC was fenced, or
        a valid higher-epoch ship arrived): stop acting as primary.
        Queued jobs fail structured ``not_primary`` — the new primary
        replays them from the replicated WAL under their original ids,
        so the structured answer is a redirect, not a loss."""
        with self._promote_lock:
            with self._lock:
                if self.role == "standby":
                    self._seen_epoch = max(
                        self._seen_epoch, int(higher_epoch)
                    )
                    if primary:
                        self._primary_hint = str(primary)
                    return
                self.role = "standby"
                self._seen_epoch = max(self._seen_epoch, int(higher_epoch))
                self._fenced_by = int(higher_epoch)
                if primary:
                    self._primary_hint = str(primary)
                elif self.cfg.ship_to:
                    self._primary_hint = self.cfg.ship_to
                hint = self._primary_hint
            if self.receiver is not None:
                self.receiver.touch()  # fresh lease: don't instantly re-promote
            obs.event("serve.takeover", role="standby",
                      epoch=int(higher_epoch), reason="fenced")
            logger.warning(
                "serve daemon FENCED by epoch %d (primary %s): demoting "
                "to standby", higher_epoch, hint or "unknown",
            )
            shipper = self.shipper
            if shipper is not None:
                self.journal.on_append = None
                self.shipper = None
                shipper.stop()
            stranded = self.scheduler.drain()
            if stranded:
                with self._lock:
                    for job in stranded:
                        self._corpus_pop(job.job_id)
                self._fail_batch(stranded, structured_error(
                    "not_primary",
                    "this daemon was demoted to standby mid-queue; the "
                    "new primary replays this job from the replicated "
                    "journal under the same id"
                    + (f" (primary {hint})" if hint else ""),
                ))

    def _maybe_lease_promote(self) -> None:
        """Standby lease expiry -> automatic takeover.  Runs on the
        dispatcher's idle tick; the explicit `promote` command is the
        other trigger."""
        if self.cfg.lease_s is None or self.receiver is None:
            return
        with self._lock:
            if self.role == "primary":
                return
        age = self.receiver.contact_age_s()
        if age is not None and age >= self.cfg.lease_s:
            logger.warning(
                "primary lease expired (%.1fs > %.1fs without contact)",
                age, self.cfg.lease_s,
            )
            self._promote(reason="lease")

    def _replication_stats(self) -> dict:
        with self._lock:
            out = {
                "role": self.role,
                "epoch": self.epoch,
                "seen_epoch": self._seen_epoch,
                "fenced_by": self._fenced_by,
                "primary_hint": self._primary_hint,
                "lease_s": self.cfg.lease_s,
            }
        shipper = self.shipper  # snapshot: _demote may null it mid-call
        if shipper is not None:
            out["ship"] = shipper.stats()
        if self.receiver is not None:
            out["standby"] = self.receiver.stats()
        return out

    # ----------------------------------------------------------- dispatch

    def _batch_key(self, job: Job):
        # bisect_group keeps the halves of a failed batch from
        # re-coalescing (jobs.Job.bisect_group): None for never-failed
        # jobs, so the common path batches exactly as before.  The
        # engine_key half already folds the PLAN fingerprint in for plan
        # jobs (cache.ExecutableCache.engine_key), so two different
        # pipelines can never coalesce.
        key = (
            self.executables.engine_key(job.spec), job.bucket,
            job.bisect_group,
        )
        if job.spec.plan is not None:
            # Plan jobs dispatch solo: a compiled plan runs one corpus
            # end-to-end (no vmapped job axis), so nothing may coalesce
            # with it — same stance as shard-eligible jobs.
            return key + (("solo", job.job_id),)
        if self.pool is not None and self._shardable(job):
            # Shard-eligible jobs dispatch solo: the fan-out owns the
            # whole batch, so nothing may coalesce with it.
            return key + (("solo", job.job_id),)
        # Cache affinity deliberately does NOT ride the key: the warm
        # set is itself keyed by (engine_key, bucket) — components
        # already in the key — so appending it could never change which
        # jobs coalesce; placement happens per-BATCH in pool.place(),
        # where the affinity decision actually lives.
        return key

    def _affinity_key(self, job: Job) -> tuple:
        return (self.executables.engine_key(job.spec), job.bucket)

    def _plan_affinity_key(self, job: Job, shape) -> tuple:
        """Pool-affinity key for a DISTRIBUTED plan job: the shape's
        primary node closure fingerprint in the workers' fold_node_key
        spelling (cache.ExecutableCache), so placement prefers workers
        already holding the compiled stage executable — alpha-renamed
        resubmits included — and a restarted daemon re-learns those
        homes from seed_affinity's warm_shapes rows."""
        from locust_tpu.plan import distribute
        from locust_tpu.serve.jobs import PLAN_WORKLOAD

        if isinstance(shape, distribute.JoinShape):
            fp = shape.leaves[0].node_fp
        else:
            fp = shape.node_fp
        return ((PLAN_WORKLOAD, f"node:{fp}"), job.bucket)

    def _shardable(self, job: Job) -> bool:
        # Plan jobs take their OWN distribution path (_plan_distributable
        # -> _dispatch_plan_distributed): the worker serve surface here
        # speaks (workload, config) batches, not plan stages.
        return (
            self.pool is not None
            and job.spec.plan is None
            and self.cfg.shard_max >= 2
            and job.n_blocks >= self.cfg.shard_min_blocks
        )

    def _plan_shape(self, job: Job):
        """(shape, reason) for a plan job: the distributable shape —
        fold spine, join tree, or pagerank iterate — or None with the
        reason it stays solo (plan/distribute.py, docs/PLAN.md
        "Distributed execution")."""
        if job.spec.plan is None:
            return None, "not_a_plan"
        try:
            from locust_tpu.plan import distribute, from_json

            return distribute.plan_shape(from_json(job.spec.plan))
        except Exception as e:  # noqa: BLE001 - unrecognized plan = solo
            logger.debug(
                "plan job %s not distributable (%s: %s); solo engine",
                job.job_id, type(e).__name__, e,
            )
            return None, f"shape_error:{type(e).__name__}"

    def _count_plan_solo(self, reason: str) -> None:
        """A pool-eligible plan job fell back to the solo engine: count
        it (stats pool.plan ``plan_solo_fallbacks`` + the closed obs
        registry) and log once per distinct reason — the fused_demoted
        stance: an operator watching a 2-worker pool buy nothing for
        their pipeline finds out WHY, not never."""
        with self._lock:
            self._plan_counters["plan_solo_fallbacks"] += 1
            first = reason not in self._plan_solo_logged
            self._plan_solo_logged.add(reason)
        obs.metric_inc("plan.solo_fallbacks")
        if first:
            logger.warning(
                "plan job demoted to the solo engine (%s); further "
                "demotions for this reason are counted, not logged "
                "(stats pool.plan plan_solo_fallbacks)", reason,
            )

    def _plan_distributable(self, job: Job) -> bool:
        """Large plan jobs whose DAG matches a covered shape fan their
        stages across the pool; everything else keeps the solo engine —
        the floor, and the byte-identity anchor the distributed path is
        measured against (docs/PLAN.md "Distributed execution").  A
        pool-eligible job that fails ONLY the shape check is a counted,
        logged demotion (never silent)."""
        if (
            self.pool is None
            or job.spec.plan is None
            or self.cfg.shard_max < 2
            or job.n_blocks < self.cfg.shard_min_blocks
        ):
            return False
        shape, reason = self._plan_shape(job)
        if shape is None:
            self._count_plan_solo(reason or "unrecognized_shape")
            return False
        return True

    def _dispatch_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self._dispatch_once()
            except Exception:  # noqa: BLE001 - the dispatcher must survive
                logger.exception("serve dispatch iteration failed")

    def _sweep_deadlines(self) -> None:
        """Expire queued/retrying jobs whose deadline passed — the
        structured ``deadline_exceeded`` answer must not wait for a
        dispatch slot the job will never productively use."""
        expired = self.scheduler.expire(time.monotonic())
        if not expired:
            return
        with self._lock:
            for j in expired:
                self._corpus_pop(j.job_id)
        self._fail_jobs([
            (j, structured_error(
                "deadline_exceeded",
                f"deadline of {j.spec.deadline_s}s expired while "
                f"{j.state} (attempt {j.attempts}/{j.spec.max_attempts})",
            ))
            for j in expired
        ])

    def _dispatch_once(self) -> None:
        self._maybe_lease_promote()
        self._sweep_deadlines()
        # Only an occupied queue is worth a queue-wait span: an idle
        # daemon's poll ticks would bury the timeline in no-op spans.
        cm = (
            obs.span("serve.queue_wait")
            if self.scheduler.depth()
            else contextlib.nullcontext()
        )
        # One batch per free placement slot, plus the local floor:
        # independent same-tick batches overlap across the pool instead
        # of serializing on one engine (the scale-out tentpole).  With
        # no pool this is exactly the old single-batch pop.
        limit = 1 + (self.pool.free_slots() if self.pool is not None else 0)
        with cm:
            batches = self.scheduler.next_batches(
                self._batch_key, max_batches=limit,
                timeout=self.cfg.dispatch_poll_s,
            )
        if not batches:
            return
        local: list[tuple[list[Job], dict]] = []
        for jobs in batches:
            jobs, corpora = self._pop_batch_corpora(jobs)
            if not jobs:
                continue
            # Chaos: the dispatch boundary (docs/FAULTS.md).  "crash"
            # models the dispatch dying mid-flight, "error" an
            # engine-side failure: either way the batch enters the
            # retry/bisection ladder — every TERMINAL failure is a
            # STRUCTURED error (never a silent wrong answer) and the
            # daemon lives on.  When no batch-level rule matches, one
            # sub-fire per job carries job=<id> so a plan can target ONE
            # poison job (the bisection tests ride this).
            rule = faultplan.fire("serve.dispatch", jobs=len(jobs))
            if rule is None:
                for j in jobs:
                    rule = faultplan.fire(
                        "serve.dispatch", jobs=len(jobs), job=j.job_id
                    )
                    if rule is not None:
                        break
            if rule is not None:
                if rule.action == "delay":
                    time.sleep(rule.delay_s)
                else:
                    self._retry_or_fail(
                        jobs, corpora,
                        f"[faultplan] injected dispatch {rule.action}",
                    )
                    continue
            if len(jobs) == 1 and self._shardable(jobs[0]):
                # On the dedicated coordinator executor: the coordinator
                # blocks (bounded) on its shard futures and must not
                # park the dispatcher; the shard RPCs themselves overlap
                # on the pool executor.
                try:
                    self._shard_executor.submit(
                        self._dispatch_sharded, jobs[0], corpora
                    )
                except RuntimeError:  # executor shut down under us
                    self._fail_batch(jobs, structured_error(
                        "shutting_down",
                        "daemon shut down before this job was "
                        "dispatched; resubmit after it returns",
                    ))
                continue
            if len(jobs) == 1 and self._plan_distributable(jobs[0]):
                # Same coordinator stance as sharding: the plan
                # coordinator blocks (bounded) on its stage futures and
                # must not park the dispatcher.
                try:
                    self._shard_executor.submit(
                        self._dispatch_plan_distributed, jobs[0], corpora
                    )
                except RuntimeError:  # executor shut down under us
                    self._fail_batch(jobs, structured_error(
                        "shutting_down",
                        "daemon shut down before this job was "
                        "dispatched; resubmit after it returns",
                    ))
                continue
            worker = (
                self.pool.place(self._affinity_key(jobs[0]))
                if self.pool is not None and jobs[0].spec.plan is None
                else None
            )
            if worker is not None:
                try:
                    self.pool.submit(
                        self._dispatch_remote, worker, jobs, corpora
                    )
                except RuntimeError:  # pool closed between place/submit
                    self.pool.release(worker)
                    local.append((jobs, corpora))
            else:
                local.append((jobs, corpora))
        for jobs, corpora in local:
            self._dispatch_local(jobs, corpora)
        self._maybe_mark_warm()
        if self.journal is not None and self.journal.compact_due():
            self._compact_journal()

    def _pop_batch_corpora(
        self, jobs: list[Job]
    ) -> tuple[list[Job], dict]:
        """Flip a popped batch to running and collect its buffered
        corpora; jobs whose bytes vanished fail structured."""
        now = time.monotonic()
        with self._lock:
            corpora: dict = {}
            lost = []
            for j in jobs:
                j.state = "running"
                j.started_s = now
                j.batch_size = len(jobs)
                # None = the entry is MISSING (an empty submit stores
                # b"").  A silent b"" default here would fold an all-zero
                # stack and hand the client an empty "done" — the silent
                # wrong answer this tier forbids — so a lost entry fails
                # the job structurally instead.
                data = self._corpus_pop(j.job_id)
                if data is None and j.corpus_digest not in corpora:
                    lost.append(j)
                else:
                    if data is not None:
                        corpora[j.corpus_digest] = data
        if lost:
            self._fail_batch(lost, structured_error(
                "dispatch_failed",
                "in-flight corpus bytes missing at dispatch (daemon "
                "bug) — resubmit",
            ))
            jobs = [j for j in jobs if j not in lost]
        return jobs, corpora

    def _dispatch_local(self, jobs: list[Job], corpora: dict) -> None:
        """One batch on the daemon's own engine — the pre-pool path and
        the pool's permanent floor."""
        if jobs[0].spec.plan is not None:
            return self._dispatch_plan(jobs[0], corpora)
        spec = jobs[0].spec
        njobs_padded = batching.bucket_blocks(len(jobs))
        bucket = jobs[0].bucket
        for j in jobs:
            j.placed_on = "local"
        try:
            # One accelerator (the worker daemon's _map_lock stance):
            # the whole device region — compile-or-build, the fold, and
            # the demux device->host transfers — holds the engine lock,
            # so the dispatcher and the shard coordinators' local
            # fallback/merge paths never overlap device work.
            with self._engine_lock:
                with obs.span(
                    "serve.compile_or_hit",
                    jobs=len(jobs), bucket=bucket,
                ):
                    engine, hit = self.executables.lookup(
                        spec, njobs_padded, bucket
                    )
                # Literal names per branch: the R009 convention — the
                # analyzer (and registry) must see every emission site.
                if hit:
                    obs.metric_inc("serve.exec_cache_hits")
                else:
                    obs.metric_inc("serve.exec_cache_misses")
                with obs.span(
                    "serve.dispatch", jobs=len(jobs), bucket=bucket
                ):
                    results = batching.dispatch_batch(
                        engine, jobs, corpora
                    )
                self.executables.mark_compiled(spec, njobs_padded, bucket)
                # Demux stays INSIDE the failure boundary:
                # to_host_pairs() is the device->host transfer and can
                # raise (the flapping TPU tunnel is the documented
                # case) — an escape here would leave jobs "running"
                # forever, a hang where the tier promises a structured
                # error.  _fail_batch skips the jobs already marked
                # done, so a mid-demux failure keeps the finished
                # results and fails only the rest.
                with obs.span("serve.demux", jobs=len(jobs)):
                    done = time.monotonic()
                    for job, res in zip(jobs, results):
                        pairs = res.to_host_pairs()
                        self._finish_job(
                            job, pairs, res.num_segments, res.truncated,
                            res.overflow_tokens,
                            "warm" if hit else "cold", done,
                        )
        except Exception as e:  # noqa: BLE001 - jobs retry/fail, daemon survives
            logger.exception("serve dispatch failed")
            self._retry_or_fail(jobs, corpora, f"{type(e).__name__}: {e}")

    def _dispatch_plan(self, job: Job, corpora: dict) -> None:
        """One plan job on the daemon's own engine (docs/PLAN.md).

        The warm-executable cache holds the COMPILED PLAN keyed by
        (plan fingerprint, config fingerprint, shape bucket) — a repeat
        of the same pipeline skips lowering and reuses the underlying
        engine's jit caches, the exact warm-hit economics named
        workloads get.  The result is the sink-rendered output bytes as
        ONE (bytes, 0) pair, so the result cache, warm persistence,
        history byte caps and journal replay all carry it unchanged;
        failures feed the same retry ladder as every other dispatch.
        """
        spec = job.spec
        try:
            with self._engine_lock:
                with obs.span(
                    "serve.compile_or_hit", jobs=1, bucket=job.bucket,
                ):
                    executor, hit = self.executables.lookup(
                        spec, 1, job.bucket
                    )
                if hit:
                    obs.metric_inc("serve.exec_cache_hits")
                else:
                    obs.metric_inc("serve.exec_cache_misses")
                job.placed_on = "local"
                with obs.span(
                    "serve.dispatch", jobs=1, bucket=job.bucket,
                ):
                    pres = executor.run_corpus(
                        corpora[job.corpus_digest],
                        sub_cache=self.subplans,
                        corpus_sha=job.corpus_digest,
                    )
                self.executables.mark_compiled(spec, 1, job.bucket)
                with obs.span("serve.demux", jobs=1):
                    self._finish_job(
                        job, [(pres.output, 0)], pres.distinct,
                        pres.truncated, pres.overflow_tokens,
                        "warm" if hit else "cold", time.monotonic(),
                    )
        except PlanError as e:
            # DETERMINISTIC rejection (e.g. a pagerank plan over a
            # corpus that does not parse as an edge list): retrying
            # would burn the whole backoff ladder on the same answer
            # and quarantine a well-formed submit as a misleading
            # poison_job — fail structured immediately instead, the
            # same bad_spec discipline admission applies.
            self._fail_batch([job], structured_error(
                "bad_spec",
                f"plan execution rejected the corpus: {e}",
            ))
        except Exception as e:  # noqa: BLE001 - retry ladder absorbs it
            logger.exception("serve plan dispatch failed")
            self._retry_or_fail(
                [job], corpora, f"plan: {type(e).__name__}: {e}"
            )

    def _dispatch_remote(
        self, worker, jobs: list[Job], corpora: dict
    ) -> None:
        """One batch on one pool worker (runs on the pool executor).

        Any failure — the worker dying mid-batch, a structured worker
        error, an injected fault — feeds the jobs back through the SAME
        retry/bisection ladder as a local failure: the pool quarantines
        the worker (WorkerHealth backoff) and the retry lands on a
        survivor or the local floor, so a worker death costs latency,
        never an answer.
        """
        try:
            try:
                # Worker-scoped chaos fire: a plan matching worker=<name>
                # models THIS worker dying mid-serve-batch.
                rule = faultplan.fire(
                    "serve.dispatch", jobs=len(jobs), worker=worker.name
                )
                if rule is not None:
                    if rule.action == "delay":
                        time.sleep(rule.delay_s)
                    else:
                        raise PoolDispatchError(
                            f"[faultplan] injected dispatch {rule.action} "
                            f"on worker {worker.name}"
                        )
                bucket = jobs[0].bucket
                for j in jobs:
                    j.placed_on = worker.name
                req_jobs = [
                    {"job_id": j.job_id, "sha": j.corpus_digest,
                     "n_lines": j.n_lines}
                    for j in jobs
                ]
                with obs.span(
                    "serve.dispatch",
                    jobs=len(jobs), bucket=bucket, worker=worker.name,
                ):
                    reply = self.pool.dispatch(
                        worker, jobs[0].spec.workload,
                        jobs[0].config_overrides or {}, bucket,
                        req_jobs, corpora,
                    )
                self.pool.mark_warm(worker, self._affinity_key(jobs[0]))
                hit = bool(reply.get("warm"))
                results = reply["results"]
                with obs.span("serve.demux", jobs=len(jobs)):
                    done = time.monotonic()
                    for job, res in zip(jobs, results):
                        pairs = [
                            (base64.b64decode(k), int(v))
                            for k, v in res["pairs"]
                        ]
                        self._finish_job(
                            job, pairs, int(res["distinct"]),
                            bool(res["truncated"]),
                            int(res["overflow_tokens"]),
                            "warm" if hit else "cold", done,
                        )
            except Exception as e:  # noqa: BLE001 - retry ladder absorbs it
                logger.warning(
                    "serve pool dispatch on %s failed: %s: %s",
                    worker.name, type(e).__name__, e,
                )
                if getattr(e, "code", None) == "stale_epoch":
                    # The worker has served a NEWER primary: we are the
                    # fenced-out zombie.  Demote with the worker's OWN
                    # high-water epoch when it sent one — the new
                    # primary replays these jobs from the replicated
                    # WAL; the retry ladder below still answers them
                    # structured here.
                    worker_epoch = getattr(e, "epoch", None)
                    with self._lock:
                        fence = max(
                            self._seen_epoch, self.epoch + 1,
                            int(worker_epoch or 0),
                        )
                    self._demote(fence)
                self._retry_or_fail(
                    jobs, corpora,
                    f"pool worker {worker.name}: {type(e).__name__}: {e}",
                )
        finally:
            self.pool.release(worker)
        self._maybe_mark_warm()

    def _dispatch_sharded(self, job: Job, corpora: dict) -> None:
        """Fan one large job across the pool and merge through the
        engine's combine (docs/SERVING.md "Scale-out dispatch").

        The corpus moves ONCE through the content-addressed spill; each
        worker folds a contiguous block-aligned line range and the
        partial tables merge with the same sort+segment-reduce the
        hierarchical mesh trusts — byte-identical to the local fold in
        the non-truncated regime.  Fewer than 2 placeable workers (or
        any shard failing) degrades to the local floor / retry ladder.
        """
        from locust_tpu.serve import pool as pool_mod

        cfg = job.spec.cfg
        corpus = corpora.get(job.corpus_digest, b"")
        ranges = pool_mod.shard_ranges(
            job.n_lines, cfg.block_lines, self.cfg.shard_max
        )
        placements = []
        submitted: list = []
        used: set[int] = set()
        try:
            if len(ranges) >= 2:
                shard_blocks = -(-(ranges[0][1] - ranges[0][0])
                                 // cfg.block_lines)
                akey = (
                    self.executables.engine_key(job.spec),
                    batching.bucket_blocks(shard_blocks),
                )
                for _ in ranges:
                    w = self.pool.place(akey, exclude=used)
                    if w is None:
                        break
                    used.add(w.idx)
                    placements.append(w)
            if len(placements) < 2:
                for w in placements:
                    self.pool.release(w)
                placements = []
                self._dispatch_local([job], corpora)
                return
            if len(placements) < len(ranges):
                ranges = pool_mod.shard_ranges(
                    job.n_lines, cfg.block_lines, len(placements)
                )
                for w in placements[len(ranges):]:
                    self.pool.release(w)
                placements = placements[: len(ranges)]
            job.shards = len(ranges)
            job.placed_on = "shard:" + ",".join(
                w.name for w in placements
            )
            self.pool.spill(job.corpus_digest, corpus)
            futs = []
            for (a, b), w in zip(ranges, placements):
                fut = self.pool.submit(self._run_shard_rpc, w, job, a, b)
                # The slot release rides the FUTURE, not the
                # coordinator: on a wait timeout the RPC is still
                # holding the worker's dispatch lane, and an early
                # release would let place() queue a second batch behind
                # the stuck connection.
                fut.add_done_callback(
                    lambda _f, _w=w: self.pool.release(_w)
                )
                submitted.append(w)
                futs.append(fut)
            done_f, not_done = concurrent.futures.wait(
                futs, timeout=self.cfg.pool_rpc_timeout + 30.0
            )
            if not_done:
                raise PoolDispatchError(
                    f"{len(not_done)} shard dispatch(es) still inflight "
                    f"after {self.cfg.pool_rpc_timeout + 30.0:.0f}s"
                )
            shard_results = [f.result(timeout=1.0) for f in futs]
            combine = WORKLOADS[job.spec.workload][1]
            # The merge is device work on the coordinator thread: it
            # serializes with every other local device touch.
            with self._engine_lock:
                pairs, distinct, truncated, overflow = (
                    batching.merge_shard_results(
                        shard_results, cfg, combine
                    )
                )
            self._finish_job(
                job, pairs, distinct, truncated, overflow, "shard",
                time.monotonic(),
            )
        except Exception as e:  # noqa: BLE001 - retry ladder absorbs it
            logger.warning(
                "sharded dispatch of %s failed: %s: %s",
                job.job_id, type(e).__name__, e,
            )
            self._retry_or_fail(
                [job], corpora,
                f"sharded dispatch: {type(e).__name__}: {e}",
            )
        finally:
            # Only reservations that never became a shard RPC release
            # here — submitted ones release via their future's callback
            # (which runs even when the coordinator timed out on them).
            for w in placements:
                if w not in submitted:
                    self.pool.release(w)

    def _run_shard_rpc(self, worker, job: Job, a: int, b: int) -> dict:
        """One shard of a fanned-out job on one worker (pool executor).
        Returns the decoded shard table; raises on any failure — the
        coordinator fails the whole job into the retry ladder."""
        from locust_tpu.serve import pool as pool_mod

        cfg = job.spec.cfg
        shard_id = pool_mod.stable_shard_id(job.job_id, a, b)
        sbucket = batching.bucket_blocks(-(-(b - a) // cfg.block_lines))
        rule = faultplan.fire(
            "serve.dispatch", jobs=1, worker=worker.name, job=shard_id
        )
        if rule is not None:
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            else:
                raise PoolDispatchError(
                    f"[faultplan] injected shard {rule.action} on "
                    f"worker {worker.name}"
                )
        with obs.span(
            "serve.dispatch", jobs=1, bucket=sbucket, worker=worker.name,
        ):
            reply = self.pool.dispatch(
                worker, job.spec.workload, job.config_overrides or {},
                sbucket,
                [{"job_id": shard_id, "sha": job.corpus_digest,
                  "n_lines": b - a, "line_start": a, "line_end": b}],
                {},  # corpus already spilled by the coordinator
            )
        self.pool.mark_warm(
            worker, (self.executables.engine_key(job.spec), sbucket)
        )
        res = reply["results"][0]
        return {
            "pairs": [
                (base64.b64decode(k), int(v)) for k, v in res["pairs"]
            ],
            "distinct": int(res["distinct"]),
            "truncated": bool(res["truncated"]),
            "overflow_tokens": int(res["overflow_tokens"]),
        }

    def _run_plan_stage_rpc(self, worker, req: dict, phase: str) -> dict:
        """One plan stage RPC on one worker (pool executor).  Raises
        ``PoolDispatchError`` on ANY failure — transport death, a
        structured worker answer (carrying code/epoch/lost_split), an
        injected fault — the coordinator's wave runner owns recovery."""
        # Worker-scoped chaos fire (the serve.dispatch shard mold):
        # models THIS stage RPC dying in flight, coordinator side.
        rule = faultplan.fire(
            "plan.stage", phase=phase, worker=worker.name,
            split=req.get("split"), part=req.get("part"),
        )
        if rule is not None:
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            else:
                raise PoolDispatchError(
                    f"[faultplan] injected plan stage {rule.action} on "
                    f"worker {worker.name}"
                )
        with obs.span(
            "plan.stage", phase=phase, worker=worker.name,
            split=req.get("split"), part=req.get("part"),
        ):
            return self.pool.stage_rpc(worker, req)

    def _dispatch_plan_distributed(self, job: Job, corpora: dict) -> None:
        """Fan one covered-shape plan across the pool as stage programs
        (docs/PLAN.md "Distributed execution").

        Fold spines (StageShape) — map wave: each contiguous
        block-aligned source split folds on a worker's warm fold-node
        executables (cache.fold_node_key: a repeat plan skips the
        per-worker recompile) and publishes its shuffle partitions
        atomically into the content-addressed spill.  Reduce wave: each
        partition's inputs move worker-to-worker over the binary data
        plane and combine on the reducing worker.  Finalize folds the
        reduced partitions into the solo renderer's EXACT bytes on the
        daemon — byte-identity to the solo engine is the contract.

        Join trees (JoinShape) run the SAME map wave once (every leaf
        is the one corpus wordcount fold) and then a join wave: each
        co-partitioned bin merges its inputs and evaluates the WHOLE
        tree locally, however deep — chained per-worker stage programs,
        no master round-trip between joins.  Two explicit identity
        gates demote to solo (counted, logged): any truncated/overflow
        map split, or total distinct past the solo fold's table
        capacity — outside both, the solo leaves are provably exact and
        the host merge reproduces them bit-for-bit.

        Pagerank (IterateShape) runs as epoch-synchronized sweeps: each
        worker owns a contiguous rank shard, computes one bit-exact
        ``pagerank_step`` per epoch over its dst-restricted edge subset
        and publishes its slice; the next epoch's stages reconstruct
        the full vector from ALL shards' partitions (the one shuffle
        per iteration).  Completed epochs journal as WAL stage records,
        so a SIGKILL mid-iteration resumes from the last fully-intact
        epoch's partitions; a lost shard partition recomputes exactly
        that (epoch, shard) stage.

        Robustness is STAGE-granular: a failed/dead worker's stage
        recomputes on a survivor from its durable inputs (never a
        full-plan restart; a reduce that lost a partition names the
        ``lost_split`` and exactly that map split recomputes),
        stragglers past ``plan_speculate_s`` get one speculative backup
        (first finisher wins — attempt-keyed filenames cannot collide),
        completed map splits journal as stage-progress records so a
        daemon restart reuses surviving partitions, and every stage RPC
        carries the fencing epoch so a zombie coordinator's publishes
        die structured ``stale_epoch``.  Fewer than 2 placeable workers
        (or any unrecognized shape upstream) = the solo floor.
        """
        from locust_tpu.plan import distribute
        from locust_tpu.plan.compile import (
            SERVE_MAX_PAGERANK_NODES, edges_from_bytes,
        )
        from locust_tpu.serve import pool as pool_mod

        shape, shape_reason = self._plan_shape(job)
        cfg = job.spec.cfg
        corpus = corpora.get(job.corpus_digest, b"")
        plan_fp = job.spec.plan_fingerprint()
        placements: list = []
        used: set[int] = set()
        part_files: set[str] = set()
        try:
            if shape is None:
                raise _PlanSolo(shape_reason or "unrecognized_shape")
            is_iter = isinstance(shape, distribute.IterateShape)
            is_join = isinstance(shape, distribute.JoinShape)
            ranges: list = []
            num_nodes = 0
            if is_iter:
                if shape.num_iters < 1:
                    # Zero sweeps = ranks0; no epoch partitions would
                    # exist to finalize from — the solo scan owns it.
                    raise _PlanSolo("iterate_no_epochs")
                # The edge list names the dense node space (PlanError
                # here = the same bad_spec the solo evaluator answers).
                src, dst = edges_from_bytes(corpus)
                num_nodes = int(max(int(src.max()), int(dst.max()))) + 1
                if num_nodes > SERVE_MAX_PAGERANK_NODES:
                    # The solo path raises the canonical bad_spec text.
                    raise _PlanSolo("pagerank_node_cap")
                n_tasks = min(self.cfg.shard_max, num_nodes)
            else:
                ranges = pool_mod.shard_ranges(
                    job.n_lines, cfg.block_lines, self.cfg.shard_max
                )
                n_tasks = len(ranges)
            akey = self._plan_affinity_key(job, shape)
            if n_tasks >= 2:
                for _ in range(n_tasks):
                    w = self.pool.place(akey, exclude=used)
                    if w is None:
                        break
                    used.add(w.idx)
                    placements.append(w)
            if len(placements) < 2:
                raise _PlanSolo("insufficient_workers")
            if not is_iter and len(placements) < len(ranges):
                # Same reconciliation as sharding: re-derive the splits
                # for the workers we actually hold — never drop lines.
                ranges = pool_mod.shard_ranges(
                    job.n_lines, cfg.block_lines, len(placements)
                )
                for w in placements[len(ranges):]:
                    self.pool.release(w)
                placements = placements[: len(ranges)]
            n_splits = len(ranges)
            n_parts = len(placements)
            job.shards = n_parts if is_iter else n_splits
            job.placed_on = "plan:" + ",".join(w.name for w in placements)
            self.pool.spill(job.corpus_digest, corpus)
            dead: set[int] = set()
            rr = 0

            def next_worker():
                nonlocal rr
                for _ in range(len(placements)):
                    w = placements[rr % len(placements)]
                    rr += 1
                    if w.idx not in dead:
                        return w
                return None

            # WAL-replayed stage progress (map split or iterate epoch
            # records — they self-discriminate by key): popped once, the
            # shape branch below decides what resumes.
            with self._lock:
                progress = self._plan_progress.pop(job.job_id, [])

            def run_wave(phase, task_ids, build_req, repair=None,
                         on_win=None):
                """One wave of stage RPCs: per-task retry (capped),
                straggler speculation (first finisher wins), rotation
                over the surviving held placements."""
                pending: dict = {}
                won: dict[int, dict] = {}
                attempts = {t: 0 for t in task_ids}
                started: dict[int, float] = {}
                speculated: set[int] = set()
                deadline = (
                    time.monotonic() + self.cfg.pool_rpc_timeout + 30.0
                )

                def launch(task):
                    w = next_worker()
                    if w is None:
                        raise PoolDispatchError(
                            "no surviving plan-stage workers"
                        )
                    fut = self.pool.submit(
                        self._run_plan_stage_rpc, w,
                        build_req(task, attempts[task]), phase,
                    )
                    attempts[task] += 1
                    started[task] = time.monotonic()
                    pending[fut] = (task, w)

                for t in task_ids:
                    launch(t)
                while len(won) < len(task_ids):
                    if time.monotonic() > deadline:
                        raise PoolDispatchError(
                            f"plan {phase} wave still inflight after "
                            f"{self.cfg.pool_rpc_timeout + 30.0:.0f}s"
                        )
                    done_f, _ = concurrent.futures.wait(
                        list(pending), timeout=0.25,
                        return_when=concurrent.futures.FIRST_COMPLETED,
                    )
                    for fut in done_f:
                        task, w = pending.pop(fut)
                        try:
                            reply = fut.result(timeout=1.0)
                        except Exception as e:  # noqa: BLE001 - per-task retry
                            if getattr(e, "code", None) == "stale_epoch":
                                raise  # the outer fence handler owns it
                            if task in won:
                                continue  # a speculative loser died
                            if (getattr(e, "lost_split", None) is None
                                    and getattr(e, "lost_epoch", None)
                                    is None):
                                # Transport-level death.  A structured
                                # loss report is the ANSWERING worker
                                # doing its job (a dead peer's partition
                                # is the casualty) — marking it dead too
                                # would strand a 2-worker pool with one
                                # real death on the solo floor.
                                dead.add(w.idx)
                            if attempts[task] >= 3 \
                                    or next_worker() is None:
                                raise
                            with self._lock:
                                self._plan_counters["recomputes"] += 1
                            obs.metric_inc("plan.recomputes")
                            if repair is not None:
                                repair(task, e)
                            launch(task)
                            continue
                        if reply.get("parts"):
                            part_files.update(
                                str(p["path"]) for p in reply["parts"]
                            )
                        ref = reply.get("ref")
                        if isinstance(ref, dict) and ref.get("path"):
                            # Iterate replies publish ONE shard slice —
                            # tracked even for speculative losers so no
                            # epoch partition outlives the job.
                            part_files.add(str(ref["path"]))
                        if task in won:
                            continue  # first finisher already won
                        won[task] = reply
                        with self._lock:
                            self._plan_counters["stages"] += 1
                        if on_win is not None:
                            on_win(task, reply, w)
                    now = time.monotonic()
                    for t in task_ids:
                        if (t in won or t in speculated
                                or now - started[t]
                                <= self.cfg.plan_speculate_s
                                or next_worker() is None):
                            continue
                        speculated.add(t)
                        with self._lock:
                            self._plan_counters["speculated"] += 1
                        obs.metric_inc("plan.speculated")
                        launch(t)
                return won

            if is_iter:
                # ---- pagerank: epoch-synchronized rank-shard sweeps --
                n_shards = n_parts
                epoch_refs: dict[int, dict[int, dict]] = {}

                def journal_epoch(epoch: int, refs: dict) -> None:
                    if self.journal is not None:
                        self.journal.append_stage(job.job_id, {
                            "epoch": epoch,
                            "n_shards": n_shards,
                            "parts": [refs[s] for s in range(n_shards)],
                        })

                # WAL-replayed epoch progress: resume from the HIGHEST
                # fully-intact journaled epoch (every shard slice present
                # with its recorded sha) — a daemon restart re-runs only
                # the sweeps past it, byte-identically (each epoch is a
                # pure function of the previous epoch's partitions).
                best = 0
                best_refs: dict[int, dict] = {}
                for st in progress:
                    try:
                        e_no = int(st.get("epoch", -1))
                        parts = list(st.get("parts") or [])
                        if (e_no <= best or e_no > shape.num_iters
                                or int(st.get("n_shards", -1)) != n_shards
                                or len(parts) != n_shards):
                            continue
                        for ref in parts:
                            with open(str(ref["path"]), "rb") as f:
                                data = f.read()
                            if (hashlib.sha256(data).hexdigest()
                                    != ref["sha256"]):
                                raise ValueError("partition sha drifted")
                        best = e_no
                        best_refs = {
                            int(r["part"]): dict(r) for r in parts
                        }
                    except Exception as e:  # noqa: BLE001 - damaged = recompute
                        logger.warning(
                            "plan resume: damaged epoch record skipped "
                            "(%s: %s); that epoch recomputes",
                            type(e).__name__, e,
                        )
                        continue
                if best:
                    epoch_refs[best] = best_refs
                    part_files.update(
                        str(r["path"]) for r in best_refs.values()
                    )
                    with self._lock:
                        self._plan_counters["partitions_reused"] += (
                            n_shards
                        )

                def inputs_for(epoch: int):
                    """The previous epoch's full partition set (None =
                    the uniform-ranks first sweep).  Read at BUILD time
                    so a mid-wave repair's fresh refs reach relaunched
                    and speculative attempts."""
                    if epoch < 1:
                        return None
                    refs = epoch_refs[epoch]
                    return [dict(refs[s]) for s in range(n_shards)]

                def build_iter_req(epoch: int):
                    def build(shard: int, attempt: int) -> dict:
                        return {
                            "phase": "iterate",
                            "sha": job.corpus_digest,
                            "spill_dir": self.pool.spill_dir,
                            "plan_fp": plan_fp,
                            "epoch": epoch, "shard": shard,
                            "n_shards": n_shards,
                            "num_nodes": num_nodes,
                            "damping": shape.damping,
                            "attempt": attempt,
                            "inputs": inputs_for(epoch - 1),
                            # split/part feed the chaos + obs stage ctx.
                            "split": epoch, "part": shard,
                        }
                    return build

                def repair_iterate(epoch: int):
                    def repair(shard: int, exc) -> None:
                        """A sweep lost one of the PREVIOUS epoch's
                        shard slices: recompute exactly that
                        (epoch-1, shard) stage on a survivor and
                        re-journal — the relaunched sweep reads the
                        fresh ref through inputs_for's closure.  The
                        recompute is deterministic, so the re-journaled
                        epoch is bit-identical to the original."""
                        le = getattr(exc, "lost_epoch", None)
                        ls = getattr(exc, "lost_split", None)
                        if le is None or ls is None:
                            return
                        le, ls = int(le), int(ls)
                        if le != epoch - 1 or le < 1:
                            return
                        w = next_worker()
                        if w is None:
                            raise PoolDispatchError(
                                "no surviving plan-stage workers"
                            )
                        old = epoch_refs[le][ls]
                        att = int(old.get("attempt", 0)) + 1
                        reply = self._run_plan_stage_rpc(
                            w, build_iter_req(le)(ls, att), "iterate"
                        )
                        ref = dict(
                            reply["ref"],
                            worker=reply.get("worker", ""),
                            attempt=att,
                        )
                        epoch_refs[le][ls] = ref
                        part_files.add(str(ref["path"]))
                        journal_epoch(le, epoch_refs[le])
                    return repair

                for epoch in range(best + 1, shape.num_iters + 1):
                    won = run_wave(
                        "iterate", list(range(n_shards)),
                        build_iter_req(epoch),
                        repair=repair_iterate(epoch),
                    )
                    refs = {}
                    for shard, reply in won.items():
                        refs[int(reply.get("shard", shard))] = dict(
                            reply["ref"],
                            worker=reply.get("worker", ""),
                            attempt=int(reply.get("attempt", 0)),
                        )
                    epoch_refs[epoch] = refs
                    journal_epoch(epoch, refs)
                    # The rank-shuffle chaos window: published slices
                    # sit durable between epochs, same exposure as the
                    # fold shuffle's map->reduce gap.
                    for s in range(n_shards):
                        distribute.chaos_partition(
                            str(refs[s]["path"]), epoch, s
                        )
                # Finalize on the host: the final epoch's shard slices
                # concatenate (shard order IS node order) into the solo
                # renderer's exact bytes — pure numpy, no engine lock.
                final = epoch_refs[shape.num_iters]
                slices = []
                for s in range(n_shards):
                    ref = final[s]
                    pairs = distribute.read_partition(
                        str(ref["path"]), str(ref["sha256"]),
                        distribute.RANK_KEY_WIDTH,
                    )
                    slices.append(distribute.decode_rank_values(pairs))
                output, distinct, trunc, ovf = (
                    distribute.finalize_ranks(slices)
                )
                self._finish_job(
                    job, [(output, 0)], distinct, trunc, ovf,
                    "distributed", time.monotonic(),
                )
                return

            # ---- fold spines + join trees: one shared map wave ------
            # Every leaf of a covered join tree is the SAME corpus
            # wordcount fold, so ONE map wave serves however many
            # leaves the tree has.
            fold = "wordcount" if is_join else shape.fold
            map_node_fp = (
                shape.leaves[0].node_fp if is_join else shape.node_fp
            )
            lines_per_doc = 1 if is_join else shape.lines_per_doc

            def build_map_req(split: int, attempt: int) -> dict:
                a, b = ranges[split]
                return {
                    "phase": "map", "fold": fold,
                    "config": job.config_overrides or {},
                    "sha": job.corpus_digest,
                    "spill_dir": self.pool.spill_dir,
                    "plan_fp": plan_fp, "split": split,
                    "attempt": attempt, "n_parts": n_parts,
                    "line_start": a, "line_end": b,
                    "lines_per_doc": lines_per_doc,
                    # Keys the worker's warm fold-node executables: a
                    # repeat plan skips the per-worker recompile.
                    "node_fp": map_node_fp,
                }

            map_done: dict[int, dict] = {}

            def journal_stage(split: int, reply: dict) -> None:
                if self.journal is not None:
                    self.journal.append_stage(job.job_id, {
                        "split": split,
                        "attempt": int(reply.get("attempt", 0)),
                        "worker": reply.get("worker", ""),
                        "n_parts": n_parts,
                        "truncated": bool(reply.get("truncated")),
                        "overflow_tokens": int(
                            reply.get("overflow_tokens", 0)
                        ),
                        "parts": reply.get("parts", []),
                    })

            # Reuse a WAL-replayed completed split when the partition
            # layout matches and every file survived with its recorded
            # sha — a restart RESUMES the plan instead of remapping
            # everything (anything damaged just recomputes).
            for st in progress:
                try:
                    s = int(st.get("split", -1))
                    parts = list(st.get("parts") or [])
                    if (not 0 <= s < n_splits or s in map_done
                            or int(st.get("n_parts", -1)) != n_parts
                            or len(parts) != n_parts):
                        continue
                    for ref in parts:
                        with open(str(ref["path"]), "rb") as f:
                            data = f.read()
                        if (hashlib.sha256(data).hexdigest()
                                != ref["sha256"]):
                            raise ValueError("partition sha drifted")
                except Exception as e:  # noqa: BLE001 - damaged = recompute
                    logger.warning(
                        "plan resume: damaged stage record skipped "
                        "(%s: %s); that split recomputes",
                        type(e).__name__, e,
                    )
                    continue
                map_done[s] = dict(st)
                part_files.update(str(p["path"]) for p in parts)
                with self._lock:
                    self._plan_counters["partitions_reused"] += n_parts

            def on_map_win(split, reply, w):
                journal_stage(split, reply)
                self.pool.mark_warm(w, akey)
                if reply.get("warm"):
                    # The worker folded on an already-compiled fold-node
                    # executable (the warm-repeat economics, test- and
                    # bench-pinned: compiles stay flat on resubmit).
                    with self._lock:
                        self._plan_counters["map_warm_hits"] += 1
                    obs.metric_inc("plan.map_warm_hits")

            todo = [s for s in range(n_splits) if s not in map_done]
            if todo:
                map_done.update(run_wave(
                    "map", todo, build_map_req, on_win=on_map_win,
                ))
            truncated = any(
                bool(r.get("truncated")) for r in map_done.values()
            )
            overflow = sum(
                int(r.get("overflow_tokens", 0))
                for r in map_done.values()
            )
            # The shuffle-partition chaos window (docs/FAULTS.md): the
            # published files sit durable between the waves — exactly
            # where a GC race or disk loss would bite a real deployment.
            for s in sorted(map_done):
                for ref in map_done[s].get("parts", []):
                    distribute.chaos_partition(
                        str(ref["path"]), s, int(ref["part"])
                    )
            key_width = distribute.partition_key_width(cfg, fold)

            def partition_inputs(part: int) -> list:
                """One bin's per-split input refs, read at BUILD time so
                a mid-wave repair's fresh refs reach relaunches."""
                return [
                    dict(
                        map_done[s]["parts"][part], split=s,
                        worker=map_done[s].get("worker", ""),
                    )
                    for s in range(n_splits)
                ]

            def repair_map_input(part: int, exc) -> None:
                """A reduce/join attempt lost a partition input:
                recompute exactly that map split (attempt-bumped, on a
                survivor) and re-journal it — the relaunched stage reads
                the fresh refs through partition_inputs' closure."""
                s = getattr(exc, "lost_split", None)
                if s is None:
                    return
                s = int(s)
                w = next_worker()
                if w is None:
                    raise PoolDispatchError(
                        "no surviving plan-stage workers"
                    )
                attempt = int(map_done[s].get("attempt", 0)) + 1
                reply = self._run_plan_stage_rpc(
                    w, build_map_req(s, attempt), "map"
                )
                part_files.update(
                    str(p["path"]) for p in reply.get("parts", [])
                )
                map_done[s] = reply
                journal_stage(s, reply)

            if is_join:
                # ---- join wave: per-bin hash-join, tree-deep ---------
                # Identity gate 1: the solo leaves must be provably
                # untruncated (a truncated fold's table is not the exact
                # wordcount the solo join reads).
                if truncated or overflow:
                    raise _PlanSolo("join_fold_truncated")
                tree_wire = distribute.tree_doc(shape.tree)

                def build_join_req(part: int, attempt: int) -> dict:
                    return {
                        "phase": "join", "part": part,
                        "key_width": key_width,
                        "attempt": attempt,
                        "tree": tree_wire,
                        "inputs": partition_inputs(part),
                    }

                join_done = run_wave(
                    "join", list(range(n_parts)), build_join_req,
                    repair=repair_map_input,
                )
                # Identity gate 2: total distinct within the solo
                # fold's table capacity — past it the solo engine WOULD
                # have truncated, so the solo path must answer.
                total_distinct = sum(
                    int(join_done[p].get("distinct", 0))
                    for p in range(n_parts)
                )
                if total_distinct > cfg.resolved_table_size:
                    raise _PlanSolo("join_fold_capacity")
                # Host-side merge on purpose: join values are unbounded
                # Python ints (mul combines) — no engine lock needed.
                output, distinct, trunc, ovf = distribute.finalize_join([
                    [
                        (base64.b64decode(k), int(v))
                        for k, v in join_done[p].get("pairs", [])
                    ]
                    for p in range(n_parts)
                ])
                self._finish_job(
                    job, [(output, 0)], distinct, trunc, ovf,
                    "distributed", time.monotonic(),
                )
                return

            def build_reduce_req(part: int, attempt: int) -> dict:
                return {
                    "phase": "reduce", "part": part,
                    "key_width": key_width,
                    "attempt": attempt,
                    "inputs": partition_inputs(part),
                }

            reduce_done = run_wave(
                "reduce", list(range(n_parts)), build_reduce_req,
                repair=repair_map_input,
            )
            partition_pairs = [
                [
                    (base64.b64decode(k), int(v))
                    for k, v in reduce_done[p].get("pairs", [])
                ]
                for p in range(n_parts)
            ]
            # Finalize is device work (the wordcount re-merge) on the
            # coordinator thread: it serializes with every other local
            # device touch.
            with self._engine_lock:
                output, distinct, trunc, ovf = distribute.finalize(
                    shape, cfg, job.n_lines, partition_pairs,
                    truncated, overflow,
                )
            self._finish_job(
                job, [(output, 0)], distinct, trunc, ovf,
                "distributed", time.monotonic(),
            )
        except _PlanSolo as e:
            # The solo engine is the correctness floor: demote LOUDLY
            # (logged once per reason, counted in stats pool.plan —
            # never silent, the fused_demoted stance).  Placements go
            # back first so the solo run never starves the pool.
            for w in placements:
                self.pool.release(w)
            placements = []
            self._count_plan_solo(e.reason)
            self._dispatch_local([job], corpora)
        except PlanError as e:
            # Deterministic rejection — same bad_spec discipline as the
            # solo plan path (retrying cannot change the answer).
            self._fail_batch([job], structured_error(
                "bad_spec",
                f"plan execution rejected the corpus: {e}",
            ))
        except Exception as e:  # noqa: BLE001 - retry ladder absorbs it
            logger.warning(
                "distributed plan dispatch of %s failed: %s: %s",
                job.job_id, type(e).__name__, e,
            )
            if getattr(e, "code", None) == "stale_epoch":
                # A worker has served a NEWER primary: we are the
                # fenced-out zombie — no stale partition may publish.
                worker_epoch = getattr(e, "epoch", None)
                with self._lock:
                    fence = max(
                        self._seen_epoch, self.epoch + 1,
                        int(worker_epoch or 0),
                    )
                self._demote(fence)
            self._retry_or_fail(
                [job], corpora,
                f"distributed plan: {type(e).__name__}: {e}",
            )
        finally:
            # Held for the whole run (each worker serves several stage
            # RPCs); a straggler RPC still in flight past this release
            # is bounded by the worker's own rpc timeout.
            for w in placements:
                self.pool.release(w)
            # Shuffle partitions are scaffolding once the job settled —
            # the fsync'd admit record can always re-run the plan — so
            # drop them best-effort to keep the spill dir from accreting.
            for p in part_files:
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _finish_job(
        self, job: Job, pairs: list, distinct, truncated, overflow,
        cache_label: str, done: float,
    ) -> None:
        """Publish one finished job — the demux core shared by the
        local, remote, and shard paths."""
        size = jobs_pairs_bytes(pairs)
        meta = {
            "distinct": int(distinct),
            "truncated": bool(truncated),
            "overflow_tokens": int(overflow),
        }
        if job.expired(done):
            # Deadline expiry ANYWHERE answers structured
            # deadline_exceeded — even when the result just landed: the
            # client stopped waiting at the budget it set.  The correct
            # result still feeds the result cache below, so a resubmit
            # of the same work is answered instantly.
            self._fail_jobs([(job, structured_error(
                "deadline_exceeded",
                f"deadline of {job.spec.deadline_s}s expired "
                "while the job was running; the result was "
                "cached — resubmit to fetch it",
            ))])
            if not job.spec.no_cache:
                self.results.put(
                    job.corpus_digest, job.spec.fingerprint(), pairs,
                    meta=meta,
                )
            return
        with self._lock:
            # state flips to "done" LAST: status/result handlers read
            # job fields without this lock, so the state write is the
            # publish barrier — a reader seeing "done" must also see the
            # result (done-with-None-result would answer an empty pairs
            # list as success).
            job.cache = cache_label
            job.finished_s = done
            job.result = pairs
            job.result_bytes = size
            job.distinct = int(distinct)
            job.truncated = bool(truncated)
            job.overflow_tokens = int(overflow)
            job.state = "done"
            self._completed += 1
            self._result_bytes += size
            self._evict_history(keep=job.job_id)
        if not job.spec.no_cache:
            self.results.put(
                job.corpus_digest, job.spec.fingerprint(), pairs,
                meta=meta,
            )
        if self.journal is not None:
            self.journal.append_state(job.job_id, "done")
        obs.metric_inc("serve.jobs")
        obs.metric_observe("serve.latency_ms", job.latency_ms())

    def _maybe_mark_warm(self) -> None:
        """Latest-wins background warm generation: never blocks on disk
        (io/snapshot.py).  Distance-based cadence, not modulo:
        ``completed`` advances by batch size on three dispatch paths and
        by result-cache hits on handler threads, so no single thread may
        ever OBSERVE a multiple of warm_every — a modulo check could
        skip marks forever and silently demote the cadence to "clean
        shutdown only".  The cursor read+write holds the lock (close()
        snapshots the generation counter under it); the mark itself
        stays outside — it only enqueues on the async writer."""
        if self.warm is None:
            return
        with self._lock:
            completed = self._completed
            due = completed - self._warm_marked >= self.cfg.warm_every
            if due:
                self._warm_marked = completed
        if due:
            self.warm.mark(completed)

    # ---------------------------------------------------- retry/fail/journal

    @staticmethod
    def _retry_jitter(job_id: str, attempt: int) -> float:
        """Deterministic jitter fraction in [0, 1): same job + attempt ->
        same jitter on every run (the chaos matrix stays reproducible),
        different jobs -> decorrelated retries (no thundering herd)."""
        h = hashlib.sha256(f"{job_id}:{attempt}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def _retry_or_fail(
        self, jobs: list[Job], corpora: dict, reason: str
    ) -> None:
        """One failed dispatch enters the retry ladder (docs/SERVING.md):

          * a multi-job batch BISECTS — the halves get distinct
            ``bisect_group`` tags so they can never re-coalesce, which
            isolates a poison job in log2(batch) extra dispatches while
            its innocent neighbors succeed on their own half;
          * each surviving job requeues with exponential backoff +
            deterministic jitter, bounded by its ``max_attempts`` budget
            and its deadline;
          * a job that exhausts attempts with its LAST kill being a SOLO
            dispatch is quarantined as structured ``poison_job`` (it
            demonstrably kills dispatches on its own); otherwise the
            terminal code is ``dispatch_failed``;
          * deadline expiry at any rung answers ``deadline_exceeded``.
        """
        now = time.monotonic()
        alive = [j for j in jobs if j.state != "done"]  # demuxed: stands
        solo = len(alive) == 1
        if len(alive) > 1:
            tag = uuid.uuid4().hex[:6]
            half = (len(alive) + 1) // 2
            for k, job in enumerate(alive):
                side = "L" if k < half else "R"
                job.bisect_group = f"{tag}.{side}"
        failures: list[tuple[Job, dict]] = []
        for job in alive:
            job.attempts += 1
            if job.expired(now):
                failures.append((job, structured_error(
                    "deadline_exceeded",
                    f"deadline of {job.spec.deadline_s}s expired after a "
                    f"failed dispatch (attempt {job.attempts}/"
                    f"{job.spec.max_attempts}; last error: {reason})",
                )))
                continue
            if job.attempts >= job.spec.max_attempts:
                if solo:
                    failures.append((job, structured_error(
                        "poison_job",
                        f"job killed {job.attempts} dispatch(es), the "
                        f"last one solo — quarantined (last error: "
                        f"{reason}); inspect the spec/corpus before "
                        "resubmitting",
                    )))
                else:
                    failures.append((job, structured_error(
                        "dispatch_failed",
                        f"dispatch failed {job.attempts} time(s), retry "
                        f"budget exhausted (last error: {reason})",
                    )))
                continue
            backoff = min(
                self.cfg.retry_cap_s,
                self.cfg.retry_base_s * 2.0 ** (job.attempts - 1),
            )
            backoff *= 1.0 + self._retry_jitter(job.job_id, job.attempts)
            not_before = now + backoff
            dm = job.deadline_mono()
            if dm is not None and not_before >= dm:
                failures.append((job, structured_error(
                    "deadline_exceeded",
                    f"deadline of {job.spec.deadline_s}s cannot fit "
                    f"another attempt after {job.attempts} failure(s) "
                    f"(last error: {reason})",
                )))
                continue
            data = corpora.get(job.corpus_digest)
            if data is None:
                failures.append((job, structured_error(
                    "dispatch_failed",
                    "in-flight corpus bytes missing at retry (daemon "
                    f"bug) — resubmit (last error: {reason})",
                )))
                continue
            with self._lock:
                if job.job_id not in self._corpus_bytes:
                    self._corpus_put(job.job_id, data)
                job.state = "retrying"
            if not self.scheduler.requeue(job, not_before):
                with self._lock:
                    self._corpus_pop(job.job_id)
                failures.append((job, structured_error(
                    "shutting_down",
                    "daemon shut down before this job could retry; "
                    "resubmit after it returns",
                )))
                continue
            obs.event(
                "serve.retry",
                job=job.job_id, attempt=job.attempts,
                backoff_ms=round(backoff * 1e3, 1),
                group=job.bisect_group,
            )
        if failures:
            self._fail_jobs(failures)

    def _fail_batch(self, jobs: list[Job], error: dict) -> None:
        self._fail_jobs([(j, error) for j in jobs])

    def _fail_jobs(self, failures: list[tuple[Job, dict]]) -> None:
        now = time.monotonic()
        with self._lock:
            for job, error in failures:
                if job.state == "done":
                    continue  # demuxed before the failure: result stands
                # error before state: the state write is the lock-free
                # readers' publish barrier (same rule as the demux path).
                job.error = dict(error)
                job.finished_s = now
                job.state = "failed"
        if self.journal is not None:
            for job, error in failures:
                if job.state == "failed":
                    self.journal.append_state(
                        job.job_id, "failed", error=error
                    )

    def _compact_journal(self) -> None:
        """Rewrite the journal down to the still-live jobs (and GC their
        orphaned corpus spills).  Liveness comes from the journal's OWN
        records under its lock (journal.compact) — a daemon-side job
        snapshot would race handler-thread admits fsync'd between the
        snapshot and the rewrite, silently dropping acked work.

        Replication-aware: compaction SHIPS as a snapshot barrier — the
        standby re-syncs to the compacted live set, so a catch-up that
        was mid-flight when the GC ran converges instead of stranding on
        swept spills (every swept spill's job has a terminal record
        already in the ship stream)."""
        self.journal.compact()
        shipper = self.shipper  # snapshot: _demote may null it mid-call
        if shipper is not None:
            shipper.barrier()

    def _replay_journal(self) -> None:
        """Crash recovery: re-enqueue every journaled job still owed an
        answer, under its ORIGINAL id (docs/SERVING.md durability):

          * terminal ``failed``/``cancelled`` records are restored as
            finished history, so a result fetch across the restart reads
            the same structured error;
          * ``done`` jobs whose (corpus sha, spec) is in the restored
            result cache are restored as done — byte-identical replay;
            done jobs the warm state had not yet persisted RE-ENQUEUE
            (the fold is deterministic, so the recompute is
            byte-identical too);
          * everything else re-enqueues from its spilled corpus; a
            missing/damaged spill is a structured failure, never a
            silent loss.  Deadline budgets re-anchor at replay time.
        """
        entries = self.journal.replay()
        requeued = restored = failed = dropped = 0
        now = time.monotonic()
        for entry in entries:
            rec = entry.admit
            term = entry.terminal
            if term is not None and term["state"] == "rejected":
                dropped += 1
                continue
            try:
                plan_json = None
                if rec.get("plan") is not None:
                    # Plan jobs journal the plan DOCUMENT in the admit
                    # record: replay re-validates it end-to-end (the
                    # same gate a fresh submit passes) so a record
                    # carrying a no-longer-valid plan fails structured
                    # below, never a dispatch-time surprise.
                    from locust_tpu.plan import from_doc as plan_from_doc

                    plan_json = plan_from_doc(
                        rec["plan"]
                    ).canonical_json()
                elif rec["workload"] not in WORKLOADS:
                    raise ValueError(f"workload {rec['workload']!r}")
                overrides = dict(rec.get("config") or {})
                spec = JobSpec(
                    tenant=str(rec["tenant"]),
                    workload=str(rec["workload"]),
                    cfg=EngineConfig(**overrides),
                    weight=float(rec.get("weight", 1.0)),
                    no_cache=bool(rec.get("no_cache")),
                    deadline_s=rec.get("deadline_s"),
                    max_attempts=int(rec.get("max_attempts", 4)),
                    plan=plan_json,
                )
                n_lines = int(rec["n_lines"])
                n_blocks, bucket = batching.job_shape(n_lines, spec.cfg)
                job = Job(
                    job_id=str(rec["job_id"]),
                    spec=spec,
                    corpus_digest=str(rec["corpus_sha"]),
                    n_lines=n_lines,
                    n_blocks=n_blocks,
                    bucket=bucket,
                    config_overrides=overrides,
                )
            except Exception as e:  # noqa: BLE001 - one bad record
                logger.warning(
                    "journal replay: admit record unusable (%s: %s)",
                    type(e).__name__, e,
                )
                # The job was ACKED: silently dropping it answers
                # unknown_job, against the every-acked-job-answers
                # guarantee.  Remember it as failed with a structured
                # reason instead (a placeholder spec carries the record
                # through status/result; nothing ever dispatches it),
                # and journal the terminal state so compaction drops it.
                job_id = str(rec.get("job_id") or "")
                if not job_id:
                    dropped += 1
                    continue
                ghost = Job(
                    job_id=job_id,
                    spec=JobSpec(
                        tenant=str(rec.get("tenant", "default")),
                        workload="wordcount",
                        cfg=EngineConfig(),
                    ),
                    corpus_digest=str(rec.get("corpus_sha", "")),
                    n_lines=0, n_blocks=1, bucket=1,
                )
                ghost.error = structured_error(
                    "dispatch_failed",
                    f"journal admit record unusable after restart "
                    f"({type(e).__name__}: {e}) — resubmit",
                )
                ghost.finished_s = now
                ghost.state = "failed"
                with self._lock:
                    self._remember(ghost)
                self.journal.append_state(
                    job_id, "failed", error=ghost.error
                )
                failed += 1
                continue
            if term is not None and term["state"] in ("failed", "cancelled"):
                job.state = term["state"]
                # Fallback code keyed by the terminal STATE: an old
                # record with no error payload must not rewrite a
                # cancellation into a dispatch failure — clients switch
                # on .code (docs/SERVING.md).
                job.error = dict(term.get("error") or structured_error(
                    "cancelled" if term["state"] == "cancelled"
                    else "dispatch_failed",
                    f"{term['state']} before the restart",
                ))
                job.finished_s = now
                with self._lock:
                    self._remember(job)
                restored += 1
                continue
            if term is not None and term["state"] == "done":
                hit = self.results.get_with_meta(
                    job.corpus_digest, spec.fingerprint()
                )
                if hit is not None:
                    pairs, meta = hit
                    job.state = "done"
                    job.cache = "result"
                    job.started_s = job.submitted_s
                    job.finished_s = now
                    job.result = pairs
                    job.result_bytes = jobs_pairs_bytes(pairs)
                    job.distinct = int(meta.get("distinct", len(pairs)))
                    job.truncated = bool(meta.get("truncated", False))
                    job.overflow_tokens = int(
                        meta.get("overflow_tokens", 0)
                    )
                    with self._lock:
                        self._result_bytes += job.result_bytes
                        self._remember(job)
                    restored += 1
                    continue
                # done but not persisted: fall through and recompute.
            corpus = self.journal.read_spill(job.corpus_digest)
            if corpus is None:
                job.error = structured_error(
                    "dispatch_failed",
                    "corpus spill missing or damaged after restart — "
                    "resubmit",
                )
                job.finished_s = now
                job.state = "failed"
                with self._lock:
                    self._remember(job)
                # Terminal record so compaction retires the admit — the
                # spill is gone, so every future replay would fail the
                # same way forever.
                self.journal.append_state(
                    job.job_id, "failed", error=job.error
                )
                failed += 1
                continue
            with self._lock:
                self._remember(job)
                self._corpus_put(job.job_id, corpus)
                if entry.stages and job.spec.plan is not None:
                    # Stage-progress records (distributed plans): the
                    # coordinator re-verifies each recorded partition
                    # file by sha and reuses the survivors instead of
                    # remapping the whole plan (docs/PLAN.md).
                    self._plan_progress[job.job_id] = list(entry.stages)
            self.scheduler.requeue(job, 0.0)
            if entry.terminal is not None:
                # A done-but-unpersisted job re-enqueues past its own
                # terminal record: a fresh admit append re-asserts
                # liveness (both compact and replay treat the LAST
                # record sequence as truth), otherwise compaction would
                # retire it mid-rerun and a second crash would lose it.
                self.journal.append_admit(job, corpus)
            requeued += 1
        self.journal.compact()
        if requeued or restored or failed or dropped:
            obs.event(
                "serve.replay",
                requeued=requeued, restored=restored,
                failed=failed, dropped=dropped,
            )
            logger.info(
                "journal replay: %d job(s) re-enqueued, %d restored "
                "finished, %d failed structured, %d dropped",
                requeued, restored, failed, dropped,
            )
