"""Locust Serve: the persistent multi-tenant engine daemon.

The one-shot CLI pays full cold start on every run — process spawn,
backend probe, 20-40 s TPU compile, cold caches (CLAUDE.md).  This daemon
keeps ONE process resident and serves many concurrent jobs against warm
compiled executables (docs/SERVING.md):

  * **protocol**: the distributor's authenticated length-prefixed frames
    (distributor/protocol.py — HMAC, replay guard, the same negotiation
    stance), with a serve-specific closed command set::

        submit | status | result | cancel | invalidate | stats
        | ping | shutdown

  * **admission + fairness**: a bounded queue that rejects-with-reason
    when full and a per-tenant weighted fair scheduler
    (serve/scheduler.py) so one heavy tenant cannot starve the rest;
  * **warm-executable cache**: compiled programs keyed by (workload,
    EngineConfig fingerprint, shape bucket) — repeat jobs skip
    compilation (serve/cache.py);
  * **shape-bucketed batching**: compatible queued jobs coalesce into one
    vmapped engine dispatch and demultiplex per-job results
    (serve/batch.py, engine.run_batch);
  * **result cache**: (corpus digest, job spec) -> finished table, with
    explicit invalidation, persisted across restarts through the async
    snapshot writer (serve/cache.WarmState -> io/snapshot.py).

Error discipline (pinned by the chaos matrix, tests/test_faults.py): a
client observes either a correct result or a STRUCTURED error carrying a
``jobs.ERROR_CODES`` reason — never a silent wrong answer.  The
``serve.admit`` and ``serve.dispatch`` fault sites (utils/faultplan.py)
inject failures at the admission and dispatch boundaries to keep that
claim honest.

Telemetry (docs/OBSERVABILITY.md): per-job phases land as ``serve.*``
spans — queue wait, compile-or-hit, dispatch, demux — plus admission
events and latency/cache metrics, all in the closed obs registry (R009).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import logging
import socket
import threading
import time
import uuid

from locust_tpu import obs
from locust_tpu.distributor import protocol
from locust_tpu.serve import batch as batching
from locust_tpu.serve.cache import (
    ExecutableCache,
    ResultCache,
    WarmState,
)
from locust_tpu.serve.jobs import (
    Job,
    parse_spec,
    structured_error,
)
from locust_tpu.serve.jobs import pairs_bytes as jobs_pairs_bytes
from locust_tpu.serve.scheduler import AdmitReject, FairScheduler
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")

SERVE_COMMANDS = (
    "ping", "submit", "status", "result", "cancel", "invalidate",
    "stats", "shutdown",
)


@dataclasses.dataclass
class ServeConfig:
    """Daemon capacity/policy knobs (docs/SERVING.md)."""

    max_queue: int = 64          # admission bound: pending jobs, global
    max_batch: int = 8           # jobs coalesced into one dispatch
    tenant_quota: int | None = 32  # pending jobs per tenant (None = off)
    max_engines: int = 4         # warm engines kept (LRU)
    max_results: int = 256       # result-cache entries kept (LRU)
    max_result_bytes: int = 256 << 20  # result-cache aggregate byte cap
    # Aggregate cap on result payloads retained by FINISHED job records
    # (max_history bounds record COUNT; 1024 records of multi-MB pairs
    # would be GBs of RSS).  Past it the oldest finished records are
    # evicted whole — a later result fetch reads unknown_job, exactly
    # like the existing count-cap eviction.
    max_history_bytes: int = 256 << 20
    max_corpus_bytes: int = 16 << 20  # inline submit payload cap
    # Aggregate cap on ALL buffered in-flight corpora: max_queue bounds
    # job COUNT, but max_queue * max_corpus_bytes of buffered bytes
    # (1 GiB at defaults) is an OOM, and overload must become a
    # structured rejection, not a dead daemon.
    max_queue_bytes: int = 256 << 20
    warm_dir: str | None = None  # persist warm state here (None = off)
    warm_every: int = 8          # warm-state generation cadence (jobs)
    max_history: int = 1024      # finished jobs kept for status/result
    conn_timeout: float = 30.0
    max_connections: int = 32
    dispatch_poll_s: float = 0.25  # dispatcher wake cadence when idle


class ServeDaemon:
    """One serve daemon: accept loop + single dispatcher thread.

    Maps serialize through the ONE dispatcher (the node has one
    accelerator — same stance as the distributor worker's map lock);
    handler threads only touch the queue, the caches, and job records.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: bytes = b"",
        cfg: ServeConfig | None = None,
    ):
        if not secret:
            raise ValueError("serve daemon requires a shared secret "
                             "(same Q8 stance as the distributor)")
        self.secret = secret
        self.cfg = cfg or ServeConfig()
        self.scheduler = FairScheduler(
            max_queue=self.cfg.max_queue,
            max_batch=self.cfg.max_batch,
            tenant_quota=self.cfg.tenant_quota,
        )
        self.executables = ExecutableCache(max_engines=self.cfg.max_engines)
        self.results = ResultCache(
            max_entries=self.cfg.max_results,
            max_bytes=self.cfg.max_result_bytes,
        )
        self.warm = (
            WarmState(self.cfg.warm_dir, self.results)
            if self.cfg.warm_dir
            else None
        )
        if self.warm is not None:
            self.warm.load()
        self._lock = threading.Lock()
        self._jobs: dict[str, Job] = {}       # insertion order = age
        self._corpus_bytes: dict[str, bytes] = {}  # job_id -> in-flight bytes
        self._corpus_total = 0  # sum of _corpus_bytes values (admission cap)
        self._result_bytes = 0  # sum of retained job.result_bytes (history cap)
        self._completed = 0
        self._warm_marked = 0  # completed-count at the last warm mark
        self._started_s = time.monotonic()
        self._replay_guard = protocol.ReplayGuard()
        self._conn_slots = threading.BoundedSemaphore(self.cfg.max_connections)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(5)
        self.addr = self._sock.getsockname()
        self._shutdown = threading.Event()
        self._closed = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="serve-dispatch", daemon=True
        )
        self._dispatcher.start()

    # --------------------------------------------------------- accept loop

    def serve_forever(self) -> None:
        # try/finally, not loop-exit cleanup: a KeyboardInterrupt in the
        # foreground CLI lands inside accept() and would otherwise skip
        # close() — losing the final warm-state flush the --warm-dir
        # flag promises (close() is idempotent, so the shutdown-command
        # path calling through here again is safe).
        try:
            while not self._shutdown.is_set():
                try:
                    self._sock.settimeout(0.5)
                    conn, _peer = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                # Bounded acquire: a plain acquire() with all slots held
                # by slow peers would wedge this loop PAST the shutdown
                # check — neither a shutdown command nor close() could
                # ever land.
                acquired = False
                while not self._shutdown.is_set():
                    if self._conn_slots.acquire(timeout=0.5):
                        acquired = True
                        break
                if not acquired:
                    conn.close()
                    continue
                threading.Thread(
                    target=self._serve_one, args=(conn,), daemon=True
                ).start()
        finally:
            self._sock.close()
            self.close()

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def close(self) -> None:
        """Stop the dispatcher and flush warm state.  Idempotent and
        race-safe: the accept loop's exit path and an operator teardown
        may both call it (first caller wins the warm flush)."""
        self._shutdown.set()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            gen = self._completed
        self.scheduler.stop()
        # The join must outlive one TPU cold compile (20-40s per
        # CLAUDE.md): a shorter timeout lets close() flush + close the
        # warm writer while a dispatch is mid-compile, so that batch's
        # late warm.mark hits a closed writer and its jobs silently
        # miss the persisted state.
        self._dispatcher.join(timeout=90.0)
        if self._dispatcher.is_alive():
            logger.warning(
                "serve dispatcher still busy after 90s at close; jobs "
                "finishing after this point will not reach warm state"
            )
        # The stopped scheduler answers next_batch with None forever, so
        # jobs still queued here can never dispatch: fail them with the
        # structured shutdown code and free their buffered corpora
        # instead of abandoning them in state "queued" — an accepted job
        # must end in a result or a reason code, even at teardown.
        stranded = self.scheduler.drain()
        if stranded:
            with self._lock:
                for job in stranded:
                    self._corpus_pop(job.job_id)
            self._fail_batch(stranded, structured_error(
                "shutting_down",
                "daemon shut down before this job was dispatched; "
                "resubmit after it returns",
            ))
        if self.warm is not None:
            try:
                self.warm.mark(gen + 1)  # final generation: latest results
            except Exception:  # noqa: BLE001 - a failed PRIOR background
                # write re-raises at the next submit (io/snapshot.py);
                # the flush is best-effort at shutdown and must not
                # leave the writer thread unjoined (close is guarded by
                # _closed, so an escape here is permanently unretryable).
                logger.exception("serve final warm mark failed")
            self.warm.close()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            self._serve_conn(conn)
        finally:
            self._conn_slots.release()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._shutdown.is_set():
                    try:
                        conn.settimeout(self.cfg.conn_timeout)
                        req = protocol.recv_frame(conn, self.secret)
                    except PermissionError:
                        return  # unauthenticated peer: drop silently
                    except (ConnectionError, socket.timeout, OSError):
                        return  # peer closed / idled out
                    except Exception as e:
                        self._try_reply(
                            conn, structured_error("bad_spec", str(e))
                        )
                        return
                    try:
                        self._replay_guard.check(req)
                        resp = self._handle(req)
                    except PermissionError:
                        return  # replayed frame: drop silently
                    except Exception as e:  # noqa: BLE001 - daemon survives
                        resp = structured_error(
                            "dispatch_failed", f"{type(e).__name__}: {e}"
                        )
                    if not self._try_reply(conn, resp):
                        return
        except Exception:  # noqa: BLE001 - connection threads never die loud
            logger.exception("serve connection handler failed")

    def _try_reply(self, conn: socket.socket, resp: dict) -> bool:
        try:
            protocol.send_frame(conn, resp, self.secret, sign_fresh=False)
            return True
        except protocol.FrameTooLarge as e:
            # Raised BEFORE any bytes hit the wire (send_frame sizes the
            # whole frame first), so the connection is still clean:
            # answer with a small structured error instead of dropping
            # the peer — a completed job whose result JSON exceeds
            # MAX_FRAME would otherwise be permanently unfetchable
            # through bare ConnectionErrors, against the tier's
            # correct-result-or-structured-error guarantee.
            err = structured_error(
                "result_too_large",
                f"reply frame exceeds protocol.MAX_FRAME "
                f"({protocol.MAX_FRAME} bytes): {e}; lower table_size "
                "or split the corpus",
            )
            try:
                protocol.send_frame(
                    conn, err, self.secret, sign_fresh=False
                )
                return True
            except (protocol.ProtocolError, OSError):
                return False
        except OSError:
            return False

    # ----------------------------------------------------------- commands

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd not in SERVE_COMMANDS:
            return structured_error(
                "unknown_command",
                f"unknown command {cmd!r} (serve speaks {SERVE_COMMANDS})",
            )
        if cmd == "ping":
            return {"status": "ok", "pong": True, "service": "locust-serve"}
        if cmd == "shutdown":
            self._shutdown.set()
            return {"status": "ok", "bye": True}
        if cmd == "submit":
            return self._cmd_submit(req)
        if cmd == "status":
            return self._cmd_status(req)
        if cmd == "result":
            return self._cmd_result(req)
        if cmd == "cancel":
            return self._cmd_cancel(req)
        if cmd == "invalidate":
            return self._cmd_invalidate(req)
        return self._cmd_stats()

    def _cmd_submit(self, req: dict) -> dict:
        try:
            spec, corpus = parse_spec(
                req, max_corpus_bytes=self.cfg.max_corpus_bytes
            )
        except ValueError as e:
            code, _, msg = str(e).partition("\n")
            obs.event("serve.reject", code=code)
            return structured_error(code, msg or code)
        if len(corpus) > self.cfg.max_corpus_bytes:
            obs.event("serve.reject", code="corpus_too_large")
            return structured_error(
                "corpus_too_large",
                f"inline corpus of {len(corpus)} bytes exceeds the "
                f"daemon cap ({self.cfg.max_corpus_bytes}); stream it "
                "through a server-side path instead",
            )
        # Chaos: the admission boundary (docs/FAULTS.md).  "error" models
        # an admission subsystem failure — the client gets a structured
        # rejection and may retry; "delay" models admission contention.
        rule = faultplan.fire(
            "serve.admit", tenant=spec.tenant, workload=spec.workload
        )
        if rule is not None:
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            else:
                obs.event("serve.reject", code="fault_injected")
                return structured_error(
                    "fault_injected",
                    "[faultplan] injected admission failure — retry",
                )
        digest = hashlib.sha256(corpus).hexdigest()
        spec_fp = spec.fingerprint()
        n_lines = batching.count_lines(corpus)
        n_blocks, bucket = batching.job_shape(n_lines, spec.cfg)
        job = Job(
            job_id=uuid.uuid4().hex[:12],
            spec=spec,
            corpus_digest=digest,
            n_lines=n_lines,
            n_blocks=n_blocks,
            bucket=bucket,
        )
        if not spec.no_cache and not spec.invalidate:
            hit = self.results.get_with_meta(digest, spec_fp)
            if hit is not None:
                # Served straight from the result cache: no queue, no
                # engine.  The job record still exists so status/result
                # work uniformly.  The ORIGINAL run's truncation flags
                # replay with the pairs — a lossy result must stay
                # flagged lossy on every replay, or the cache hit would
                # be the silent wrong answer this tier forbids.
                pairs, meta = hit
                job.state = "done"
                job.cache = "result"
                job.started_s = job.submitted_s
                job.finished_s = time.monotonic()
                job.result = pairs
                job.result_bytes = jobs_pairs_bytes(pairs)
                job.distinct = int(meta.get("distinct", len(pairs)))
                job.truncated = bool(meta.get("truncated", False))
                job.overflow_tokens = int(meta.get("overflow_tokens", 0))
                with self._lock:
                    self._result_bytes += job.result_bytes
                    self._remember(job)
                    self._completed += 1
                obs.metric_inc("serve.result_cache_hits")
                obs.metric_inc("serve.jobs")
                obs.metric_observe("serve.latency_ms", job.latency_ms())
                return {
                    "status": "ok", "job_id": job.job_id,
                    "state": "done", "cached": True,
                }
        # Record the job + its bytes BEFORE admit: admit() wakes the
        # dispatcher, which may pop the job immediately — if the corpus
        # landed after, the dispatch would fold an empty stack and hand
        # the client a silently-empty "done" (the exact wrong answer
        # this tier promises never to produce).
        with self._lock:
            over = (
                self._corpus_total + len(corpus)
                > self.cfg.max_queue_bytes
            )
            if not over:
                self._remember(job)
                self._corpus_put(job.job_id, corpus)
        if over:
            self.scheduler.count_rejection()
            obs.event("serve.reject", code="queue_full")
            return structured_error(
                "queue_full",
                f"buffered corpus bytes at cap "
                f"({self.cfg.max_queue_bytes}); retry with backoff",
            )
        try:
            self.scheduler.admit(job)
        except AdmitReject as e:
            with self._lock:
                self._jobs.pop(job.job_id, None)
                self._corpus_pop(job.job_id)
            obs.event("serve.reject", code=e.code)
            return structured_error(e.code, str(e))
        if spec.invalidate:
            # Only AFTER admission succeeds: a rejected submit must have
            # no side effects — wiping before admission let one tenant's
            # queue_full request destroy the cached entry every other
            # tenant was being served from.  (The cache-hit check above
            # already skips lookups for invalidate submits, so this job
            # recomputes either way.)
            self.results.invalidate(digest=digest, spec_fp=spec_fp)
        obs.event(
            "serve.admit",
            job=job.job_id, tenant=spec.tenant, bucket=bucket,
        )
        return {
            "status": "ok", "job_id": job.job_id,
            "state": "queued", "cached": False,
        }

    def _remember(self, job: Job) -> None:
        """Record a job, then evict past the history caps.  Caller
        holds self._lock."""
        self._jobs[job.job_id] = job
        self._evict_history(keep=job.job_id)

    def _evict_history(self, keep: str | None = None) -> None:
        """Evict the OLDEST FINISHED records while over the history
        count cap OR the aggregate retained-result byte cap
        (queued/running records are live state, never evicted).
        ``keep`` is the job whose completion triggered this call: it
        must survive even when its result alone overflows the byte cap,
        or a job could be evicted between its own done-ack and the
        client's result fetch (same stance as ResultCache keeping a
        single oversized entry).  Caller holds self._lock."""

        def over() -> bool:
            return (len(self._jobs) > self.cfg.max_history
                    or self._result_bytes > self.cfg.max_history_bytes)

        if not over():
            return
        for jid, j in list(self._jobs.items()):
            if not over():
                break
            if jid != keep and j.state in ("done", "failed", "cancelled"):
                del self._jobs[jid]
                self._corpus_pop(jid)
                self._result_bytes -= j.result_bytes

    def _job(self, req: dict) -> Job | None:
        with self._lock:
            return self._jobs.get(str(req.get("job_id", "")))

    def _corpus_put(self, job_id: str, data: bytes) -> None:
        """Buffer one job's corpus; caller holds self._lock."""
        self._corpus_bytes[job_id] = data
        self._corpus_total += len(data)

    def _corpus_pop(self, job_id: str) -> bytes | None:
        """Drop one job's buffered corpus; caller holds self._lock."""
        data = self._corpus_bytes.pop(job_id, None)
        if data is not None:
            self._corpus_total -= len(data)
        return data

    def _cmd_status(self, req: dict) -> dict:
        job = self._job(req)
        if job is None:
            return structured_error(
                "unknown_job", f"no job {req.get('job_id')!r}"
            )
        return {"status": "ok", **job.public()}

    def _cmd_result(self, req: dict) -> dict:
        import base64

        job = self._job(req)
        if job is None:
            return structured_error(
                "unknown_job", f"no job {req.get('job_id')!r}"
            )
        if job.state == "failed":
            err = job.error or structured_error(
                "dispatch_failed", "job failed"
            )
            return dict(err, job_id=job.job_id, state="failed")
        if job.state == "cancelled":
            return structured_error(
                "cancelled", f"job {job.job_id} was cancelled"
            )
        if job.state != "done":
            return dict(
                structured_error(
                    "not_done", f"job {job.job_id} is {job.state}"
                ),
                state=job.state,
            )
        return {
            "status": "ok",
            "job_id": job.job_id,
            "state": "done",
            "cache": job.cache,
            "distinct": job.distinct,
            "truncated": job.truncated,
            "overflow_tokens": job.overflow_tokens,
            "latency_ms": job.latency_ms(),
            "pairs": [
                [base64.b64encode(k).decode(), int(v)]
                for k, v in (job.result or [])
            ],
        }

    def _cmd_cancel(self, req: dict) -> dict:
        job = self._job(req)
        if job is None:
            return structured_error(
                "unknown_job", f"no job {req.get('job_id')!r}"
            )
        popped = self.scheduler.cancel(job.job_id)
        if popped is not None:
            with self._lock:
                job.state = "cancelled"
                job.finished_s = time.monotonic()
                job.error = structured_error(
                    "cancelled", "cancelled while queued"
                )
                self._corpus_pop(job.job_id)
            return {"status": "ok", "cancelled": True, "state": "cancelled"}
        # Running/finished jobs are past the point of no return — report
        # the state, don't pretend.
        return {"status": "ok", "cancelled": False, "state": job.state}

    def _cmd_invalidate(self, req: dict) -> dict:
        digest = req.get("digest")
        spec_fp = req.get("spec_fp")
        if req.get("job_id"):
            job = self._job(req)
            if job is None:
                # Falling through with (digest, spec_fp) both None hits
                # ResultCache's wipe-everything match: a typo'd or
                # history-evicted id would silently destroy EVERY
                # tenant's cached results and still answer "ok".
                return structured_error(
                    "unknown_job", f"no job {req.get('job_id')!r}"
                )
            digest = job.corpus_digest
            spec_fp = job.spec.fingerprint()
        n = self.results.invalidate(
            digest=str(digest) if digest else None,
            spec_fp=str(spec_fp) if spec_fp else None,
        )
        return {"status": "ok", "invalidated": n}

    def _cmd_stats(self) -> dict:
        with self._lock:
            states: dict[str, int] = {}
            for j in self._jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            completed = self._completed
            corpus_total = self._corpus_total
            result_bytes = self._result_bytes
        return {
            "status": "ok",
            "service": "locust-serve",
            "uptime_s": round(time.monotonic() - self._started_s, 3),
            "completed": completed,
            "jobs_by_state": states,
            "queued_corpus_bytes": corpus_total,
            "history_result_bytes": result_bytes,
            "queue": self.scheduler.stats(),
            "exec_cache": self.executables.stats(),
            "result_cache": self.results.stats(),
            "warm": self.warm.stats() if self.warm is not None else None,
        }

    # ----------------------------------------------------------- dispatch

    def _batch_key(self, job: Job):
        return (self.executables.engine_key(job.spec), job.bucket)

    def _dispatch_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                self._dispatch_once()
            except Exception:  # noqa: BLE001 - the dispatcher must survive
                logger.exception("serve dispatch iteration failed")

    def _dispatch_once(self) -> None:
        # Only an occupied queue is worth a queue-wait span: an idle
        # daemon's poll ticks would bury the timeline in no-op spans.
        cm = (
            obs.span("serve.queue_wait")
            if self.scheduler.depth()
            else contextlib.nullcontext()
        )
        with cm:
            jobs = self.scheduler.next_batch(
                self._batch_key, timeout=self.cfg.dispatch_poll_s
            )
        if not jobs:
            return
        now = time.monotonic()
        with self._lock:
            corpora = {}
            lost = []
            for j in jobs:
                j.state = "running"
                j.started_s = now
                j.batch_size = len(jobs)
                # None = the entry is MISSING (an empty submit stores
                # b"").  A silent b"" default here would fold an all-zero
                # stack and hand the client an empty "done" — the silent
                # wrong answer this tier forbids — so a lost entry fails
                # the job structurally instead.
                data = self._corpus_pop(j.job_id)
                if data is None and j.corpus_digest not in corpora:
                    lost.append(j)
                else:
                    if data is not None:
                        corpora[j.corpus_digest] = data
        if lost:
            self._fail_batch(lost, structured_error(
                "dispatch_failed",
                "in-flight corpus bytes missing at dispatch (daemon "
                "bug) — resubmit",
            ))
            jobs = [j for j in jobs if j not in lost]
            if not jobs:
                return
        # Chaos: the dispatch boundary (docs/FAULTS.md).  "crash" models
        # the dispatch dying mid-flight, "error" an engine-side failure:
        # either way every job in the batch fails with a STRUCTURED
        # error (never a silent wrong answer) and the daemon lives on.
        rule = faultplan.fire("serve.dispatch", jobs=len(jobs))
        if rule is not None:
            if rule.action == "delay":
                time.sleep(rule.delay_s)
            else:
                self._fail_batch(jobs, structured_error(
                    "fault_injected",
                    f"[faultplan] injected dispatch {rule.action}",
                ))
                return
        spec = jobs[0].spec
        njobs_padded = batching.bucket_blocks(len(jobs))
        bucket = jobs[0].bucket
        try:
            with obs.span(
                "serve.compile_or_hit",
                jobs=len(jobs), bucket=bucket,
            ):
                engine, hit = self.executables.lookup(
                    spec, njobs_padded, bucket
                )
            # Literal names per branch: the R009 convention — the
            # analyzer (and the registry) must see every emission site.
            if hit:
                obs.metric_inc("serve.exec_cache_hits")
            else:
                obs.metric_inc("serve.exec_cache_misses")
            with obs.span("serve.dispatch", jobs=len(jobs), bucket=bucket):
                results = batching.dispatch_batch(engine, jobs, corpora)
            self.executables.mark_compiled(spec, njobs_padded, bucket)
            # Demux stays INSIDE the failure boundary: to_host_pairs()
            # is the device->host transfer and can raise (the flapping
            # TPU tunnel is the documented case) — an escape here would
            # leave jobs "running" forever, a hang where the tier
            # promises a structured error.  _fail_batch skips the jobs
            # already marked done, so a mid-demux failure keeps the
            # finished results and fails only the rest.
            with obs.span("serve.demux", jobs=len(jobs)):
                done = time.monotonic()
                for job, res in zip(jobs, results):
                    pairs = res.to_host_pairs()
                    size = jobs_pairs_bytes(pairs)
                    with self._lock:
                        # state flips to "done" LAST: status/result
                        # handlers read job fields without this lock, so
                        # the state write is the publish barrier — a
                        # reader seeing "done" must also see the result
                        # (done-with-None-result would answer an empty
                        # pairs list as success).
                        job.cache = "warm" if hit else "cold"
                        job.finished_s = done
                        job.result = pairs
                        job.result_bytes = size
                        job.distinct = res.num_segments
                        job.truncated = bool(res.truncated)
                        job.overflow_tokens = int(res.overflow_tokens)
                        job.state = "done"
                        self._completed += 1
                        completed = self._completed
                        self._result_bytes += size
                        self._evict_history(keep=job.job_id)
                    if not job.spec.no_cache:
                        self.results.put(
                            job.corpus_digest, job.spec.fingerprint(), pairs,
                            meta={
                                "distinct": job.distinct,
                                "truncated": job.truncated,
                                "overflow_tokens": job.overflow_tokens,
                            },
                        )
                    obs.metric_inc("serve.jobs")
                    obs.metric_observe("serve.latency_ms", job.latency_ms())
        except Exception as e:  # noqa: BLE001 - jobs fail, daemon survives
            logger.exception("serve dispatch failed")
            self._fail_batch(jobs, structured_error(
                "dispatch_failed", f"{type(e).__name__}: {e}"
            ))
            return
        if self.warm is not None:
            # Latest-wins background generation: the dispatcher never
            # blocks on disk (io/snapshot.py).  Distance-based cadence,
            # not modulo: ``completed`` advances by batch size here and
            # by result-cache hits on handler threads, so the dispatcher
            # may never OBSERVE a multiple of warm_every — a modulo
            # check could skip marks forever and silently demote the
            # cadence to "clean shutdown only".  The cursor read+write
            # holds the lock (close() snapshots the generation counter
            # under it); the mark itself stays outside — it only enqueues
            # on the async writer.
            with self._lock:
                due = completed - self._warm_marked >= self.cfg.warm_every
                if due:
                    self._warm_marked = completed
            if due:
                self.warm.mark(completed)

    def _fail_batch(self, jobs: list[Job], error: dict) -> None:
        now = time.monotonic()
        with self._lock:
            for job in jobs:
                if job.state == "done":
                    continue  # demuxed before the failure: result stands
                # error before state: the state write is the lock-free
                # readers' publish barrier (same rule as the demux path).
                job.error = dict(error)
                job.finished_s = now
                job.state = "failed"
