"""Bounded job queue with admission control + per-tenant weighted fairness.

Admission control (the "reject-with-reason" half of the serve tier): the
queue holds at most ``max_queue`` jobs globally and ``tenant_quota`` per
tenant — a submit past either bound is REJECTED with a structured reason
(``queue_full`` / ``tenant_quota``), never silently dropped or unboundedly
buffered (an unbounded queue turns overload into OOM + unbounded p99).

Fairness is stride scheduling (Waldspurger & Weihl, OSDI '94) over
tenants: each tenant carries a virtual time ``vt``; dispatching one of
its jobs charges ``vt += cost / weight`` where cost is the job's shape
bucket (big jobs cost proportionally more of the tenant's share) and
weight is the job's declared weight.  The dispatcher always serves the
minimum-``vt`` tenant's oldest job, so a tenant flooding the queue only
ever gets its weighted share — it cannot starve the others.  A tenant
going idle and returning re-enters at ``max(own vt, min active vt)``: no
banking unused share into a later burst.

Batching hook: ``next_batch`` picks the fair head job, then COALESCES
further queued jobs with the same ``batch_key`` — same executable
fingerprint and same shape bucket (serve/cache.py) — in fair order up to
``max_batch``, each charged to its own tenant.  One engine dispatch then
serves the whole batch (engine.run_batch), which is what makes many tiny
jobs cheap without letting them jump the fairness queue.

Thread-safe: handler threads admit/cancel, the single dispatcher thread
pops; all state mutates under one condition variable.
"""

from __future__ import annotations

import threading
import time

from locust_tpu.serve.jobs import Job


class AdmitReject(Exception):
    """Admission refused; ``code`` is an ERROR_CODES entry."""

    def __init__(self, code: str, message: str):
        self.code = code
        super().__init__(message)


class FairScheduler:
    def __init__(
        self,
        max_queue: int = 64,
        max_batch: int = 8,
        tenant_quota: int | None = None,
    ):
        if max_queue < 1 or max_batch < 1:
            raise ValueError("max_queue and max_batch must be >= 1")
        self.max_queue = max_queue
        self.max_batch = max_batch
        # 0-disables convention (health_port 0 etc.): the CLI has no
        # None spelling for --tenant-quota, and a literal 0 would
        # reject every tenant's FIRST job — a daemon that starts
        # cleanly but can never accept work.
        if tenant_quota is not None and tenant_quota < 1:
            tenant_quota = None
        self.tenant_quota = tenant_quota
        self._cond = threading.Condition()
        self._pending: list[Job] = []  # submit order; fairness picks by vt
        # Backoff parking lot (docs/SERVING.md retry ladder): jobs
        # requeued after a failed dispatch wait out their not-before
        # time here, promoted into _pending by the dispatcher's next
        # poll.  Counted against max_queue and the tenant quota — a
        # retrying job still occupies its admission slot.
        self._delayed: list[tuple[float, Job]] = []
        self._vt: dict[str, float] = {}
        # Global virtual time: the vt of the most-behind tenant at each
        # dispatch, monotone.  It is the rejoin floor when the queue is
        # EMPTY — without it, a tenant joining an idle queue would enter
        # at 0 and then starve every returning tenant until their past
        # usage amortized (the inverse of the no-banked-share rule).
        self._global_vt = 0.0
        self._stopped = False
        self._paused = False
        self._admitted = 0
        self._rejected = 0
        self._dispatched = 0

    # ------------------------------------------------------------- admit

    def admit(self, job: Job) -> None:
        """Enqueue or raise ``AdmitReject`` with the structured reason."""
        with self._cond:
            if self._stopped:
                # Permanent, not transient: "queue_full" here would tell
                # a well-behaved client to back off and retry a daemon
                # that will never accept again.
                self._rejected += 1
                raise AdmitReject("shutting_down", "scheduler is shut down")
            occupied = len(self._pending) + len(self._delayed)
            if occupied >= self.max_queue:
                self._rejected += 1
                raise AdmitReject(
                    "queue_full",
                    f"queue full ({occupied}/{self.max_queue} "
                    "jobs pending); retry with backoff",
                )
            tenant = job.spec.tenant
            if self.tenant_quota is not None:
                mine = sum(
                    1 for j in self._pending if j.spec.tenant == tenant
                ) + sum(
                    1 for _, j in self._delayed if j.spec.tenant == tenant
                )
                if mine >= self.tenant_quota:
                    self._rejected += 1
                    raise AdmitReject(
                        "tenant_quota",
                        f"tenant {tenant!r} already has {mine} pending "
                        f"jobs (quota {self.tenant_quota})",
                    )
            if tenant not in self._vt or not any(
                j.spec.tenant == tenant
                for j in self._pending + [d[1] for d in self._delayed]
            ):
                # (Re)joining tenant: no banked share from idle time.
                active = [
                    self._vt[j.spec.tenant]
                    for j in self._pending
                    if j.spec.tenant in self._vt
                ]
                floor = min(active) if active else self._global_vt
                self._vt[tenant] = max(self._vt.get(tenant, 0.0), floor)
            self._pending.append(job)
            self._admitted += 1
            self._cond.notify_all()

    def requeue(self, job: Job, not_before: float = 0.0) -> bool:
        """Put an already-admitted job back for another dispatch attempt
        after ``not_before`` (monotonic).  Skips the admission caps — the
        job holds its slot from the original admit; rejecting a retry
        would double-charge the tenant.  False when the scheduler is
        stopped (the caller fails the job structured ``shutting_down``).
        """
        with self._cond:
            if self._stopped:
                return False
            tenant = job.spec.tenant
            if tenant not in self._vt:
                self._vt[tenant] = self._global_vt
            if not_before <= time.monotonic():
                self._pending.append(job)
            else:
                self._delayed.append((not_before, job))
            self._cond.notify_all()
            return True

    def expire(self, now: float) -> list[Job]:
        """Remove and return queued/retrying jobs whose deadline passed —
        the dispatcher's sweep turns them into structured
        ``deadline_exceeded`` answers (a job must never sit in the queue
        past a budget the client stopped waiting on)."""
        with self._cond:
            dead = [j for j in self._pending if j.expired(now)]
            for j in dead:
                self._pending.remove(j)
            dead_delayed = [
                (nb, j) for nb, j in self._delayed if j.expired(now)
            ]
            for item in dead_delayed:
                self._delayed.remove(item)
            return dead + [j for _, j in dead_delayed]

    # ----------------------------------------------------------- dispatch

    def _promote_ripe(self) -> None:
        """Move delayed jobs whose backoff expired into the dispatch
        pool.  Caller holds the condition."""
        if not self._delayed:
            return
        now = time.monotonic()
        ripe = [item for item in self._delayed if item[0] <= now]
        for item in ripe:
            self._delayed.remove(item)
            self._pending.append(item[1])

    def _fair_order(self) -> list[Job]:
        """Pending jobs in dispatch-fair order: tenants by vt (ties by
        name for determinism), submit order within a tenant."""
        order: dict[str, list[Job]] = {}
        for j in self._pending:
            order.setdefault(j.spec.tenant, []).append(j)
        tenants = sorted(order, key=lambda t: (self._vt.get(t, 0.0), t))
        out: list[Job] = []
        for t in tenants:
            out.extend(order[t])
        return out

    def next_batch(
        self, batch_key, timeout: float | None = None
    ) -> list[Job] | None:
        """Pop one fair, coalesced batch; None on timeout or shutdown.

        ``batch_key(job)`` maps a job to its compatibility key (same key
        = shares one compiled dispatch).  The head job is the fair pick;
        followers join in fair order only if their key matches.
        """
        batches = self.next_batches(batch_key, max_batches=1,
                                    timeout=timeout)
        return batches[0] if batches else None

    def next_batches(
        self, batch_key, max_batches: int = 1,
        timeout: float | None = None,
    ) -> list[list[Job]] | None:
        """Pop up to ``max_batches`` DISJOINT fair batches in one lock
        acquisition; None on timeout or shutdown (never ``[]``).

        The scale-out dispatcher's entry point (docs/SERVING.md): with a
        worker pool beneath it, the daemon asks for as many batches as
        it has free placement slots, so independent same-tick batches
        overlap across workers instead of serializing on one engine.
        Fairness is unchanged — each batch is picked exactly as
        ``next_batch`` would have picked it after the previous one's
        virtual-time charge, so the multi-batch pop equals N sequential
        single pops, minus the lock churn.
        """
        if max_batches < 1:
            raise ValueError("max_batches must be >= 1")
        with self._cond:
            self._promote_ripe()
            while (not self._pending or self._paused) and not self._stopped:
                if not self._cond.wait(timeout=timeout):
                    return None
                self._promote_ripe()
            if self._stopped or not self._pending or self._paused:
                # Stopped beats a non-empty queue: stop() must never be
                # answered with a fresh dispatch (close() is waiting on
                # the dispatcher with a bounded join; a cold TPU compile
                # here would blow it and race the warm-state flush).
                return None
            batches: list[list[Job]] = []
            while self._pending and len(batches) < max_batches:
                ordered = self._fair_order()
                head = ordered[0]
                key = batch_key(head)
                batch = [head]
                for j in ordered[1:]:
                    if len(batch) >= self.max_batch:
                        break
                    if batch_key(j) == key:
                        batch.append(j)
                for j in batch:
                    self._pending.remove(j)
                    w = max(j.spec.weight, 1e-6)
                    self._vt[j.spec.tenant] = (
                        self._vt.get(j.spec.tenant, 0.0) + j.bucket / w
                    )
                # The head was the most-behind tenant, so its charged vt
                # is the service time the system has actually reached
                # (within one stride) — the monotone clock idle joiners
                # floor at.
                self._global_vt = max(
                    self._global_vt, self._vt.get(head.spec.tenant, 0.0)
                )
                self._dispatched += len(batch)
                batches.append(batch)
            # Prune idle tenants whose vt is at/below the floor: their
            # rejoin would re-enter at the floor anyway, so the entry
            # carries no information — and tenant names are CLIENT
            # chosen, so an unpruned dict grows daemon memory (and every
            # stats reply) without bound.  Backoff-parked jobs count as
            # pending here: pruning a tenant whose only jobs are in
            # _delayed would re-enter it at vt 0.0 when they ripen — a
            # banked burst that wins every fair pick until it re-catches
            # the floor, the exact starvation this scheduler forbids.
            pending_tenants = {j.spec.tenant for j in self._pending} | {
                j.spec.tenant for _, j in self._delayed
            }
            for t in [
                t for t, v in self._vt.items()
                if t not in pending_tenants and v <= self._global_vt
            ]:
                del self._vt[t]
            return batches

    # ------------------------------------------------------------ control

    def cancel(self, job_id: str) -> Job | None:
        """Remove a still-queued job; returns it (caller marks the state)
        or None when it is not pending (unknown, running, or finished)."""
        with self._cond:
            for j in self._pending:
                if j.job_id == job_id:
                    self._pending.remove(j)
                    return j
            for item in self._delayed:
                if item[1].job_id == job_id:
                    self._delayed.remove(item)
                    return item[1]
            return None

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def drain(self) -> list:
        """Remove and return every pending job — the shutdown path.
        ``stop()`` makes ``next_batch`` answer None forever, so anything
        still queued would otherwise be abandoned in state "queued" with
        no structured answer.  Call after the dispatcher has exited."""
        with self._cond:
            drained = list(self._pending) + [j for _, j in self._delayed]
            self._pending.clear()
            self._delayed.clear()
            return drained

    def pause(self) -> None:
        """Hold dispatch (admission keeps working; jobs queue up) — the
        operator/test hook behind deterministic batch coalescing."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def count_rejection(self) -> None:
        """Fold an admission rejection decided OUTSIDE admit() (the
        daemon's aggregate byte cap) into the rejected stat — the
        counter must match the queue_full codes actually emitted, or an
        operator watching it concludes admission control never engaged
        while clients are being turned away."""
        with self._cond:
            self._rejected += 1

    def depth(self) -> int:
        """Pending + backoff-parked job count — the dispatcher's
        idle-tick probe (stats() builds per-tenant dicts; too heavy for
        4x/second)."""
        with self._cond:
            return len(self._pending) + len(self._delayed)

    def stats(self) -> dict:
        with self._cond:
            per_tenant: dict[str, int] = {}
            for j in self._pending:
                per_tenant[j.spec.tenant] = per_tenant.get(j.spec.tenant, 0) + 1
            return {
                "depth": len(self._pending),
                "retrying": len(self._delayed),
                "max_queue": self.max_queue,
                "max_batch": self.max_batch,
                "admitted": self._admitted,
                "rejected": self._rejected,
                "dispatched": self._dispatched,
                "pending_by_tenant": per_tenant,
                "virtual_time": dict(self._vt),
            }
