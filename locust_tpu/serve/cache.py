"""The serve tier's two cache layers + warm-state persistence.

**Warm-executable cache** (``ExecutableCache``): compiled engine programs
keyed by ``(workload, EngineConfig.fingerprint(), shape bucket)``.  The
20-40 s TPU compile (CLAUDE.md) is the serve tier's whole reason to
exist: a repeat job — or ANY job whose corpus rounds into an
already-compiled shape bucket — must skip compilation.  Buckets round a
job's block count up a power-of-two ladder (``bucket_blocks``), so small
jobs of different sizes share one executable at the cost of folding a few
zero blocks (zero lines emit nothing; identical results by the engine's
existing padding semantics).  Engines are LRU-bounded: each holds device
buffers and a jit cache, so an unbounded config zoo would hold the
accelerator's memory hostage.

**Result cache** (``ResultCache``): finished tables keyed by
``(corpus digest, spec fingerprint)``.  A repeat of the SAME bytes under
the SAME program is answered without touching the engine at all.
Explicit invalidation only (the ``invalidate`` command / submit flag):
the daemon cannot know when a client's corpus path contents changed
semantics, so staleness is the client's call — but the key includes the
corpus sha256, so different BYTES can never alias.

**Warm-state persistence** (``WarmState``): the result cache (and cache
counters) survive daemon restarts by riding the SAME bounded async
snapshot machinery the streaming tier trusts (io/snapshot.py):
``AsyncCheckpointWriter`` latest-wins generations off the dispatch path,
``finalize_snapshot``'s tmp-write + atomic rename (which also carries the
``io.ckpt_write``/``io.checkpoint`` chaos sites — the serve warm file is
chaos-covered for free).  A missing/corrupt/version-skewed warm file
costs a cold start, never a crash and never a wrong answer (results are
re-validated by their content-addressed keys).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading

from locust_tpu.config import EngineConfig
from locust_tpu.serve.jobs import (
    PLAN_WORKLOAD,
    WORKLOADS,
    JobSpec,
    pairs_bytes,
)

logger = logging.getLogger("locust_tpu")

# Warm-file format version: bumped on layout changes so an old daemon's
# file is a clean cold start for a new one, not a parse crash.
WARM_VERSION = 1
WARM_FILE = "serve_warm.json"


def bucket_blocks(n_blocks: int) -> int:
    """Shape-bucket ladder: block counts round UP to the next power of
    two, so jobs of nearby sizes share one compiled executable (the
    padding cost is bounded by <2x blocks, and padded blocks are all-NUL
    rows the map stage emits nothing for)."""
    n = max(1, int(n_blocks))
    b = 1
    while b < n:
        b <<= 1
    return b


def _resolve_workload(name: str):
    """Lazy map-fn import (jax enters the process here, not at module
    import): 'pkg.mod:attr' -> (map_fn, combine)."""
    path, combine = WORKLOADS[name]
    mod_name, _, attr = path.partition(":")
    import importlib

    return getattr(importlib.import_module(mod_name), attr), combine


class ExecutableCache:
    """Warm engines + compiled-shape tracking, hit/miss accounted.

    A LOOKUP is a hit iff the engine for ``(workload, cfg fingerprint)``
    exists AND the exact batched dispatch shape ``(njobs, bucket)`` has
    run before (jax's jit cache then reuses the compiled executable — no
    trace, no compile).  Anything else is a miss that pays the build
    and/or compile; the stats make the distinction auditable
    (tests/test_serve.py pins that a repeat job reports ``compiles``
    unchanged).
    """

    def __init__(self, max_engines: int = 4):
        if max_engines < 1:
            raise ValueError("max_engines must be >= 1")
        self.max_engines = max_engines
        self._lock = threading.Lock()
        self._engines: dict[tuple, object] = {}  # key -> engine (LRU order)
        self._shapes: set[tuple] = set()         # (key, njobs, bucket)
        self.hits = 0
        self.misses = 0
        self.builds = 0     # engines constructed
        self.compiles = 0   # batched shapes first-dispatched
        self.evictions = 0

    @staticmethod
    def engine_key(spec: JobSpec) -> tuple:
        if spec.plan is not None:
            # Plan jobs: the compiled executable is the (plan, config)
            # pair, so the plan fingerprint IS the workload half of the
            # key — two different pipelines can never share a warm
            # engine, and a repeat of the same plan always hits
            # (docs/PLAN.md).
            return (
                PLAN_WORKLOAD, spec.plan_fingerprint(),
                spec.cfg.fingerprint(),
            )
        return (spec.workload, spec.cfg.fingerprint())

    @staticmethod
    def fold_node_key(node_fp: str, cfg_fp: str) -> tuple:
        """The warm key for a distributed plan MAP STAGE's fold engine:
        (plan-node closure fingerprint, config fingerprint).  The
        closure fp (``Plan.node_fingerprint``) is node-id independent,
        so an alpha-renamed resubmit of the same pipeline lands on the
        same warm executable — and the shape bucket rides the ledger
        exactly as for whole jobs, so a repeat distributed plan skips
        the per-worker recompile (docs/SERVING.md, docs/PLAN.md
        "Distributed execution")."""
        return (PLAN_WORKLOAD, f"node:{node_fp}", cfg_fp)

    def _lookup_key(self, key: tuple, njobs: int, bucket: int, build):
        """(engine, hit) for one warm key — builds via ``build()`` on a
        miss; the SHAPE is marked compiled only by ``_mark_key`` after
        the dispatch ran (a dispatch that dies must not poison the
        ledger as warm)."""
        with self._lock:
            eng = self._engines.pop(key, None)
            if eng is not None:
                self._engines[key] = eng  # LRU touch
                if (key, njobs, bucket) in self._shapes:
                    self.hits += 1
                    return eng, True
                self.misses += 1
                return eng, False
            self.misses += 1
        # Build OUTSIDE the lock: engine construction imports/compiles
        # nothing device-side yet, but it is not free and must not block
        # concurrent lookups of already-warm keys.
        built = build()
        with self._lock:
            eng = self._engines.get(key)
            if eng is None:  # we won the (benign) build race
                eng = built
                self._engines[key] = eng
                self.builds += 1
                while len(self._engines) > self.max_engines:
                    evicted_key = next(iter(self._engines))
                    self._engines.pop(evicted_key)
                    self._shapes = {
                        s for s in self._shapes if s[0] != evicted_key
                    }
                    self.evictions += 1
            return eng, False

    def _mark_key(self, key: tuple, njobs: int, bucket: int) -> None:
        with self._lock:
            shape = (key, njobs, bucket)
            if shape not in self._shapes:
                self._shapes.add(shape)
                self.compiles += 1

    def lookup(self, spec: JobSpec, njobs: int, bucket: int):
        """(engine, hit) — builds the engine on a miss (see
        ``_lookup_key`` for the ledger discipline)."""

        def build():
            if spec.plan is not None:
                # Plan jobs hold a CompiledPlan instead of a bare
                # engine: same LRU, same shape ledger, same warm-hit
                # economics (the compiled plan keeps its underlying
                # engine's jit caches).
                from locust_tpu.plan import from_json
                from locust_tpu.plan.compile import compile_plan

                return compile_plan(from_json(spec.plan), spec.cfg)
            from locust_tpu.engine import MapReduceEngine

            map_fn, combine = _resolve_workload(spec.workload)
            return MapReduceEngine(
                spec.cfg, map_fn=map_fn, combine=combine
            )

        return self._lookup_key(self.engine_key(spec), njobs, bucket,
                                build)

    def lookup_fold_node(self, node_fp: str, cfg, njobs: int,
                         bucket: int):
        """(engine, hit) for a distributed plan map stage, keyed by the
        fold node's CLOSURE fingerprint (``fold_node_key``).  Only the
        wordcount fold dispatches device-side on workers (the composite
        folds shuffle host-built pair tables), so the engine is always
        the wordcount map/combine under the stage's config."""

        def build():
            from locust_tpu.engine import MapReduceEngine

            map_fn, combine = _resolve_workload("wordcount")
            return MapReduceEngine(cfg, map_fn=map_fn, combine=combine)

        return self._lookup_key(
            self.fold_node_key(node_fp, cfg.fingerprint()), njobs,
            bucket, build,
        )

    def mark_compiled(self, spec: JobSpec, njobs: int, bucket: int) -> None:
        self._mark_key(self.engine_key(spec), njobs, bucket)

    def mark_compiled_fold_node(self, node_fp: str, cfg_fp: str,
                                njobs: int, bucket: int) -> None:
        self._mark_key(self.fold_node_key(node_fp, cfg_fp), njobs,
                       bucket)

    def warm_shapes(self) -> list[list]:
        """Every compiled shape as ``[workload, cfg_fp, njobs, bucket]``
        rows — the worker's ``serve_stats`` reply (the pool's warm-cache
        RPC seeds its affinity map from this, serve/pool.py)."""
        with self._lock:
            return [
                [key[0], key[1], njobs, bucket]
                for (key, njobs, bucket) in sorted(self._shapes)
            ]

    def stats(self) -> dict:
        with self._lock:
            # Megakernel visibility: how many warm engines actually run
            # the fused kernel vs were demoted at construction (stats is
            # where an operator finds out a fused-mode daemon is
            # silently folding like hasht — the engines log the reason
            # once, this keeps it visible after the log rotates).  Plan
            # executables hold their engine as ``_engine`` (None until
            # the first fold builds it).
            fused_on = fused_demoted = 0
            for eng in self._engines.values():
                e = getattr(eng, "_engine", eng)
                if getattr(e, "_fused_kernel_on", False):
                    fused_on += 1
                if getattr(e, "_fused_demoted", False):
                    fused_demoted += 1
            return {
                "engines": len(self._engines),
                "shapes": len(self._shapes),
                "hits": self.hits,
                "misses": self.misses,
                "builds": self.builds,
                "compiles": self.compiles,
                "evictions": self.evictions,
                "fused_on": fused_on,
                "fused_demoted": fused_demoted,
            }


class ResultCache:
    """Finished tables keyed by (corpus sha256, spec fingerprint)."""

    def __init__(self, max_entries: int = 256,
                 max_bytes: int = 256 << 20):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = max_entries
        # Entry COUNT alone cannot bound memory: 256 entries of
        # multi-MB pair lists is GBs of retention, the same
        # overload-must-reject-not-OOM class as the daemon's queue
        # byte cap.  LRU eviction runs on whichever cap trips first.
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: dict[tuple[str, str], dict] = {}  # LRU order
        self._bytes = 0  # sum of entry "bytes" estimates
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def get(self, digest: str, spec_fp: str) -> list | None:
        hit = self.get_with_meta(digest, spec_fp)
        return None if hit is None else hit[0]

    def get_with_meta(
        self, digest: str, spec_fp: str
    ) -> tuple[list, dict] | None:
        """(pairs, meta) on a hit — meta carries the ORIGINAL run's
        distinct/truncated/overflow_tokens so a replayed lossy result
        stays flagged lossy (daemon submit path)."""
        with self._lock:
            ent = self._entries.pop((digest, spec_fp), None)
            if ent is None:
                self.misses += 1
                return None
            self._entries[(digest, spec_fp)] = ent  # LRU touch
            ent["hits"] += 1
            self.hits += 1
            return ent["pairs"], dict(ent["meta"])

    def put(self, digest: str, spec_fp: str, pairs: list,
            meta: dict | None = None) -> None:
        size = pairs_bytes(pairs)
        with self._lock:
            old = self._entries.pop((digest, spec_fp), None)
            if old is not None:
                self._bytes -= old["bytes"]
            self._entries[(digest, spec_fp)] = {
                "pairs": list(pairs),
                "bytes": size,
                "hits": 0,
                "meta": dict(meta or {}),
            }
            self._bytes += size
            while (len(self._entries) > self.max_entries
                   or self._bytes > self.max_bytes):
                if len(self._entries) == 1:
                    break  # a single oversized entry still serves hits
                ent = self._entries.pop(next(iter(self._entries)))
                self._bytes -= ent["bytes"]

    def invalidate(self, digest: str | None = None,
                   spec_fp: str | None = None) -> int:
        """Drop matching entries (both None = everything); returns count."""
        with self._lock:
            doomed = [
                k for k in self._entries
                if (digest is None or k[0] == digest)
                and (spec_fp is None or k[1] == spec_fp)
            ]
            for k in doomed:
                self._bytes -= self._entries.pop(k)["bytes"]
            self.invalidations += len(doomed)
            return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
            }

    # ------------------------------------------------- (de)serialization

    def dump(self) -> list[dict]:
        # Shallow snapshot under the lock, base64 OUTSIDE it: the encode
        # is O(total cached pairs) and must not stall concurrent lookups
        # (pairs lists are never mutated after put(), so reading them
        # lock-free is safe).
        with self._lock:
            snapshot = [
                (k, ent["pairs"], dict(ent["meta"]))
                for k, ent in self._entries.items()
            ]
        return [
            {
                "digest": k[0],
                "spec_fp": k[1],
                "pairs": [
                    [base64.b64encode(key).decode(), int(v)]
                    for key, v in pairs
                ],
                "meta": meta,
            }
            for k, pairs, meta in snapshot
        ]

    def load(self, rows: list[dict]) -> int:
        n = 0
        for row in rows:
            try:
                pairs = [
                    (base64.b64decode(k), int(v)) for k, v in row["pairs"]
                ]
                self.put(str(row["digest"]), str(row["spec_fp"]), pairs,
                         meta=row.get("meta"))
                n += 1
            except (KeyError, TypeError, ValueError) as e:
                # One rotten entry must not cost the warm start.
                logger.warning("serve warm entry skipped (%s)", e)
        return n


class SubPlanCache:
    """Per-EDGE fold results keyed by (closure fingerprint, config
    fingerprint, corpus sha256) — the plan optimizer's cross-tenant
    sub-plan cache (docs/PLAN.md "Optimizer").

    Generalizes ``ResultCache``'s byte-identity discipline from whole-
    job to per-edge: the closure fingerprint
    (``Plan.node_fingerprint``) is node-id independent, so two tenants
    whose plans spell the same corpus + tokenize prefix under different
    names share the entry.  Same bounding stance as ``ResultCache``
    (byte-capped LRU, count cap, one oversized entry still serves),
    same explicit invalidation.  IN-MEMORY ONLY by design: WAL replay
    after a restart recomputes from a cold cache and must reproduce the
    same bytes — the optimizer's identity contract, pinned by tests.

    Entries are dicts built by ``plan/compile._RunCtx`` (value + loss
    accounting + ``corpus_len``/``corpus_sha``/``n_lines`` + a
    ``bytes`` size estimate).  ``prefix_candidates`` feeds the
    incremental-refold probe: entries under the same (closure, config)
    identity, newest-corpus first, whose corpus may be a verified
    prefix of a grown resubmit (``optimize.incremental_delta`` does the
    hash verification — nothing here trusts a client).
    """

    def __init__(self, max_entries: int = 256,
                 max_bytes: int = 128 << 20):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        # key: (closure_fp, cfg_fp, corpus_sha) -> entry dict (LRU order)
        self._entries: dict[tuple[str, str, str], dict] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.incremental_hits = 0
        self.invalidations = 0
        # Last incremental merge's block accounting (bench/check
        # evidence: the delta refold must touch FEWER blocks than a
        # full one).
        self.last_delta_blocks = 0
        self.last_total_blocks = 0

    def get(self, closure_fp: str, cfg_fp: str,
            corpus_sha: str) -> dict | None:
        with self._lock:
            ent = self._entries.pop((closure_fp, cfg_fp, corpus_sha),
                                    None)
            if ent is None:
                self.misses += 1
                return None
            self._entries[(closure_fp, cfg_fp, corpus_sha)] = ent
            self.hits += 1
            return ent

    def put(self, closure_fp: str, cfg_fp: str, corpus_sha: str,
            entry: dict) -> None:
        size = int(entry.get("bytes") or 0)
        key = (closure_fp, cfg_fp, corpus_sha)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= int(old.get("bytes") or 0)
            self._entries[key] = entry
            self._bytes += size
            while (len(self._entries) > self.max_entries
                   or self._bytes > self.max_bytes):
                if len(self._entries) == 1:
                    break  # one oversized entry still serves hits
                ent = self._entries.pop(next(iter(self._entries)))
                self._bytes -= int(ent.get("bytes") or 0)

    def prefix_candidates(self, closure_fp: str,
                          cfg_fp: str) -> list[dict]:
        """Entries under (closure_fp, cfg_fp) regardless of corpus —
        largest corpus first, so the incremental probe tries the
        longest verified prefix (smallest delta) before older
        generations."""
        with self._lock:
            ents = [
                ent for (fp, cf, _sha), ent in self._entries.items()
                if fp == closure_fp and cf == cfg_fp
            ]
        return sorted(
            ents, key=lambda e: int(e.get("corpus_len") or 0),
            reverse=True,
        )

    def record_incremental(self, delta_blocks: int,
                           total_blocks: int) -> None:
        with self._lock:
            self.incremental_hits += 1
            self.last_delta_blocks = int(delta_blocks)
            self.last_total_blocks = int(total_blocks)

    def invalidate(self, corpus_sha: str | None = None) -> int:
        """Drop entries for one corpus (None = everything); returns the
        count.  Rides the daemon's existing invalidation surface: an
        ``--invalidate`` submit or an explicit invalidate for a corpus
        digest drops the per-edge entries too — a tenant asking for a
        fresh recompute must not be answered from a sub-plan edge."""
        with self._lock:
            doomed = [
                k for k in self._entries
                if corpus_sha is None or k[2] == corpus_sha
            ]
            for k in doomed:
                self._bytes -= int(self._entries.pop(k).get("bytes") or 0)
            self.invalidations += len(doomed)
            return len(doomed)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "incremental_hits": self.incremental_hits,
                "invalidations": self.invalidations,
                "last_delta_blocks": self.last_delta_blocks,
                "last_total_blocks": self.last_total_blocks,
            }


class WarmState:
    """Persist the result cache across daemon restarts, asynchronously.

    ``mark(generation)`` hands a serialize-closure to the bounded
    latest-wins ``AsyncCheckpointWriter`` (io/snapshot.py) — the dispatch
    loop never blocks on disk; ``finalize_snapshot`` publishes atomically
    through the existing ``io.ckpt_write``/``io.checkpoint`` chaos sites.
    ``load()`` at daemon startup restores entries; any failure is a cold
    start, logged, never fatal.
    """

    def __init__(self, warm_dir: str, results: ResultCache):
        # Lazy: locust_tpu.io pulls jax in via serde at package import,
        # and this module must stay importable without it — the thin
        # client (submit/stats/shutdown against a remote daemon) must
        # not pay a jax init, which can HANG on a wedged axon tunnel
        # (CLAUDE.md).  Only the daemon constructs a WarmState.
        from locust_tpu.io.snapshot import (
            AsyncCheckpointWriter,
            finalize_snapshot,
        )

        self._finalize_snapshot = finalize_snapshot
        self.path = os.path.join(warm_dir, WARM_FILE)
        self._results = results
        os.makedirs(warm_dir, exist_ok=True)
        self._writer = AsyncCheckpointWriter(name="serve-warm-writer")

    def load(self) -> int:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
        except OSError:
            return 0  # no warm file: cold start
        except ValueError as e:
            logger.warning(
                "serve warm state %s unreadable (%s); cold start",
                self.path, e,
            )
            return 0
        if not isinstance(doc, dict) or doc.get("version") != WARM_VERSION:
            logger.warning(
                "serve warm state %s has version %r (want %d); cold start",
                self.path, getattr(doc, "get", lambda _: None)("version"),
                WARM_VERSION,
            )
            return 0
        n = self._results.load(doc.get("results") or [])
        logger.info("serve warm state: restored %d cached result(s)", n)
        return n

    def mark(self, generation: int) -> None:
        # The whole serialize — dump() included — runs in the write
        # closure ON THE WRITER THREAD: encoding every cached pair is
        # O(total cached bytes) and would otherwise bill the dispatch
        # loop this layer promises never to block.  The file then
        # carries the cache state at WRITE time (fresher than mark time,
        # which is fine: it is a cache, and latest-wins already skips
        # lapped generations).
        def write():
            doc = {"version": WARM_VERSION, "generation": generation,
                   "results": self._results.dump()}
            tmp = f"{self.path}.tmp.{generation}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            self._finalize_snapshot(tmp, self.path, generation=generation)

        self._writer.submit(generation, write)

    def flush(self) -> None:
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()

    def stats(self) -> dict:
        return dict(self._writer.stats(), path=self.path)
