"""Scale-out serve dispatch: place batches on distributor workers.

The serve daemon's dispatcher is production-shaped (admission, fairness,
WAL, retries, breaker) but without this module every batch folds on the
daemon's single LOCAL engine — aggregate throughput is capped at one
engine while the distributor's hardened worker tier (binary HMAC'd
frames, persistent connections, straggler quarantine) idles beneath it.
``WorkerPool`` is the placement layer between them (docs/SERVING.md
"Scale-out dispatch"):

  * **registration + health**: a fixed worker roster, each with ONE
    persistent authenticated connection (distributor/protocol.py frames,
    the same wire the map/fetch plane rides) and the master's
    ``WorkerHealth`` exponential-backoff quarantine — a worker that
    kills a dispatch backs off and is re-probed by the next attempt;
  * **cache-affinity placement**: every worker runs its own warm
    ``ExecutableCache`` (serve/cache.py), and a cold placement costs the
    20-40 s TPU compile (CLAUDE.md), so ``place()`` prefers the worker
    that already holds the warm executable for the batch's
    ``(workload, config fingerprint, shape bucket)`` key — affinity IS
    the throughput lever — and spills over to the least-loaded healthy
    worker only when the affine one is saturated (``max_inflight``);
  * **content-addressed corpus spill**: batch corpora move through the
    write-once ``<sha256>.bin`` spill files the job journal already
    keeps (serve/journal.py) instead of re-serializing per worker — a
    worker reads the spill path and VERIFIES the sha before folding, so
    a stale or torn spill is a structured error, never a silent wrong
    answer.  Workers must share the spill filesystem (loopback or a
    shared mount); there is no inline-bytes fallback on this path.

The floor is always the local engine: ``place()`` returning ``None``
(pool saturated, everyone quarantined, placement fault injected) routes
the batch to the daemon's own dispatch path, and a worker dying
mid-batch feeds the jobs back through the daemon's retry/bisection
ladder onto the survivors — never a dead daemon, never a lost job.

Chaos: the ``serve.place`` fault site fires inside ``place()`` ("error"
= placement failure, the batch falls back to the local engine and the
result stays byte-identical; "delay" = a slow placement decision).
Telemetry: the ``serve.place`` span wraps each placement decision and
``serve.affinity_hits`` counts warm-worker placements (closed obs
registry, R009).
"""

from __future__ import annotations

import hashlib
import logging
import os
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from locust_tpu import obs
from locust_tpu.distributor import protocol
from locust_tpu.utils import faultplan

logger = logging.getLogger("locust_tpu")


class PoolDispatchError(RuntimeError):
    """A worker dispatch failed (connection death, structured worker
    error, injected fault).  The daemon's retry ladder absorbs it.
    ``code`` carries the worker's structured reason when it answered
    one — ``stale_epoch`` is the fencing rejection a demoted zombie
    primary must react to, not merely retry (docs/SERVING.md)."""

    def __init__(self, message: str, code: str | None = None,
                 epoch: int | None = None, lost_split: int | None = None,
                 lost_epoch: int | None = None):
        self.code = code
        self.epoch = epoch  # the rejecting side's fencing epoch, if sent
        # A reduce stage naming the map split whose partition input it
        # lost: the plan coordinator recomputes exactly that split
        # (docs/PLAN.md "Distributed execution"), not the whole plan.
        self.lost_split = lost_split
        # An iterate sweep naming the EPOCH whose shard partition it
        # lost (lost_split then names the shard): the coordinator
        # recomputes that (epoch, shard) stage from the epoch before
        # it, not the whole iteration history.
        self.lost_epoch = lost_epoch
        super().__init__(message)


def parse_worker_addr(spec) -> tuple[str, int]:
    """'host:port' (or an ``(host, port)`` pair) -> validated tuple."""
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        return str(spec[0]), int(spec[1])
    host, _, port = str(spec).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"worker address {spec!r} is not host:port")
    return host, int(port)


class PoolWorker:
    """One pool member: address + its persistent connection.

    The connection is lazily dialed and serialized under ``_conn_lock``
    (the worker answers frames strictly in order, so one RPC at a time
    per connection); a failed RPC closes it and the next use redials.
    """

    def __init__(self, idx: int, addr: tuple[str, int]):
        self.idx = idx
        self.addr = addr
        self.name = f"{addr[0]}:{addr[1]}"
        self._conn: socket.socket | None = None
        self._conn_lock = threading.Lock()

    def _connect(self, timeout: float) -> socket.socket:
        """Dial (or reuse) the persistent connection.  Caller holds
        ``_conn_lock``."""
        if self._conn is None:
            faultplan.check_connect(self.addr[0], self.addr[1])
            self._conn = socket.create_connection(self.addr, timeout=timeout)
        return self._conn

    def _drop_conn(self) -> None:
        """Close the connection (broken peer).  Caller holds
        ``_conn_lock``."""
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def rpc(self, req: dict, secret: bytes, timeout: float) -> dict:
        """One request/reply on the persistent connection."""
        with self._conn_lock:
            try:
                sock = self._connect(timeout)
                sock.settimeout(timeout)
                protocol.send_frame(sock, req, secret)
                return protocol.recv_frame(sock, secret)
            except (OSError, ConnectionError, protocol.ProtocolError):
                self._drop_conn()
                raise

    def close(self) -> None:
        # Deliberately NOT under _conn_lock: an inflight RPC holds that
        # lock for up to rpc_timeout, and close() is the call that must
        # CUT such an RPC — closing a socket from another thread
        # unblocks its pending recv (the RPC then fails onto the retry
        # ladder and drops the connection itself).  Waiting politely
        # here stalled daemon shutdown behind a blackholed worker.
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass


class WorkerPool:
    """Placement + dispatch across serve-capable distributor workers.

    Thread-safe: the dispatcher thread places, executor threads dispatch
    and release, ``close()`` may race both — all shared state (inflight
    depths, warm-key map, counters, the closed flag) mutates under one
    lock; per-worker sockets serialize under their own connection locks.
    """

    def __init__(
        self,
        workers,
        secret: bytes,
        spill_dir: str,
        max_inflight: int = 1,
        rpc_timeout: float = 600.0,
        spill_cap_bytes: int | None = None,
        epoch_fn=None,
    ):
        if not workers:
            raise ValueError("WorkerPool needs at least one worker address")
        if not secret:
            raise ValueError("WorkerPool requires the shared secret")
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.secret = secret
        self.spill_dir = spill_dir
        self.max_inflight = max_inflight
        self.rpc_timeout = rpc_timeout
        # Byte cap for a POOL-OWNED spill dir (the daemon passes one
        # when no journal owns the dir): without it a long-running
        # daemon's distinct-corpus stream grows the dir until the disk
        # fills — the journal-backed dir has compaction GC, this is the
        # ownerless dir's substitute.  None = someone else GCs.
        self.spill_cap_bytes = spill_cap_bytes
        # Fencing (docs/SERVING.md "High availability"): when set, every
        # serve_batch RPC is stamped with the daemon's promotion epoch
        # (protocol.EPOCH_KEY) so a worker that has served a newer
        # primary rejects a fenced-out zombie's dispatch structured.
        self.epoch_fn = epoch_fn
        self._spill_gc_lock = threading.Lock()
        self.workers = [
            PoolWorker(i, parse_worker_addr(w)) for i, w in enumerate(workers)
        ]
        os.makedirs(spill_dir, exist_ok=True)
        # Lazy: master.py pulls jax through io.loader, and the serve
        # package is pinned jax-free at import (a thin control-plane
        # client must never pay — or hang on — a jax init, CLAUDE.md).
        # Only a daemon that actually configured workers builds a pool.
        from locust_tpu.distributor.master import WorkerHealth

        self.health = WorkerHealth(len(self.workers))
        self._lock = threading.Lock()
        self._inflight = [0] * len(self.workers)
        # affinity key -> worker idxs that hold it compiled.  A SET, not
        # one owner: repeat small jobs pack onto warm workers instead of
        # spraying cold compiles across the roster, but once several
        # workers are warm the load spreads across ALL of them — a
        # single-owner map was measured serializing the whole stream on
        # one worker's connection while its warm siblings idled.
        self._warm: dict[tuple, set[int]] = {}
        self._closed = False
        self._placements = [0] * len(self.workers)
        self._affinity_hits = 0
        self._spill_overs = 0
        self._place_fallbacks = 0
        self._dispatch_failures = 0
        # Dispatch executor: capacity-bounded — place() reserves a slot
        # before submit, so queued-but-unrunnable dispatches cannot pile
        # up.  Shut down (bounded) in close(), R012.
        self._executor = ThreadPoolExecutor(
            max_workers=len(self.workers) * max_inflight,
            thread_name_prefix="serve-pool",
        )

    # ---------------------------------------------------------- placement

    def capacity(self) -> int:
        return len(self.workers) * self.max_inflight

    def free_slots(self) -> int:
        """Open placement slots on PLACEABLE workers only: the dispatcher
        sizes its multi-batch pop by this, and counting quarantined
        workers' slots would pop batches that can only pile up
        serialized on the local floor."""
        with self._lock:
            if self._closed:
                return 0
            return sum(
                max(0, self.max_inflight - self._inflight[i])
                for i in range(len(self.workers))
                if self._placeable(i)
            )

    def preferred(self, key: tuple) -> tuple | None:
        """The warm workers for an affinity key (sorted name tuple, or
        None) — introspection for tests/operators; the placement
        decision itself lives in ``place()`` where load is known."""
        with self._lock:
            warm = self._warm.get(key)
            if not warm:
                return None
            return tuple(sorted(self.workers[i].name for i in warm))

    def _placeable(self, idx: int) -> bool:
        """Caller holds self._lock."""
        if self._inflight[idx] >= self.max_inflight:
            return False
        # Quarantined workers sit out their backoff; a due probe rides a
        # real dispatch (success un-quarantines), the master's stance.
        return not self.health.quarantined(idx)

    def place(self, key: tuple, exclude: set[int] | None = None):
        """Reserve a placement for one batch with affinity key ``key``.

        Returns the reserved ``PoolWorker`` (caller MUST ``release`` it)
        or None — the local-engine floor.  Policy: the warm (affine)
        worker when it has a free slot; otherwise the least-loaded
        placeable worker (spill-over); None when the pool is saturated,
        fully quarantined, closed, or the placement fault fires.
        """
        with obs.span("serve.place"):
            rule = faultplan.fire("serve.place", key=str(key))
            if rule is not None:
                if rule.action == "delay":
                    time.sleep(rule.delay_s)
                else:
                    # Placement failure: the batch falls back to the
                    # local engine — byte-identical, never an error the
                    # client sees.
                    with self._lock:
                        self._place_fallbacks += 1
                    return None
            with self._lock:
                if self._closed:
                    return None
                warm = self._warm.get(key) or ()
                candidates = [
                    i for i in range(len(self.workers))
                    if self._placeable(i)
                    and not (exclude and i in exclude)
                ]
                if not candidates:
                    self._place_fallbacks += 1
                    return None
                warm_cands = [i for i in candidates if i in warm]
                if warm_cands:
                    # Affinity: the least-loaded WARM worker — packs
                    # onto compiled executables without serializing the
                    # stream on a single warm worker while its warm
                    # siblings idle.  Ties by index for determinism.
                    idx = min(
                        warm_cands, key=lambda i: (self._inflight[i], i)
                    )
                    self._affinity_hits += 1
                    obs.metric_inc("serve.affinity_hits")
                else:
                    # Spill-over: every warm worker is saturated or
                    # quarantined — the queue must not block behind
                    # them, so the least-loaded cold candidate pays the
                    # compile.
                    idx = min(
                        candidates, key=lambda i: (self._inflight[i], i)
                    )
                    if warm:
                        self._spill_overs += 1
                self._inflight[idx] += 1
                self._placements[idx] += 1
                return self.workers[idx]

    def release(self, worker: PoolWorker) -> None:
        with self._lock:
            self._inflight[worker.idx] = max(
                0, self._inflight[worker.idx] - 1
            )

    def mark_warm(self, worker: PoolWorker, key: tuple) -> None:
        with self._lock:
            self._warm.setdefault(key, set()).add(worker.idx)

    # ----------------------------------------------------------- dispatch

    def submit(self, fn, *args):
        """Run ``fn`` on the pool's dispatch executor (the daemon's
        remote-dispatch path rides this so same-tick batches overlap)."""
        return self._executor.submit(fn, *args)

    def spill(self, sha: str, corpus: bytes) -> str:
        """Content-addressed write-once corpus spill (same layout as the
        journal's: ``<sha>.bin``, tmp + atomic rename).  Lock-free on
        purpose: a sha already on disk IS the bytes by construction and
        two concurrent writers race benignly through distinct tmp names
        into one atomic rename — holding the pool lock here would gate
        the whole placement plane on corpus disk I/O.  (GC coordination
        is the journal's own concern: pool spills always belong to LIVE
        jobs, which its compaction never sweeps.)"""
        path = os.path.join(self.spill_dir, f"{sha}.bin")
        if not os.path.exists(path):
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(corpus)
            os.replace(tmp, path)
            self._gc_spill()
        return path

    def _gc_spill(self) -> None:
        """Evict oldest spills past ``spill_cap_bytes`` (pool-owned dirs
        only).  Evicting a spill a dispatch is mid-reading is safe:
        the worker's sha check fails structured, and the retry re-spills
        from the daemon's still-buffered corpus bytes."""
        if self.spill_cap_bytes is None:
            return
        with self._spill_gc_lock:
            try:
                entries = []
                total = 0
                for name in os.listdir(self.spill_dir):
                    if not name.endswith(".bin"):
                        continue
                    p = os.path.join(self.spill_dir, name)
                    st = os.stat(p)
                    entries.append((st.st_mtime, st.st_size, p))
                    total += st.st_size
                entries.sort()
                for _mt, size, p in entries:
                    if total <= self.spill_cap_bytes:
                        break
                    os.remove(p)
                    total -= size
            except OSError:  # racing removals / dir vanishing at close
                pass

    def dispatch(
        self,
        worker: PoolWorker,
        workload: str,
        config: dict,
        bucket: int,
        jobs: list[dict],
        corpora: dict[str, bytes],
    ) -> dict:
        """One serve batch on ``worker``; returns the worker's reply —
        ``results`` holds per-job dicts (``job_id``/``pairs``/
        ``distinct``/``truncated``/``overflow_tokens``) in request
        order, ``warm`` whether the worker's executable was warm.

        Raises ``PoolDispatchError`` on ANY failure (dead worker,
        structured worker error, short reply) after marking the worker's
        health — the caller feeds the jobs back through the retry
        ladder.  A success clears the worker's quarantine slate.
        """
        for sha, data in corpora.items():
            self.spill(sha, data)
        req = {
            "cmd": "serve_batch",
            "workload": workload,
            "config": dict(config or {}),
            "bucket": int(bucket),
            "jobs": jobs,
            "spill_dir": self.spill_dir,
        }
        if self.epoch_fn is not None:
            req[protocol.EPOCH_KEY] = int(self.epoch_fn())
        try:
            reply = worker.rpc(req, self.secret, self.rpc_timeout)
        except Exception as e:
            self._dispatch_failed(
                worker,
                f"dispatch died ({type(e).__name__}: {e})",
                cause=e,
            )
        if reply.get("status") != "ok":
            self._dispatch_failed(
                worker, f"answered: {reply.get('error')}",
                code=reply.get("code"), epoch=reply.get("epoch"),
            )
        results = reply.get("results")
        if not isinstance(results, list) or len(results) != len(jobs):
            got = len(results) if isinstance(results, list) else 0
            self._dispatch_failed(
                worker, f"returned {got} results for {len(jobs)} jobs"
            )
        self.health.ok(worker.idx)
        return reply

    def stage_rpc(self, worker: PoolWorker, req: dict) -> dict:
        """One distributed-plan stage RPC on ``worker`` (docs/PLAN.md
        "Distributed execution"): the plan coordinator's map-split and
        reduce-partition dispatches ride this.  Epoch-stamped exactly
        like ``dispatch`` — a fenced-out zombie primary's stage can
        never publish a stale partition.  Raises ``PoolDispatchError``
        on ANY failure after marking the worker's health; a reduce
        stage's structured loss report surfaces as ``lost_split``."""
        req = dict(req, cmd="plan_stage")
        if self.epoch_fn is not None:
            req[protocol.EPOCH_KEY] = int(self.epoch_fn())
        try:
            reply = worker.rpc(req, self.secret, self.rpc_timeout)
        except Exception as e:
            self._dispatch_failed(
                worker,
                f"stage rpc died ({type(e).__name__}: {e})",
                cause=e,
            )
        if reply.get("status") != "ok":
            self._dispatch_failed(
                worker, f"answered: {reply.get('error')}",
                code=reply.get("code"), epoch=reply.get("epoch"),
                lost_split=reply.get("lost_split"),
                lost_epoch=reply.get("lost_epoch"),
            )
        self.health.ok(worker.idx)
        return reply

    def _dispatch_failed(
        self, worker: PoolWorker, msg: str, cause=None, code=None,
        epoch=None, lost_split=None, lost_epoch=None,
    ):
        """The ONE failure path out of ``dispatch``: quarantine the
        worker, count it, raise for the caller's retry ladder."""
        self.health.fail(worker.idx)
        with self._lock:
            self._dispatch_failures += 1
        err = PoolDispatchError(
            f"worker {worker.name} {msg}",
            code=str(code) if code else None,
            epoch=int(epoch) if epoch is not None else None,
            lost_split=int(lost_split) if lost_split is not None else None,
            lost_epoch=int(lost_epoch) if lost_epoch is not None else None,
        )
        if cause is not None:
            raise err from cause
        raise err

    def seed_affinity(self, worker: PoolWorker) -> int:
        """Warm-cache RPC: ask a worker which shapes it already holds
        compiled (``serve_stats``) and seed the affinity map — a daemon
        restarting against warm workers re-learns their homes instead of
        cold-spraying.  Best-effort with a SHORT timeout: this runs
        serially at daemon startup, and a roster of blackholed hosts
        must not hold the listen socket hostage for tens of seconds
        (affinity is re-learned from dispatches anyway)."""
        try:
            reply = worker.rpc(
                {"cmd": "serve_stats"}, self.secret, min(self.rpc_timeout, 2.0)
            )
        except Exception as e:  # noqa: BLE001 - seeding is best-effort
            logger.debug(
                "affinity seed from %s skipped (%s: %s); re-learned "
                "from dispatches", worker.name, type(e).__name__, e,
            )
            return 0
        shapes = reply.get("warm_shapes") or []
        n = 0
        with self._lock:
            for shape in shapes:
                try:
                    workload, fp, _njobs, bucket = shape
                except (TypeError, ValueError):
                    continue
                self._warm.setdefault(
                    ((str(workload), str(fp)), int(bucket)), set()
                ).add(worker.idx)
                n += 1
        return n

    # ------------------------------------------------------------ control

    def stats(self) -> dict:
        with self._lock:
            return {
                "workers": [w.name for w in self.workers],
                "inflight": list(self._inflight),
                "placements": list(self._placements),
                "affinity_hits": self._affinity_hits,
                "spill_overs": self._spill_overs,
                "local_fallbacks": self._place_fallbacks,
                "dispatch_failures": self._dispatch_failures,
                "quarantined": [
                    self.health.quarantined(i)
                    for i in range(len(self.workers))
                ],
                "warm_keys": len(self._warm),
            }

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop new placements and wait (bounded) for inflight worker
        RPCs to land.  True when the pool went quiet in time."""
        with self._lock:
            self._closed = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not any(self._inflight):
                    return True
            time.sleep(0.05)
        with self._lock:
            busy = sum(self._inflight)
        logger.warning(
            "serve pool still has %d inflight dispatch(es) after %.0fs "
            "drain; their jobs will fail structured at daemon close",
            busy, timeout,
        )
        return False

    def close(self, timeout: float = 30.0) -> None:
        """Drain (bounded), stop the executor, close every connection.
        Idempotent; safe to call with dispatches still inflight — they
        fail onto the retry ladder when their sockets close."""
        self.drain(timeout)
        # cancel_futures: anything still queued (there should be nothing,
        # place() reserved real slots) must not start after close.
        self._executor.shutdown(wait=False, cancel_futures=True)
        for w in self.workers:
            w.close()


def shard_ranges(n_lines: int, block_lines: int, shards: int) -> list[tuple[int, int]]:
    """Split ``n_lines`` into ``shards`` contiguous line ranges aligned
    to block boundaries (a shard is a whole number of blocks, so every
    shard's padding semantics match the engine's own block padding).
    Fewer ranges come back when the corpus has fewer blocks than
    requested shards."""
    n_blocks = max(1, -(-n_lines // block_lines))
    shards = max(1, min(shards, n_blocks))
    per = -(-n_blocks // shards)
    out = []
    start_blk = 0
    while start_blk < n_blocks:
        end_blk = min(n_blocks, start_blk + per)
        a = start_blk * block_lines
        b = min(n_lines, end_blk * block_lines)
        if b > a:
            out.append((a, b))
        start_blk = end_blk
    return out


def stable_shard_id(job_id: str, a: int, b: int) -> str:
    """Deterministic shard sub-id: replays and retries of the same job
    produce the same shard ids (chaos plans can target one shard)."""
    h = hashlib.sha256(f"{job_id}:{a}:{b}".encode()).hexdigest()[:6]
    return f"{job_id}#{a}-{b}-{h}"
