"""Thin client for the serve daemon (serve/daemon.py).

Speaks the distributor's authenticated frame protocol over one fresh
connection per request — stateless and retry-friendly (the daemon's
replay guard wants fresh nonces anyway; a persistent connection buys
nothing at control-plane request sizes).  ``ServeError`` carries the
daemon's structured reason code so callers can switch on ``code``
(``queue_full`` -> back off, ``bad_spec`` -> fix the request, ...).
"""

from __future__ import annotations

import base64
import socket
import time

from locust_tpu.distributor import protocol
from locust_tpu.utils import faultplan


class ServeError(RuntimeError):
    """A structured daemon-side error; ``code`` is an ERROR_CODES entry."""

    def __init__(self, code: str, message: str, reply: dict | None = None):
        self.code = code
        self.reply = reply or {}
        super().__init__(f"[{code}] {message}")


def _parse_addr(spec) -> tuple[str, int]:
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        return (str(spec[0]), int(spec[1]))
    host, _, port = str(spec).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"daemon address {spec!r} is not host:port")
    return (host, int(port))


# Bound on redirect-following per RPC: a not_primary chain longer than
# this is a misconfigured ring, not a failover.
_MAX_REDIRECTS = 4


class ServeClient:
    """``addr`` may be ONE address — ``(host, port)`` or ``"host:port"``
    — or a ROSTER (list/tuple of them, or a comma-separated string): the
    client tries each in order, follows a standby's structured
    ``not_primary`` redirect to the address it names, and remembers
    whichever daemon answered so ``submit``/``result``/``stats`` survive
    a takeover transparently (docs/SERVING.md "High availability")."""

    def __init__(
        self,
        addr,
        secret: bytes,
        timeout: float = 60.0,
    ):
        if isinstance(addr, str) and "," in addr:
            addr = [a.strip() for a in addr.split(",") if a.strip()]
        if isinstance(addr, str):
            roster = [_parse_addr(addr)]
        elif isinstance(addr, (list, tuple)) and len(addr) == 2 and (
            isinstance(addr[1], int)
            or (isinstance(addr[1], str) and addr[1].isdigit())
        ):
            # The legacy single-address tuple, port int OR numeric
            # string (the pre-roster constructor coerced with int()).
            roster = [_parse_addr(addr)]
        elif isinstance(addr, (list, tuple)) and addr:
            roster = [_parse_addr(a) for a in addr]
        else:
            roster = [_parse_addr(addr)]
        if not roster:
            raise ValueError("ServeClient needs at least one address")
        self.roster = roster
        self.addr = roster[0]  # the preferred (last-known-good) daemon
        self.secret = secret
        self.timeout = timeout

    # ------------------------------------------------------------ plumbing

    def _rpc_one(self, addr: tuple[str, int], req: dict) -> dict:
        faultplan.check_connect(addr[0], addr[1])
        with socket.create_connection(addr, timeout=self.timeout) as s:
            s.settimeout(self.timeout)
            protocol.send_frame(s, req, self.secret)
            return protocol.recv_frame(s, self.secret)

    def rpc(self, req: dict) -> dict:
        """One request against the roster: try the last-known-good
        daemon first, fail over to the others on connection errors, and
        follow ``not_primary`` redirects to the named primary.  The last
        connection error re-raises when nobody answers (single-address
        behavior is unchanged); a structured reply — even an error — is
        an ANSWER and returns to the caller."""
        order = [self.addr] + [a for a in self.roster if a != self.addr]
        last_err: Exception | None = None
        last_resp: dict | None = None
        redirects = 0
        i = 0
        while i < len(order):
            addr = order[i]
            i += 1
            try:
                resp = self._rpc_one(addr, req)
            except (ConnectionError, socket.timeout, OSError) as e:
                last_err = e
                continue
            if resp.get("code") == "not_primary":
                last_resp = resp
                if resp.get("primary") and redirects < _MAX_REDIRECTS:
                    redirects += 1
                    try:
                        target = _parse_addr(resp["primary"])
                    except ValueError:
                        continue
                    if target not in order:
                        order.insert(i, target)
                continue
            self.addr = addr  # sticky: later RPCs start here
            return resp
        if last_resp is not None:
            # Everyone reachable called themselves a standby: hand the
            # caller the structured not_primary answer, not a socket
            # error — the reason code is the actionable part.
            return last_resp
        raise last_err

    def _rpc_ok(self, req: dict) -> dict:
        resp = self.rpc(req)
        if resp.get("status") != "ok":
            raise ServeError(
                str(resp.get("code", "dispatch_failed")),
                str(resp.get("error", "serve request failed")),
                reply=resp,
            )
        return resp

    # ------------------------------------------------------------ commands

    def ping(self) -> bool:
        return bool(self._rpc_ok({"cmd": "ping"}).get("pong"))

    def submit(
        self,
        corpus: bytes | None = None,
        path: str | None = None,
        tenant: str = "default",
        workload: str | None = None,
        config: dict | None = None,
        weight: float = 1.0,
        invalidate: bool = False,
        no_cache: bool = False,
        deadline_s: float | None = None,
        max_attempts: int | None = None,
        plan: dict | str | None = None,
    ) -> dict:
        """Submit one job; returns the daemon's ack ({job_id, state,
        cached}).  Raises ``ServeError`` on a structured rejection.
        ``deadline_s``/``max_attempts`` are the job's robustness budgets
        (docs/SERVING.md): expiry anywhere answers ``deadline_exceeded``,
        a job that kills ``max_attempts`` dispatches is quarantined as
        ``poison_job``.  ``plan`` submits a composable dataflow plan
        (a plan document dict or its JSON text, docs/PLAN.md) instead of
        a named workload; the result is the pipeline's rendered output
        bytes as one (bytes, 0) pair, flagged ``plan`` in the reply."""
        req: dict = {
            "cmd": "submit",
            "tenant": tenant,
            "weight": weight,
        }
        if plan is not None:
            # Mirror the daemon's parse_spec rule EXACTLY (workload
            # None or the reserved "plan" name may ride a plan submit;
            # anything else is conflicting intent) instead of silently
            # dropping the caller's workload — the default is None so
            # an explicitly stated workload is always distinguishable.
            if workload not in (None, "plan"):
                raise ValueError(
                    "submit takes a plan OR a workload name, not both"
                )
            req["plan"] = plan
            # The reserved name rides ALONGSIDE the plan: a pre-plan
            # daemon ignores the unknown "plan" key, and without this it
            # would default the submit to wordcount and answer a wrong
            # but "done" table — with it, the old build rejects loudly
            # with unknown_workload (never a silent wrong answer).
            req["workload"] = "plan"
        else:
            req["workload"] = workload or "wordcount"
        if config:
            req["config"] = dict(config)
        if invalidate:
            req["invalidate"] = True
        if no_cache:
            req["no_cache"] = True
        if deadline_s is not None:
            req["deadline_s"] = deadline_s
        if max_attempts is not None:
            req["max_attempts"] = max_attempts
        if corpus is not None:
            req["corpus_b64"] = base64.b64encode(corpus).decode()
        if path is not None:
            req["path"] = path
        return self._rpc_ok(req)

    def status(self, job_id: str) -> dict:
        return self._rpc_ok({"cmd": "status", "job_id": job_id})

    def result(self, job_id: str) -> dict:
        """The finished job's decoded result: the reply dict with
        ``pairs`` as (key bytes, count) tuples.  Raises ``ServeError``
        with the job's structured code on failed/cancelled/not-done."""
        resp = self._rpc_ok({"cmd": "result", "job_id": job_id})
        resp["pairs"] = [
            (base64.b64decode(k), int(v)) for k, v in resp.get("pairs", [])
        ]
        return resp

    def wait(self, job_id: str, timeout: float = 120.0,
             poll_s: float = 0.05, max_poll_s: float = 1.0) -> dict:
        """Poll until the job leaves the queue/engine; returns
        ``result()`` on success, raises ``ServeError`` on a structured
        failure or ``TimeoutError`` when the deadline passes (a bounded
        wait — a wedged daemon must not hang the client).

        Polling backs off geometrically from ``poll_s`` to ``max_poll_s``
        with jitter: a fixed interval across N waiting clients
        synchronizes their status RPCs into daemon-hammering waves, and
        long jobs do not need 20 polls a second.  The timeout error
        carries the daemon-reported job state and attempt count — "still
        retrying (attempt 2/4)" is actionable where a bare "still
        running after Ns" is not."""
        deadline = time.monotonic() + timeout
        sleep_s = poll_s
        while True:
            st = self.status(job_id)
            if st["state"] in ("done", "failed", "cancelled"):
                return self.result(job_id)
            now = time.monotonic()
            if now >= deadline:
                attempts = st.get("attempts")
                budget = st.get("max_attempts")
                detail = f"state {st['state']!r}"
                if attempts is not None and budget is not None:
                    detail += f", attempt {attempts}/{budget}"
                if st.get("batch_size"):
                    detail += f", batch of {st['batch_size']}"
                raise TimeoutError(
                    f"job {job_id} not finished after {timeout}s "
                    f"({detail}); the daemon still holds it — poll "
                    "status/result again or raise the timeout"
                )
            # Deterministic-enough jitter without the global RNG: the
            # fractional spread only needs to decorrelate clients.
            jitter = 0.5 + (hash((job_id, now)) % 1024) / 2048.0
            time.sleep(min(sleep_s * jitter, max(deadline - now, 0.001)))
            sleep_s = min(sleep_s * 1.6, max_poll_s)

    def cancel(self, job_id: str) -> dict:
        return self._rpc_ok({"cmd": "cancel", "job_id": job_id})

    def invalidate(self, digest: str | None = None,
                   spec_fp: str | None = None,
                   job_id: str | None = None) -> int:
        req: dict = {"cmd": "invalidate"}
        if digest:
            req["digest"] = digest
        if spec_fp:
            req["spec_fp"] = spec_fp
        if job_id:
            req["job_id"] = job_id
        return int(self._rpc_ok(req).get("invalidated", 0))

    def promote(self) -> dict:
        """Promote THE FIRST ROSTER ADDRESS to PRIMARY (fenced epoch
        bump + WAL replay, docs/SERVING.md "High availability").
        Deliberately neither redirect-following nor failing-over: an
        epoch bump fences the other pair member, so it must land on
        exactly the daemon the operator named — point a single-address
        client at the standby.  A connection error raises; a primary
        answers a structured refusal."""
        resp = self._rpc_one(self.roster[0], {"cmd": "promote"})
        if resp.get("status") != "ok":
            raise ServeError(
                str(resp.get("code", "dispatch_failed")),
                str(resp.get("error", "promote failed")),
                reply=resp,
            )
        return resp

    def stats(self) -> dict:
        return self._rpc_ok({"cmd": "stats"})

    def shutdown(self) -> bool:
        return bool(self._rpc_ok({"cmd": "shutdown"}).get("bye"))
