"""The MapReduce engine: pluggable map/combine over blocked byte tensors.

Single-device orchestration — the TPU-native analog of the reference driver's
map -> process -> reduce sequencing (reference MapReduce/src/main.cu:397-473),
with three deliberate departures:

* **No global line cap.**  The reference truncates input at
  MAX_LINES_FILE_READ=5800 lines (main.cu:18).  Here the corpus streams
  through fixed-shape blocks of ``cfg.block_lines`` and partial result tables
  merge associatively (sort + segment-reduce is a monoid fold), so input
  size is unbounded (SURVEY.md §5 "long-context").
* **Pluggable semantics.**  ``map_fn(lines, cfg) -> (KVBatch, overflow)`` and
  a monoid ``combine`` replace the hardcoded WordCount map()/count-reduce
  (main.cu:136-153, 210-238); WordCount, PageRank and inverted-index are
  instances (locust_tpu/apps/).
* **One sort per block.**  The block's emits concatenate with the bounded
  running table (``cfg.resolved_table_size`` rows) and a SINGLE
  sort+segment-reduce both groups the new emits and merges them into the
  accumulator — the per-block sort and the cross-block merge sort of a
  naive formulation fused into one.  With ``sort_mode="hash"`` that sort has
  3 key operands regardless of key width (ops/process_stage.py).

Every stage is jit-compiled once per config; ``run_fused`` runs the whole
corpus in ONE dispatch (lax.scan over blocks), ``timed_run`` dispatches
stages separately to reproduce the reference's per-stage Map/Process/Reduce
timing report (main.cu:405-468).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu import backend as backend_mod
from locust_tpu import obs
from locust_tpu.config import DEFAULT_CONFIG, EngineConfig
from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch
from locust_tpu.io.snapshot import AsyncCheckpointWriter, finalize_snapshot
from locust_tpu.ops.map_stage import wordcount_map
from locust_tpu.ops.process_stage import sort_and_compact
from locust_tpu.ops.reduce_stage import (
    normalize_combine,
    segment_reduce,
    segment_reduce_into,
)

logger = logging.getLogger("locust_tpu")

MapFn = Callable[[jax.Array, EngineConfig], tuple[KVBatch, jax.Array]]

# Host-side monoid mirrors of ops/reduce_stage.COMBINERS, used to re-merge
# the (astronomically rare) duplicate table rows a 64-bit hash collision can
# produce in sort_mode="hash" (see core/packing.hash_pair).
_HOST_COMBINE = {
    "sum": lambda a, b: a + b,
    "count": lambda a, b: a + b,
    "min": min,
    "max": max,
}


def finalize_host_pairs(
    table: KVBatch, combine: str = "sum", sort: bool = True
) -> list[tuple[bytes, int]]:
    """Decode a device table to host (key, value) pairs, exactly.

    Re-merges duplicate key rows (possible only via a full 64-bit hash
    collision in sort_mode="hash") and restores lexicographic key order —
    the reference's sorted final print (main.cu:473).
    """
    op = _HOST_COMBINE[combine]
    merged: dict[bytes, int] = {}
    for k, v in table.to_host_pairs():
        merged[k] = op(merged[k], v) if k in merged else v
    pairs = list(merged.items())
    return sorted(pairs) if sort else pairs


def _wrap_i32(v: int) -> int:
    """Two's-complement int32 wraparound — the device table's value
    dtype, so a host-side merge wraps exactly where a full device fold
    would."""
    v &= 0xFFFFFFFF
    return v - 0x100000000 if v >= 0x80000000 else v


def merge_host_pairs(
    base: list[tuple[bytes, int]],
    delta: list[tuple[bytes, int]],
    combine: str = "sum",
) -> list[tuple[bytes, int]]:
    """Merge two finalized host-pairs lists by key — the mergeable-table
    property the plan optimizer's incremental refold rides
    (plan/optimize.py ``incremental_fold``): an exact fold is a pure
    function of the line multiset, so fold(prefix) ⊕ fold(delta) ==
    fold(prefix + delta).  Sum/count merge with int32 WRAPAROUND to
    match the device accumulator's dtype bit-for-bit; ordering matches
    ``finalize_host_pairs`` (lexicographic key sort)."""
    op = _HOST_COMBINE[combine]
    wrap = combine in ("sum", "count")
    merged: dict[bytes, int] = dict(base)
    for k, v in delta:
        if k in merged:
            out = op(merged[k], v)
            merged[k] = _wrap_i32(int(out)) if wrap else out
        else:
            merged[k] = v
    return sorted(merged.items())


@dataclasses.dataclass
class StageTimes:
    """Per-stage wall-clock, the reference's timing report (main.cu:405-468)."""

    map_ms: float = 0.0
    process_ms: float = 0.0
    reduce_ms: float = 0.0

    @property
    def total_ms(self) -> float:
        return self.map_ms + self.process_ms + self.reduce_ms


@dataclasses.dataclass
class RunResult:
    table: KVBatch            # unique keys + combined values (device order)
    num_segments: int         # distinct keys found (<= table capacity)
    overflow_tokens: int      # emits dropped by the per-line cap
    truncated: bool           # True if distinct keys exceeded table capacity
    times: StageTimes
    combine: str = "sum"
    # run_stream only: hot-loop stall accounting + checkpoint-writer
    # stats (backpressure_stall_ms, ckpt.{mark_ms,written,skipped,
    # max_lag,...}) — the numbers behind bench.py's "stream" sub-dict.
    stream: dict | None = None
    # Which megakernel formulation actually served this run (ISSUE 19
    # operator visibility): "batch" = per-block fused_block_preagg,
    # "stream" = the persistent streaming segments, None = no kernel
    # (non-fused sort modes, or a demoted fused request).
    fused_kernel: str | None = None
    # True iff sort_mode="fused" was REQUESTED but the kernel did not
    # engage (eligibility miss / off-TPU interpret cap / mesh-on-CPU) —
    # the fold ran hasht-identically.  The mesh engines mirror this on
    # DistributedResult; previously the demotion was silent.
    fused_demoted: bool = False

    def to_host_pairs(self, sort: bool = True) -> list[tuple[bytes, int]]:
        """Decode the table; re-merge hash-collision duplicates; key-sort.

        The device table in sort_mode="hash" is hash-ordered; lexicographic
        output order (the reference's sorted print, main.cu:473) is restored
        here on the final table, which is orders of magnitude smaller than
        the emit stream.
        """
        return finalize_host_pairs(self.table, self.combine, sort)

    def dump_intermediate(self, path: str, fmt: str = "tsv") -> None:
        """Stage-1 output plumbing: the combined local table as an
        intermediate file — ``tsv`` for reference parity, ``bin`` for the
        distributor's packed-KV data plane (io/serde.py)."""
        from locust_tpu.io import serde

        serde.write_intermediate(self.to_host_pairs(), path, fmt)


class _StagingRing:
    """Reusable host staging buffers for the streaming fold.

    ``slots`` pre-allocated ``[block_lines, width]`` uint8 buffers cycled
    round-robin: each block is padded into the next slot
    (normalize_round_chunk ``out=``) and handed straight to the device,
    so steady-state staging allocates nothing — the flat-RSS contract's
    allocation-free upgrade.

    Reuse safety: jax's CPU backend aliases host numpy buffers zero-copy
    at ``device_put``, so a slot must not be overwritten while its fold
    is in flight.  ``run_stream``'s bounded-inflight backpressure syncs
    the fold ``STREAM_DISPATCH_DEPTH`` blocks back before dispatching a
    new one; with ``STREAM_DISPATCH_DEPTH + 1`` slots, the slot being
    re-filled at block ``i`` was consumed by fold ``i - (slots)``, which
    that sync already proved complete.
    """

    def __init__(self, slots: int, block_lines: int, width: int):
        self._bufs = [
            np.zeros((block_lines, width), np.uint8) for _ in range(slots)
        ]
        self._next = 0

    def stage(self, chunk, block_lines: int, width: int) -> np.ndarray:
        from locust_tpu.parallel.shuffle import normalize_round_chunk

        buf = self._bufs[self._next]
        self._next = (self._next + 1) % len(self._bufs)
        return normalize_round_chunk(chunk, block_lines, width, out=buf)


class _CheckpointPump:
    """Per-run snapshot scheduler for the single-device engine.

    Synchronous mode writes in the fold loop (the pre-existing
    behavior); async mode (cfg.async_checkpoint) marks a generation —
    an on-device copy of the accumulator, dispatched BEFORE the next
    fold donates its buffers — and hands the serialize+rename to the
    bounded background writer (io/snapshot.AsyncCheckpointWriter,
    latest-wins if the loop laps it).  The on-disk format and atomic-
    replace semantics are identical in both modes.
    """

    def __init__(self, engine: "MapReduceEngine", state_path: str,
                 fingerprint: str, use_async: bool):
        self._eng = engine
        self._path = state_path
        self._fp = fingerprint
        self._writer = AsyncCheckpointWriter() if use_async else None
        self.mark_ms = 0.0
        self._sync_writes = 0

    def mark(self, acc: KVBatch, next_block: int, overflow, max_distinct):
        t0 = time.perf_counter()
        obs.event(
            "ckpt.mark",
            generation=next_block,
            mode="async" if self._writer is not None else "sync",
        )
        obs.metric_inc("ckpt.marks")
        if self._writer is None:
            self._eng._save_state(
                self._path, acc, next_block, overflow, max_distinct, self._fp
            )
            self._sync_writes += 1
        else:
            # Device-to-device copy (async dispatch, no host sync): the
            # donated fold reuses acc's buffers next iteration, so the
            # writer must snapshot a buffer the loop will never touch.
            # The scalar counters are fresh eager arrays each fold and
            # are never donated — holding references suffices.
            snap = KVBatch(
                key_lanes=jnp.copy(acc.key_lanes),
                values=jnp.copy(acc.values),
                valid=jnp.copy(acc.valid),
            )
            self._writer.submit(
                next_block,
                partial(
                    self._eng._save_state, self._path, snap, next_block,
                    overflow, max_distinct, self._fp,
                ),
            )
        self.mark_ms += (time.perf_counter() - t0) * 1e3

    def finish(self) -> float:
        """Normal-path completion: block until the last marked generation
        is durably renamed; re-raises writer errors.  Returns the wait ms
        (the ONLY synchronous write cost the async mode keeps)."""
        t0 = time.perf_counter()
        if self._writer is not None:
            self._writer.flush()
        return (time.perf_counter() - t0) * 1e3

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()

    def stats(self) -> dict:
        out = {
            "mode": "async" if self._writer is not None else "sync",
            "mark_ms": round(self.mark_ms, 3),
        }
        if self._writer is not None:
            out.update(self._writer.stats())
        else:
            out["written"] = self._sync_writes
        return out


class MapReduceEngine:
    """Blocked map/shuffle/reduce on one device (mesh version in parallel/)."""

    # run_stream keeps at most this many folds in flight before blocking:
    # pipeline overlap without per-corpus RSS growth (each in-flight fold
    # pins its staged host block).  scripts/stream_scale.py derives its
    # expected-working-set estimate from this constant — keep them linked.
    STREAM_DISPATCH_DEPTH = 4

    def __init__(
        self,
        cfg: EngineConfig = DEFAULT_CONFIG,
        map_fn: MapFn = wordcount_map,
        combine: str = "sum",
    ):
        self.cfg = cfg
        if cfg.trace:
            # API-level telemetry opt-in (the CLI's --trace-out does the
            # same enable + an export at exit); idempotent, shares one
            # process timeline with any tracer already enabled.
            obs.enable()
        self.combine = combine  # user-facing semantics (host finalize)
        # "count" lowers to emit-1 + sum so the block-accumulator merge is
        # associative (reduce_stage.normalize_combine); the device pipeline
        # below uses the normalized pair throughout.  The RAW map_fn is
        # what the fused-kernel eligibility check identifies (the count
        # wrapper emits the same 1s the kernel counts).
        raw_map_fn = map_fn
        map_fn, combine = normalize_combine(map_fn, combine)
        self.map_fn = map_fn
        tsize = cfg.resolved_table_size
        mode = cfg.sort_mode

        from locust_tpu.ops.hash_table import fold_into

        # sort_mode="fused": the Pallas map->aggregate megakernel
        # (ops/pallas/fused_fold.py) replaces the map stage + first
        # aggregation at THIS boundary only — everywhere else the mode
        # is "hasht" exactly (config.HASHT_FAMILY).  Eligibility is
        # fully static, decided (and logged) once here, never inside
        # traced code.
        self._fused_kernel_on = False
        self._fused_demoted = False
        # Persistent streaming segment length (megakernel v2): how many
        # staged blocks run_stream groups into ONE kernel launch with the
        # table VMEM-resident across the whole segment.  1 = per-block
        # (the v1 formulation); the clamp keeps the per-segment emit
        # budget f32-exact and bounds off-TPU interpret cost
        # (config.fused_stream_seg_blocks).
        self._fused_stream_seg = 1
        if mode == "fused":
            from locust_tpu.config import fused_stream_seg_blocks
            from locust_tpu.ops.pallas.fused_fold import (
                fused_engine_eligible,
            )

            ok, why = fused_engine_eligible(cfg, raw_map_fn, self.combine)
            self._fused_kernel_on = ok
            self._fused_demoted = not ok
            if not ok:
                logger.info("sort_mode='fused': kernel not engaged — %s",
                            why)
            else:
                self._fused_stream_seg = fused_stream_seg_blocks(
                    cfg.emits_per_block,
                    cfg.block_lines,
                    jax.default_backend() == "tpu",
                )

        def fold_block(acc: KVBatch, lines: jax.Array):
            """Map one block and merge its emits into the running table.

            Sort modes: ONE sort of (table_size + emits_per_block) rows
            does both the block's shuffle-grouping and the cross-block
            merge.  The hasht family ("hasht" = scatter combine,
            "hasht-mxu" = one-hot MXU combine, "fused" = the Pallas
            megakernel below, else hasht): the sort-free fold with its
            exactness ladder, rebuilt per fold (ops/hash_table.fold_into
            — see there for why the incremental variant measured worse
            and is not wired).  Either way the running distinct-key
            count is measured BEFORE the capacity slice so a truncation
            in any fold is observable.

            Fused kernel path: the block pre-aggregates IN VMEM (the
            [lines, emits, key_width] token tensor never touches HBM)
            and the settlement folds (acc + kernel table + residual)
            through the SAME aggregate_exact as "hasht" — the final
            table is a pure function of the distinct-key set and the
            per-key totals, so it is bit-identical to the hasht fold
            (ops/pallas/fused_fold.py module docstring; pinned by
            tests/test_fused_fold.py).  A residual-buffer overflow in
            the kernel re-folds the block through the stock path via
            lax.cond — exact either way, and the overflow counter is
            the kernel's under both branches (identical tokenize
            formulation).
            """
            if self._fused_kernel_on:
                from locust_tpu.ops.pallas.fused_fold import (
                    fused_block_preagg,
                )

                interpret = jax.default_backend() != "tpu"
                ktab, kresid, overflow, bad = fused_block_preagg(
                    lines, cfg, interpret=interpret
                )

                def fused_path(acc_in):
                    return fold_into(
                        acc_in, KVBatch.concat(ktab, kresid), tsize,
                        combine, mode,
                    )

                def stock_path(acc_in):
                    kv, _ = map_fn(lines, cfg)
                    return fold_into(acc_in, kv, tsize, combine, mode)

                merged, distinct = jax.lax.cond(
                    bad, stock_path, fused_path, acc
                )
                return merged, overflow, distinct
            return stock_fold(acc, lines)

        def stock_fold(acc: KVBatch, lines: jax.Array):
            """The kernel-free fold — fold_block's non-kernel tail, and
            the breaker-failover executable: the CPU fallback must never
            trace the Mosaic kernel (at failover trace time
            jax.default_backend() is still the dead primary, so the
            in-fold interpret switch cannot see the migration;
            run_checkpointed dispatches THIS on the fallback device).
            Bit-identical outputs to the kernel path by the settlement
            argument, so mid-job migration changes nothing downstream.
            """
            kv, overflow = map_fn(lines, cfg)
            merged, distinct = fold_into(acc, kv, tsize, combine, mode)
            return merged, overflow, distinct

        def fold_segment(acc: KVBatch, seg_lines: jax.Array):
            """Persistent-kernel streaming fold (megakernel v2): ONE
            kernel launch over ``[seg_blocks * block_lines, width]``
            staged lines, table planes VMEM-resident across the whole
            segment (fused_block_preagg already supports any
            tile-multiple line count; its constant-index table BlockSpec
            IS the persistence).  The acc->settle->acc HBM round-trip
            and the table flush amortize by the segment length — the v2
            traffic model in utils/roofline.py.

            Bit-identity carries over from fold_block unchanged: the
            settlement folds concat(acc, table, residual) through the
            same aggregate_exact, and hasht's final table is a pure
            function of the distinct-key set + per-key totals — which
            are grouping-invariant (emit overflow is per-line, counts
            are per-key sums).  A residual overflow re-folds the WHOLE
            segment through the stock path (map over the segment lines
            is exact at any length), so both cond branches stay exact.
            """
            from locust_tpu.ops.pallas.fused_fold import (
                fused_block_preagg,
            )

            interpret = jax.default_backend() != "tpu"
            ktab, kresid, overflow, bad = fused_block_preagg(
                seg_lines, cfg, interpret=interpret
            )

            def fused_path(acc_in):
                return fold_into(
                    acc_in, KVBatch.concat(ktab, kresid), tsize,
                    combine, mode,
                )

            def stock_path(acc_in):
                kv, _ = map_fn(seg_lines, cfg)
                return fold_into(acc_in, kv, tsize, combine, mode)

            merged, distinct = jax.lax.cond(bad, stock_path, fused_path, acc)
            return merged, overflow, distinct

        def scan_blocks_into(acc0: KVBatch, blocks: jax.Array):
            """Whole-corpus pipeline in ONE dispatch: fold blocks with lax.scan.

            One device dispatch per corpus instead of per block — essential
            when dispatch latency is high (remote TPU tunnels) and the XLA-
            idiomatic way to loop without data-dependent Python control flow.
            The init accumulator arrives as an ARGUMENT so the jit below
            can donate it into the scan carry (cfg.donate_fold): even the
            one-dispatch path allocates no second table.
            """

            def body(carry, blk):
                acc, overflow_acc, max_distinct = carry
                acc, overflow, distinct = fold_block(acc, blk)
                return (
                    acc,
                    overflow_acc + overflow,
                    jnp.maximum(max_distinct, distinct),
                ), None

            init = (acc0, jnp.int32(0), jnp.int32(0))
            (acc, overflow, num), _ = jax.lax.scan(body, init, blocks)
            return acc, overflow, num

        # Donated fold state (cfg.donate_fold): the accumulator table —
        # the largest live array — is donated into every per-block
        # dispatch and into the scan init, so XLA aliases its buffers
        # input->output (updated in place, no per-fold re-allocation).
        # Callers therefore must treat the acc they passed as consumed;
        # every loop here rebinds it, and snapshot marks copy on device
        # first (_CheckpointPump.mark).
        donate = (0,) if cfg.donate_fold else ()
        self._fold_block = jax.jit(fold_block, donate_argnums=donate)
        # Breaker-failover fold (run_checkpointed's on-CPU dispatch):
        # identical to _fold_block unless the fused kernel is on — then
        # it is the kernel-free stock fold (see stock_fold above).
        # Traced lazily, so non-failover runs never pay its compile.
        self._fold_block_fallback = (
            jax.jit(stock_fold, donate_argnums=donate)
            if self._fused_kernel_on
            else self._fold_block
        )
        # Streaming-segment executable (megakernel v2): traced lazily on
        # first run_stream use; None when the kernel is off or the clamp
        # leaves segments at one block (then run_stream's per-block loop
        # is already optimal).
        self._fold_segment = (
            jax.jit(fold_segment, donate_argnums=donate)
            if self._fused_kernel_on and self._fused_stream_seg > 1
            else None
        )
        self._scan_blocks_into = jax.jit(scan_blocks_into, donate_argnums=donate)
        # The export/compile-check surface (__graft_entry__.entry, the
        # TPU StableHLO lowering gates) keeps the one-argument signature.
        self._scan_blocks = jax.jit(
            lambda blocks: scan_blocks_into(
                KVBatch.empty(tsize, cfg.key_lanes), blocks
            )
        )
        # Batched job executable (the serve tier's coalesced dispatch,
        # docs/SERVING.md): vmap the whole-corpus scan over a leading JOB
        # axis, so N compatible small jobs fold in ONE device dispatch
        # with per-job tables/counters out.  Each job slot gets its own
        # fresh accumulator (no donation: slots are independent and the
        # batch is rebuilt per dispatch); traced/compiled lazily on first
        # use per [njobs, nblocks] shape — non-serve users never pay it.
        self._scan_blocks_batch = jax.jit(
            jax.vmap(
                lambda blocks: scan_blocks_into(
                    KVBatch.empty(tsize, cfg.key_lanes), blocks
                )
            )
        )

        # Split stages for the timed path only.
        def merge_tables(acc: KVBatch, table: KVBatch, max_distinct: jax.Array):
            merged, distinct = segment_reduce_into(
                sort_and_compact(KVBatch.concat(acc, table), mode), tsize, combine
            )
            return merged, jnp.maximum(max_distinct, distinct)

        self._map = jax.jit(lambda lines: map_fn(lines, cfg))
        self._process = jax.jit(partial(sort_and_compact, mode=mode))
        self._reduce = jax.jit(partial(segment_reduce, combine=combine))
        self._merge = jax.jit(merge_tables, donate_argnums=donate)
        self._table_size = tsize

    # ---------------------------------------------------------------- ingest

    def rows_from_lines(self, lines: Sequence[bytes]) -> np.ndarray:
        return bytes_ops.strings_to_rows(list(lines), self.cfg.line_width)

    def _blocks(self, rows: np.ndarray):
        """Yield fixed-shape [block_lines, line_width] blocks, zero-padded."""
        bl = self.cfg.block_lines
        n = rows.shape[0]
        for i in range(0, max(n, 1), bl):
            blk = rows[i : i + bl]
            if blk.shape[0] < bl:
                pad = np.zeros((bl - blk.shape[0], rows.shape[1]), np.uint8)
                blk = np.concatenate([blk, pad]) if blk.size else pad
            yield jnp.asarray(blk)

    # ------------------------------------------------------------------- run

    def run(self, rows: np.ndarray) -> RunResult:
        """Fused per-block fold, one dispatch per block.

        Keeps overflow/distinct counters on device across the loop — no
        host sync until the end, so block dispatches pipeline asynchronously.
        """
        acc = KVBatch.empty(self._table_size, self.cfg.key_lanes)
        overflow = jnp.int32(0)
        max_distinct = jnp.int32(0)
        t0 = time.perf_counter()
        for blk in self._blocks(rows):
            acc, blk_overflow, distinct = self._fold_block(acc, blk)
            overflow = overflow + blk_overflow
            max_distinct = jnp.maximum(max_distinct, distinct)
        jax.block_until_ready(acc.key_lanes)
        total_ms = (time.perf_counter() - t0) * 1e3
        return self._finish(
            acc, max_distinct, int(overflow), StageTimes(0, total_ms, 0)
        )

    def prepare_blocks(self, rows: np.ndarray) -> jax.Array:
        """Pad + reshape a host row array into device-resident scan blocks.

        Staging is split from ``run_blocks`` so callers can overlap/amortize
        the host->device transfer — the reference's published stage timings
        likewise start AFTER its H2D memcpy (main.cu:402-408).
        """
        bl, w = self.cfg.block_lines, self.cfg.line_width
        n = rows.shape[0]
        nblocks = max(1, -(-n // bl))
        padded = np.zeros((nblocks * bl, w), dtype=np.uint8)
        padded[:n] = rows[:, :w]
        return jax.device_put(padded.reshape(nblocks, bl, w))

    def run_blocks(self, blocks: jax.Array) -> RunResult:
        """One-dispatch run over pre-staged ``[nblocks, block_lines, width]``."""
        t0 = time.perf_counter()
        acc0 = KVBatch.empty(self._table_size, self.cfg.key_lanes)
        acc, overflow, num = self._scan_blocks_into(acc0, blocks)
        num = int(num)  # host sync: the scan (and everything before) is done
        total_ms = (time.perf_counter() - t0) * 1e3
        return self._finish(acc, num, int(overflow), StageTimes(0, total_ms, 0))

    def run_batch(self, blocks: jax.Array) -> list[RunResult]:
        """One dispatch over a JOB-batched ``[njobs, nblocks, block_lines,
        width]`` stack: every job folds independently (vmapped scan) and
        the per-job tables/counters demultiplex back into one RunResult
        per job.  The serve tier's coalesced executable (docs/SERVING.md):
        compatible queued small jobs share this single compiled program
        instead of paying one dispatch (and one compile shape) each.
        Zero-filled job slots (batch padding) fold to empty tables.
        ``StageTimes`` carries the WHOLE batch's wall per job — per-job
        wall latency is the caller's (the daemon times submit->done).
        """
        t0 = time.perf_counter()
        acc, overflow, num = self._scan_blocks_batch(blocks)
        num = np.asarray(num)  # host sync: the batch is done
        overflow = np.asarray(overflow)
        total_ms = (time.perf_counter() - t0) * 1e3
        return [
            self._finish(
                KVBatch(
                    key_lanes=acc.key_lanes[j],
                    values=acc.values[j],
                    valid=acc.valid[j],
                ),
                int(num[j]),
                int(overflow[j]),
                StageTimes(0, total_ms, 0),
            )
            for j in range(blocks.shape[0])
        ]

    def run_fused(self, rows: np.ndarray) -> RunResult:
        """Whole-corpus run as a single device dispatch (lax.scan over blocks).

        Preferred for throughput: amortizes dispatch latency and lets XLA
        pipeline block processing.  Compiles once per number-of-blocks; pad
        the corpus externally to a fixed block count to reuse the executable.
        """
        return self.run_blocks(self.prepare_blocks(rows))

    def timed_run(self, rows: np.ndarray) -> RunResult:
        """Per-stage timing parity with the reference's report (main.cu:405-468).

        Stage boundaries force ``block_until_ready``, so this is slower than
        ``run``; use it for the stage report, ``run`` for throughput.  The
        cross-block table merge is accounted to the Process stage (it is a
        sort), matching where the reference spends that time (main.cu:447).
        """
        acc = KVBatch.empty(self._table_size, self.cfg.key_lanes)
        overflow = 0
        max_distinct = jnp.int32(0)
        times = StageTimes()
        for blk in self._blocks(rows):
            # obs spans shadow the t0..t4 boundaries exactly (each stage's
            # sync is inside its span), so an exported timeline and the
            # reference-parity StageTimes report can never disagree.
            t0 = time.perf_counter()
            with obs.span("engine.stage.map"):
                kv, blk_overflow = self._map(blk)
                jax.block_until_ready(kv.key_lanes)  # locust: noqa[R003] stage-timing boundary (reference parity): the sync IS the measurement
            t1 = time.perf_counter()
            with obs.span("engine.stage.process"):
                kv = self._process(kv)
                jax.block_until_ready(kv.key_lanes)  # locust: noqa[R003] stage-timing boundary (reference parity): the sync IS the measurement
            t2 = time.perf_counter()
            with obs.span("engine.stage.reduce"):
                table = self._reduce(kv)
                jax.block_until_ready(table.key_lanes)  # locust: noqa[R003] stage-timing boundary (reference parity): the sync IS the measurement
            t3 = time.perf_counter()
            with obs.span("engine.stage.merge"):
                acc, max_distinct = self._merge(acc, table, max_distinct)
                jax.block_until_ready(acc.key_lanes)  # locust: noqa[R003] stage-timing boundary (reference parity): the sync IS the measurement
            t4 = time.perf_counter()
            times.map_ms += (t1 - t0) * 1e3
            times.process_ms += (t2 - t1) * 1e3 + (t4 - t3) * 1e3
            times.reduce_ms += (t3 - t2) * 1e3
            overflow += int(blk_overflow)
        jax.block_until_ready(acc.key_lanes)
        return self._finish(acc, max_distinct, overflow, times)

    def run_lines(self, lines: Sequence[bytes]) -> RunResult:
        return self.run(self.rows_from_lines(lines))

    def run_stream(
        self,
        blocks,
        checkpoint_dir: str | None = None,
        every: int = 8,
        fingerprint: str | None = None,
    ) -> RunResult:
        """Fold an ITERABLE of ``[<=block_lines, width]`` host row blocks.

        Bounded-memory ingest for corpora that don't fit RAM (VERDICT r2
        missing #4): pair with ``io.loader.StreamingCorpus`` and only one
        file window plus the accumulator table are ever resident.  Device
        counters stay on device across blocks (same pipelining as
        ``run``); blocks shorter than ``cfg.block_lines`` are zero-padded
        so every fold reuses the one compiled executable.

        With ``checkpoint_dir`` + ``fingerprint`` (e.g.
        ``StreamingCorpus.fingerprint()``, which hashes file identity
        without reading it fully), snapshots land every ``every`` blocks
        exactly as in ``run_checkpointed``; a resume re-READS but does not
        re-process already-folded blocks.

        Zero-stall executor (docs/DESIGN.md): the fold accumulator is
        DONATED into each dispatch (updated in place), blocks stage
        through a reusable host buffer ring instead of per-block
        allocations, and snapshots ride the background writer — the hot
        loop's only synchronous work is the bounded-inflight
        backpressure.  Stall accounting lands in ``RunResult.stream``.
        """
        from locust_tpu.io.loader import prefetch_blocks
        from locust_tpu.parallel.shuffle import normalize_round_chunk
        blocks = prefetch_blocks(blocks)  # overlap host reads with folds
        bl, w = self.cfg.block_lines, self.cfg.line_width
        acc = KVBatch.empty(self._table_size, self.cfg.key_lanes)
        overflow = jnp.int32(0)
        max_distinct = jnp.int32(0)
        start_block = 0
        pump = None
        if checkpoint_dir is not None:
            if every < 1:
                raise ValueError(f"checkpoint every must be >= 1, got {every}")
            if fingerprint is None:
                raise ValueError(
                    "run_stream needs an explicit corpus fingerprint to "
                    "checkpoint (e.g. StreamingCorpus.fingerprint())"
                )
            fingerprint = f"{fingerprint}:{self.cfg!r}:{self.combine}:" + getattr(
                self.map_fn, "__name__", str(self.map_fn)
            )
            os.makedirs(checkpoint_dir, exist_ok=True)
            state_path = os.path.join(checkpoint_dir, "state.npz")
            start_block, overflow, max_distinct, acc = self._load_state(
                state_path, fingerprint, acc
            )
            pump = _CheckpointPump(
                self, state_path, fingerprint, self.cfg.async_checkpoint
            )
        if self._fold_segment is not None:
            # Megakernel v2 persistent streaming: segments of
            # _fused_stream_seg staged blocks per kernel launch, table
            # VMEM-resident across each segment (fold_segment docstring).
            return self._run_stream_fused(
                blocks, acc, overflow, max_distinct, start_block, pump,
                every,
            )
        ring = (
            _StagingRing(self.STREAM_DISPATCH_DEPTH + 1, bl, w)
            if self.cfg.stream_staging_ring
            else None
        )

        stall_ms = 0.0
        flush_ms = 0.0
        t0 = time.perf_counter()
        # Bound the async dispatch depth: without a sync, the host loop
        # races ahead of the device and EVERY staged block stays
        # referenced by its in-flight fold — RSS then grows with corpus
        # size, which is exactly what a streaming fold must not do
        # (measured: +55MB at 16MB vs +110MB at 64MB before this bound).
        # Blocking on the fold K steps back keeps K blocks of pipeline
        # overlap while releasing older staging buffers — and proves the
        # staging ring's slot about to be re-filled is no longer read by
        # any in-flight fold (_StagingRing).
        import collections as _collections

        inflight: _collections.deque = _collections.deque()
        # Start one before start_block: an exhausted/empty iterator then
        # advances nothing, writes no snapshot, and finishes with the
        # RESTORED counters instead of zeros.
        i = start_block - 1
        last_mark = start_block
        try:
            for i, blk in enumerate(blocks):
                if i < start_block:  # resume: re-read, don't re-fold
                    continue
                # Span covers staging + dispatch, NOT device completion
                # (folds are async; completion shows up as the later
                # stream.stall events) — docs/OBSERVABILITY.md.
                with obs.span("stream.block", i=i,
                              staging="ring" if ring is not None else "alloc"):
                    blk = (
                        ring.stage(blk, bl, w)
                        if ring is not None
                        else normalize_round_chunk(blk, bl, w)
                    )
                    acc, blk_overflow, distinct = self._fold_block(
                        acc, jnp.asarray(blk)
                    )
                overflow = overflow + blk_overflow
                max_distinct = jnp.maximum(max_distinct, distinct)
                inflight.append(blk_overflow)
                if len(inflight) > self.STREAM_DISPATCH_DEPTH:
                    t_sync = time.perf_counter()
                    jax.block_until_ready(inflight.popleft())  # locust: noqa[R003] bounded-inflight backpressure: sync caps device queue depth, overlap stays STREAM_DISPATCH_DEPTH deep
                    sync_ms = (time.perf_counter() - t_sync) * 1e3
                    stall_ms += sync_ms
                    obs.event("stream.stall", block=i, ms=round(sync_ms, 3))
                    obs.metric_observe("stream.stall_ms", sync_ms)
                if pump is not None and (i + 1) % every == 0:
                    pump.mark(acc, i + 1, overflow, max_distinct)
                    last_mark = i + 1
            # Final-generation mark — only when folds ran past the last
            # cadence mark (a cadence-aligned corpus otherwise writes
            # its largest array twice back-to-back).
            if pump is not None and i + 1 > last_mark:
                pump.mark(acc, i + 1, overflow, max_distinct)
            if pump is not None:
                # The final generation must be durable before returning
                # (resume contract); this is the async mode's only wait.
                flush_ms = pump.finish()
        finally:
            if pump is not None:
                pump.close()
        jax.block_until_ready(acc.key_lanes)
        total_ms = (time.perf_counter() - t0) * 1e3
        obs.metric_inc("stream.blocks", max(0, i + 1 - start_block))
        stream = {
            "blocks": max(0, i + 1 - start_block),
            "staging_ring": ring is not None,
            "donate_fold": self.cfg.donate_fold,
            "backpressure_stall_ms": round(stall_ms, 3),
            "total_ms": round(total_ms, 3),
        }
        if pump is not None:
            stream["ckpt"] = dict(
                pump.stats(), every=every, final_flush_ms=round(flush_ms, 3)
            )
        return self._finish(
            acc, max_distinct, int(overflow), StageTimes(0, total_ms, 0),
            stream=stream,
        )

    def _run_stream_fused(
        self, blocks, acc, overflow, max_distinct, start_block: int,
        pump, every: int,
    ) -> RunResult:
        """run_stream's persistent-kernel tail (megakernel v2).

        Blocks stage into ``[seg_blocks * block_lines, width]`` segment
        buffers (a ring sized like _StagingRing when
        cfg.stream_staging_ring) and each FULL segment folds in ONE
        ``_fold_segment`` dispatch — the kernel table stays VMEM-resident
        across the whole segment, so the per-block acc->settle->acc HBM
        round-trip and table flush amortize by ``seg_blocks``.  The
        trailing partial segment zero-pads its unfilled blocks (zero
        lines tokenize to nothing, the _blocks padding contract), so one
        executable serves every segment.  Checkpoint marks land at
        segment boundaries — which ARE block boundaries — once ``every``
        blocks have elapsed since the last mark, and resume re-forms
        segments from the restored block cursor: the fold is a pure
        function of the line multiset, so the regrouped resume stays
        byte-identical (tests/test_fused_fold.py crash-resume pin).
        Backpressure/stall accounting mirror run_stream at segment
        granularity.
        """
        import collections as _collections

        from locust_tpu.parallel.shuffle import normalize_round_chunk

        bl, w = self.cfg.block_lines, self.cfg.line_width
        seg = self._fused_stream_seg
        n_slots = self.STREAM_DISPATCH_DEPTH + 1
        bufs = (
            [np.zeros((seg * bl, w), np.uint8) for _ in range(n_slots)]
            if self.cfg.stream_staging_ring
            else None
        )
        state = {
            "acc": acc, "overflow": overflow,
            "max_distinct": max_distinct, "slot": 0, "segments": 0,
            "stall_ms": 0.0, "last_mark": start_block,
        }
        flush_ms = 0.0
        inflight: _collections.deque = _collections.deque()
        t0 = time.perf_counter()

        def next_buf() -> np.ndarray:
            if bufs is None:
                return np.zeros((seg * bl, w), np.uint8)
            buf = bufs[state["slot"]]
            state["slot"] = (state["slot"] + 1) % n_slots
            return buf

        def dispatch(buf: np.ndarray, n_filled: int, seg_end: int) -> None:
            if n_filled < seg and bufs is not None:
                buf[n_filled * bl:, :] = 0  # ring reuse: clear stale tail
            with obs.span("stream.block", i=seg_end - 1,
                          staging="ring" if bufs is not None else "alloc",
                          seg_blocks=n_filled):
                acc2, blk_overflow, distinct = self._fold_segment(
                    state["acc"], jnp.asarray(buf)
                )
            state["acc"] = acc2
            state["overflow"] = state["overflow"] + blk_overflow
            state["max_distinct"] = jnp.maximum(
                state["max_distinct"], distinct
            )
            state["segments"] += 1
            inflight.append(blk_overflow)
            if len(inflight) > self.STREAM_DISPATCH_DEPTH:
                t_sync = time.perf_counter()
                jax.block_until_ready(inflight.popleft())  # locust: noqa[R003] bounded-inflight backpressure: sync caps device queue depth, overlap stays STREAM_DISPATCH_DEPTH deep
                sync_ms = (time.perf_counter() - t_sync) * 1e3
                state["stall_ms"] += sync_ms
                obs.event("stream.stall", block=seg_end - 1,
                          ms=round(sync_ms, 3))
                obs.metric_observe("stream.stall_ms", sync_ms)
            if pump is not None and seg_end - state["last_mark"] >= every:
                pump.mark(state["acc"], seg_end, state["overflow"],
                          state["max_distinct"])
                state["last_mark"] = seg_end

        i = start_block - 1
        fill = 0
        cur: np.ndarray | None = None
        try:
            for i, blk in enumerate(blocks):
                if i < start_block:  # resume: re-read, don't re-fold
                    continue
                if fill == 0:
                    cur = next_buf()
                normalize_round_chunk(
                    blk, bl, w, out=cur[fill * bl:(fill + 1) * bl]
                )
                fill += 1
                if fill == seg:
                    dispatch(cur, fill, i + 1)
                    fill = 0
            if fill:
                dispatch(cur, fill, i + 1)
            if pump is not None and i + 1 > state["last_mark"]:
                pump.mark(state["acc"], i + 1, state["overflow"],
                          state["max_distinct"])
            if pump is not None:
                flush_ms = pump.finish()
        finally:
            if pump is not None:
                pump.close()
        jax.block_until_ready(state["acc"].key_lanes)
        total_ms = (time.perf_counter() - t0) * 1e3
        obs.metric_inc("stream.blocks", max(0, i + 1 - start_block))
        stream = {
            "blocks": max(0, i + 1 - start_block),
            "staging_ring": bufs is not None,
            "donate_fold": self.cfg.donate_fold,
            "backpressure_stall_ms": round(state["stall_ms"], 3),
            "total_ms": round(total_ms, 3),
            "fused": {
                "formulation": "stream",
                "seg_blocks": seg,
                "segments": state["segments"],
                "interpret": jax.default_backend() != "tpu",
            },
        }
        if pump is not None:
            stream["ckpt"] = dict(
                pump.stats(), every=every, final_flush_ms=round(flush_ms, 3)
            )
        return self._finish(
            state["acc"], state["max_distinct"], int(state["overflow"]),
            StageTimes(0, total_ms, 0), stream=stream, fused_kernel="stream",
        )

    def _load_state(self, state_path: str, fingerprint: str, acc: KVBatch):
        """Restore (start_block, overflow, max_distinct, acc) from a
        matching snapshot; pass-through fresh state otherwise.  Shared by
        ``run_stream`` and ``run_checkpointed``."""
        start_block = 0
        overflow = jnp.int32(0)
        max_distinct = jnp.int32(0)
        if os.path.exists(state_path):
            try:
                with np.load(state_path) as z:
                    if str(z["fingerprint"]) == fingerprint:
                        start_block = int(z["next_block"])
                        overflow = jnp.int32(int(z["overflow"]))
                        max_distinct = jnp.int32(int(z["max_distinct"]))
                        # jnp.array(copy=True), NOT asarray: on CPU, jax
                        # zero-copy aliases host numpy buffers, and the
                        # first resumed fold DONATES the accumulator —
                        # donating numpy-owned memory corrupts the heap
                        # (XLA frees what it never allocated; observed as
                        # nondeterministic segfaults under pytest).  The
                        # copy puts the restored table in jax-owned
                        # memory the donation machinery may reclaim.
                        acc = KVBatch(
                            key_lanes=jnp.array(z["key_lanes"], copy=True),
                            values=jnp.array(z["values"], copy=True),
                            valid=jnp.array(z["valid"], copy=True),
                        )
                        logger.info(
                            "resuming from checkpoint at block %d (%s)",
                            start_block,
                            state_path,
                        )
                    else:
                        logger.warning(
                            "checkpoint at %s belongs to a different run; "
                            "starting fresh",
                            state_path,
                        )
            except Exception as e:  # noqa: BLE001 - truncated/garbled npz
                # A corrupt snapshot costs a clean restart, never a crash
                # and never wrong counts (ISSUE 1; the mesh engines'
                # ShardedCheckpoint additionally falls back to a previous
                # generation — this single-file engine just starts over).
                logger.warning(
                    "checkpoint at %s is unreadable (%s: %s); starting "
                    "fresh", state_path, type(e).__name__, e,
                )
                start_block = 0
                overflow = jnp.int32(0)
                max_distinct = jnp.int32(0)
        return start_block, overflow, max_distinct, acc

    @staticmethod
    def _save_state(state_path, acc, next_block, overflow, max_distinct,
                    fingerprint) -> None:
        """One atomically-replaced npz: table + cursor + counters can never
        tear apart.  The tmp name keeps the .npz suffix (np.savez appends
        it otherwise).  Runs on the fold loop (sync mode) or the
        background writer (cfg.async_checkpoint) — the np.asarray
        conversions wait on the marked fold's readiness and copy
        device->host, then finalize_snapshot publishes atomically
        (io.ckpt_write / io.checkpoint chaos sites)."""
        tmp = state_path + ".tmp.npz"
        np.savez_compressed(
            tmp,
            key_lanes=np.asarray(acc.key_lanes),
            values=np.asarray(acc.values),
            valid=np.asarray(acc.valid),
            next_block=np.int64(next_block),
            overflow=np.asarray(overflow),
            max_distinct=np.asarray(max_distinct),
            fingerprint=np.str_(fingerprint),
        )
        finalize_snapshot(tmp, state_path, generation=int(next_block))

    # ---------------------------------------------------------- checkpointing

    def run_checkpointed(
        self,
        rows: np.ndarray,
        checkpoint_dir: str,
        every: int = 8,
        breaker=None,
    ) -> RunResult:
        """Block-granular fold with crash-resumable snapshots.

        The reference's entire persistence story is "map wrote /tmp/out.txt,
        re-run reduce from it" (main.cu:428-441, SURVEY.md §5).  This is the
        TPU-native upgrade: every ``every`` blocks, the bounded accumulator
        table, the block cursor and the running counters land in ONE npz
        replaced atomically — table and cursor can never tear apart, so a
        crash at any instant resumes without double-folding blocks.  A
        re-run with a different corpus/config fingerprint starts fresh.
        Snapshots are a few MB (table_size rows) regardless of corpus size.

        ``breaker`` (a ``backend.CircuitBreaker``) adds mid-job failover:
        every primary dispatch runs through ``backend.guarded_dispatch``
        (the ``backend.dispatch`` chaos site); a failed dispatch reloads
        the last durable checkpoint — the donated accumulator may have
        died with the dispatch, the snapshot cannot — and once the
        breaker is OPEN the fold continues on the CPU fallback device
        from that checkpoint.  When the half-open probe readmits the
        primary, the fold migrates back.  Fallback-side failures are
        REAL failures and re-raise (there is no second fallback).
        """
        from locust_tpu.io.serde import fingerprint_corpus

        if every < 1:
            raise ValueError(f"checkpoint every must be >= 1, got {every}")
        os.makedirs(checkpoint_dir, exist_ok=True)
        state_path = os.path.join(checkpoint_dir, "state.npz")
        fingerprint = fingerprint_corpus(
            rows,
            cfg=repr(self.cfg),
            combine=self.combine,
            map_fn=getattr(self.map_fn, "__name__", str(self.map_fn)),
        )

        # Counters stay DEVICE scalars between snapshots: no per-block host
        # sync, so dispatches pipeline exactly like run().
        start_block, overflow, max_distinct, acc = self._load_state(
            state_path,
            fingerprint,
            KVBatch.empty(self._table_size, self.cfg.key_lanes),
        )
        pump = _CheckpointPump(
            self, state_path, fingerprint, self.cfg.async_checkpoint
        )

        t0 = time.perf_counter()
        on_cpu = False
        cpu_dev = None  # resolved once at first failover, then cached
        try:
            while True:
                dispatch_died = None
                i = start_block - 1
                last_mark = start_block
                for i, blk in enumerate(self._blocks(rows)):
                    if i < start_block:
                        continue
                    if breaker is not None:
                        acc, on_cpu, cpu_dev = self._breaker_place(
                            breaker, acc, on_cpu, cpu_dev
                        )
                    # Only the FOLD dispatch is failover-retryable —
                    # checkpoint-writer errors re-raised by pump.mark
                    # must stay loud (retrying them from the same
                    # checkpoint would loop forever).
                    try:
                        if on_cpu:
                            blk = jax.device_put(blk, cpu_dev)
                            # _fold_block_fallback, not _fold_block: the
                            # fused kernel must not re-trace for the
                            # fallback device (stock_fold docstring).
                            acc, blk_overflow, distinct = (
                                self._fold_block_fallback(acc, blk)
                            )
                        elif breaker is not None:
                            acc, blk_overflow, distinct = (
                                backend_mod.guarded_dispatch(
                                    breaker,
                                    partial(self._fold_block, acc, blk),
                                    block=i, backend="primary",
                                )
                            )
                        else:
                            acc, blk_overflow, distinct = self._fold_block(
                                acc, blk
                            )
                    except Exception as e:
                        if breaker is None or on_cpu:
                            raise  # no breaker, or the FALLBACK died: real
                        dispatch_died = e
                        break
                    overflow = overflow + blk_overflow
                    max_distinct = jnp.maximum(max_distinct, distinct)
                    if (i + 1) % every == 0:
                        pump.mark(acc, i + 1, overflow, max_distinct)
                        last_mark = i + 1
                if dispatch_died is None:
                    if i + 1 > last_mark:  # skip cadence-aligned double write
                        pump.mark(acc, i + 1, overflow, max_distinct)
                    pump.finish()  # final generation durable before returning
                    break
                if (
                    breaker.state() != "closed"
                    and backend_mod.cpu_fallback_device() is None
                ):
                    # Tripped breaker and nothing to fail over TO (a
                    # TPU-only jax process): going around again would
                    # busy-loop re-reading the same snapshot against a
                    # dead primary forever — re-raise loud instead (the
                    # checkpoint survives for a later resume).  state(),
                    # not allow(): allow() would consume the half-open
                    # probe token this path never dispatches.
                    raise dispatch_died
                # Primary dispatch died (guarded_dispatch recorded the
                # failure).  The donated accumulator is suspect; the last
                # checkpoint is not: flush any pending async write
                # best-effort, reload, and go around — on the primary
                # while the breaker still allows it, on the CPU fallback
                # once it is open.
                try:
                    pump.finish()
                except Exception as e:  # noqa: BLE001 - reload decides
                    logger.warning(
                        "checkpoint flush during failover failed (%s); "
                        "resuming from the last durable generation", e,
                    )
                start_block, overflow, max_distinct, acc = self._load_state(
                    state_path, fingerprint,
                    KVBatch.empty(self._table_size, self.cfg.key_lanes),
                )
        finally:
            pump.close()
        total_ms = (time.perf_counter() - t0) * 1e3
        return self._finish(
            acc, max_distinct, int(overflow), StageTimes(0, total_ms, 0)
        )

    def _breaker_place(self, breaker, acc, on_cpu: bool, cpu_dev):
        """Move the fold accumulator to whichever device the breaker
        currently makes eligible; returns (acc, on_cpu, cpu_dev).  The
        device is resolved once and cached by the caller (the hot loop
        must not pay a local_devices lookup per block); the migration
        copies through ``jax.device_put`` (never a donation), so the
        reloaded-from-checkpoint table stays jax-owned either way."""
        primary_ok = breaker.allow()
        if primary_ok and on_cpu:
            # Half-open probe (or a closed breaker after recovery): the
            # next dispatch tries the primary again from the live state.
            acc = jax.device_put(acc)
            obs.event("backend.failover", direction="cpu_to_primary")
            return acc, False, cpu_dev
        if not primary_ok and not on_cpu:
            if cpu_dev is None:
                cpu_dev = backend_mod.cpu_fallback_device()
            if cpu_dev is None:
                return acc, False, None  # nothing to fail over to
            acc = jax.device_put(acc, cpu_dev)
            obs.event("backend.failover", direction="primary_to_cpu")
            logger.warning(
                "backend breaker open: fold continuing on the CPU "
                "fallback from the last checkpoint"
            )
            return acc, True, cpu_dev
        return acc, on_cpu, cpu_dev

    def _finish(self, acc, num_segments, overflow, times,
                stream: dict | None = None,
                fused_kernel: str | None = None) -> RunResult:
        if os.environ.get("LOCUST_DEBUG_CHECKS"):
            # Opt-in invariant sweep on the result table (the sanitizer
            # analog, SURVEY.md §5): valid-prefix layout + NUL-padded keys.
            # hasht-family tables are slot-ordered (valid entries
            # scattered by hash, not compacted to a prefix) — the layout
            # invariant is a property of the SORT folds, not of
            # correctness.
            from locust_tpu.config import HASHT_FAMILY
            from locust_tpu.utils.checks import validate_batch

            validate_batch(
                acc, expect_compact=self.cfg.sort_mode not in HASHT_FAMILY
            )
        num = int(num_segments)
        truncated = num > acc.size
        if truncated:
            logger.warning(
                "distinct keys (%d) exceeded table capacity (%d); tail "
                "dropped — raise table_size (the default capacity is "
                "min(65536, max(one block's emits, 4096)))",
                num,
                acc.size,
            )
        if overflow and self.cfg.warn_on_overflow:
            # Reference: "WARN: Exceeded emit limit" printf (main.cu:141-144).
            logger.warning(
                "WARN: Exceeded emit limit — %d tokens beyond %d-per-line cap dropped",
                overflow,
                self.cfg.emits_per_line,
            )
        if fused_kernel is None and self._fused_kernel_on:
            fused_kernel = "batch"
        return RunResult(
            table=acc,
            num_segments=min(num, acc.size),
            overflow_tokens=overflow,
            truncated=truncated,
            times=times,
            combine=self.combine,
            stream=stream,
            fused_kernel=fused_kernel,
            fused_demoted=self._fused_demoted,
        )
