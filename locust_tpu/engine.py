"""The MapReduce engine: pluggable map/combine over blocked byte tensors.

Single-device orchestration — the TPU-native analog of the reference driver's
map -> process -> reduce sequencing (reference MapReduce/src/main.cu:397-473),
with two deliberate departures:

* **No global line cap.**  The reference truncates input at
  MAX_LINES_FILE_READ=5800 lines (main.cu:18).  Here the corpus streams
  through fixed-shape blocks of ``cfg.block_lines`` and partial result tables
  merge associatively (sort + segment-reduce is a monoid fold), so input
  size is unbounded (SURVEY.md §5 "long-context").
* **Pluggable semantics.**  ``map_fn(lines, cfg) -> (KVBatch, overflow)`` and
  a monoid ``combine`` replace the hardcoded WordCount map()/count-reduce
  (main.cu:136-153, 210-238); WordCount, PageRank and inverted-index are
  instances (locust_tpu/apps/).

Every stage is jit-compiled once per config; ``run`` uses one fused program
per block, ``timed_run`` dispatches stages separately to reproduce the
reference's per-stage Map/Process/Reduce timing report (main.cu:405-468).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from locust_tpu.config import DEFAULT_CONFIG, EngineConfig
from locust_tpu.core import bytes_ops
from locust_tpu.core.kv import KVBatch
from locust_tpu.ops.map_stage import wordcount_map
from locust_tpu.ops.process_stage import sort_and_compact
from locust_tpu.ops.reduce_stage import segment_reduce

logger = logging.getLogger("locust_tpu")

MapFn = Callable[[jax.Array, EngineConfig], tuple[KVBatch, jax.Array]]


@dataclasses.dataclass
class StageTimes:
    """Per-stage wall-clock, the reference's timing report (main.cu:405-468)."""

    map_ms: float = 0.0
    process_ms: float = 0.0
    reduce_ms: float = 0.0

    @property
    def total_ms(self) -> float:
        return self.map_ms + self.process_ms + self.reduce_ms


@dataclasses.dataclass
class RunResult:
    table: KVBatch            # key-sorted unique keys + combined values
    num_segments: int         # distinct keys found (<= table capacity)
    overflow_tokens: int      # emits dropped by the per-line cap
    truncated: bool           # True if distinct keys exceeded table capacity
    times: StageTimes

    def to_host_pairs(self) -> list[tuple[bytes, int]]:
        return self.table.to_host_pairs()


class MapReduceEngine:
    """Blocked map/shuffle/reduce on one device (mesh version in parallel/)."""

    def __init__(
        self,
        cfg: EngineConfig = DEFAULT_CONFIG,
        map_fn: MapFn = wordcount_map,
        combine: str = "sum",
    ):
        self.cfg = cfg
        self.map_fn = map_fn
        self.combine = combine

        def block_step(lines: jax.Array):
            kv, overflow = map_fn(lines, cfg)
            kv = sort_and_compact(kv)
            return segment_reduce(kv, combine), overflow

        def merge(acc: KVBatch, blk: KVBatch, max_distinct: jax.Array):
            """Associative table merge, tracking the running max distinct-key
            count so a capacity truncation in ANY merge is reported, not just
            the last one."""
            both = KVBatch(
                key_lanes=jnp.concatenate([acc.key_lanes, blk.key_lanes]),
                values=jnp.concatenate([acc.values, blk.values]),
                valid=jnp.concatenate([acc.valid, blk.valid]),
            )
            merged = segment_reduce(sort_and_compact(both), self.combine)
            new_max = jnp.maximum(max_distinct, merged.num_valid())
            cap = acc.size
            head = KVBatch(
                key_lanes=merged.key_lanes[:cap],
                values=merged.values[:cap],
                valid=merged.valid[:cap],
            )
            return head, new_max

        def scan_blocks(blocks: jax.Array):
            """Whole-corpus pipeline in ONE dispatch: fold blocks with lax.scan.

            One device dispatch per corpus instead of per block — essential
            when dispatch latency is high (remote TPU tunnels) and the XLA-
            idiomatic way to loop without data-dependent Python control flow.
            """

            def body(carry, blk):
                acc, overflow_acc, max_distinct = carry
                table, overflow = block_step(blk)
                merged, max_distinct = merge(acc, table, max_distinct)
                return (merged, overflow_acc + overflow, max_distinct), None

            init = (
                KVBatch.empty(cfg.emits_per_block, cfg.key_lanes),
                jnp.int32(0),
                jnp.int32(0),
            )
            (acc, overflow, num), _ = jax.lax.scan(body, init, blocks)
            return acc, overflow, num

        self._block_step = jax.jit(block_step)
        self._merge = jax.jit(merge)
        self._scan_blocks = jax.jit(scan_blocks)
        # Split stages for the timed path only.
        self._map = jax.jit(lambda lines: map_fn(lines, cfg))
        self._process = jax.jit(sort_and_compact)
        self._reduce = jax.jit(partial(segment_reduce, combine=combine))

    # ---------------------------------------------------------------- ingest

    def rows_from_lines(self, lines: Sequence[bytes]) -> np.ndarray:
        return bytes_ops.strings_to_rows(list(lines), self.cfg.line_width)

    def _blocks(self, rows: np.ndarray):
        """Yield fixed-shape [block_lines, line_width] blocks, zero-padded."""
        bl = self.cfg.block_lines
        n = rows.shape[0]
        for i in range(0, max(n, 1), bl):
            blk = rows[i : i + bl]
            if blk.shape[0] < bl:
                pad = np.zeros((bl - blk.shape[0], rows.shape[1]), np.uint8)
                blk = np.concatenate([blk, pad]) if blk.size else pad
            yield jnp.asarray(blk)

    # ------------------------------------------------------------------- run

    def run(self, rows: np.ndarray) -> RunResult:
        """Fused per-block pipeline + associative cross-block merge.

        Keeps overflow/distinct counters on device across the loop — no
        host sync until the end, so block dispatches pipeline asynchronously.
        """
        acc = None
        overflow = None
        max_distinct = jnp.int32(0)
        t0 = time.perf_counter()
        for blk in self._blocks(rows):
            table, blk_overflow = self._block_step(blk)
            overflow = blk_overflow if overflow is None else overflow + blk_overflow
            if acc is None:
                acc, max_distinct = table, table.num_valid()
            else:
                acc, max_distinct = self._merge(acc, table, max_distinct)
        jax.block_until_ready(acc.key_lanes)
        total_ms = (time.perf_counter() - t0) * 1e3
        return self._finish(acc, max_distinct, int(overflow), StageTimes(0, total_ms, 0))

    def run_fused(self, rows: np.ndarray) -> RunResult:
        """Whole-corpus run as a single device dispatch (lax.scan over blocks).

        Preferred for throughput: amortizes dispatch latency and lets XLA
        pipeline block processing.  Compiles once per number-of-blocks; pad
        the corpus externally to a fixed block count to reuse the executable.
        """
        bl, w = self.cfg.block_lines, self.cfg.line_width
        n = rows.shape[0]
        nblocks = max(1, -(-n // bl))
        padded = np.zeros((nblocks * bl, w), dtype=np.uint8)
        padded[:n] = rows[:, :w]
        blocks = jnp.asarray(padded.reshape(nblocks, bl, w))
        t0 = time.perf_counter()
        acc, overflow, num = self._scan_blocks(blocks)
        jax.block_until_ready(acc.key_lanes)
        total_ms = (time.perf_counter() - t0) * 1e3
        return self._finish(
            acc, num, int(overflow), StageTimes(0, total_ms, 0)
        )

    def timed_run(self, rows: np.ndarray) -> RunResult:
        """Per-stage timing parity with the reference's report (main.cu:405-468).

        Stage boundaries force ``block_until_ready``, so this is slower than
        ``run``; use it for the stage report, ``run`` for throughput.
        """
        acc = None
        overflow = 0
        max_distinct = jnp.int32(0)
        times = StageTimes()
        for blk in self._blocks(rows):
            t0 = time.perf_counter()
            kv, blk_overflow = self._map(blk)
            jax.block_until_ready(kv.key_lanes)
            t1 = time.perf_counter()
            kv = self._process(kv)
            jax.block_until_ready(kv.key_lanes)
            t2 = time.perf_counter()
            table = self._reduce(kv)
            jax.block_until_ready(table.key_lanes)
            t3 = time.perf_counter()
            times.map_ms += (t1 - t0) * 1e3
            times.process_ms += (t2 - t1) * 1e3
            times.reduce_ms += (t3 - t2) * 1e3
            overflow += int(blk_overflow)
            if acc is None:
                acc, max_distinct = table, table.num_valid()
            else:
                acc, max_distinct = self._merge(acc, table, max_distinct)
        jax.block_until_ready(acc.key_lanes)
        return self._finish(acc, max_distinct, overflow, times)

    def run_lines(self, lines: Sequence[bytes]) -> RunResult:
        return self.run(self.rows_from_lines(lines))

    def _finish(self, acc, num_segments, overflow, times) -> RunResult:
        num = int(num_segments)
        truncated = num > acc.size
        if truncated:
            logger.warning(
                "distinct keys (%d) exceeded table capacity (%d); tail dropped",
                num,
                acc.size,
            )
        if overflow and self.cfg.warn_on_overflow:
            # Reference: "WARN: Exceeded emit limit" printf (main.cu:141-144).
            logger.warning(
                "WARN: Exceeded emit limit — %d tokens beyond %d-per-line cap dropped",
                overflow,
                self.cfg.emits_per_line,
            )
        return RunResult(
            table=acc,
            num_segments=min(num, acc.size),
            overflow_tokens=overflow,
            truncated=truncated,
            times=times,
        )
