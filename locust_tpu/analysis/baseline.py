"""Checked-in baseline: fingerprints of findings that predate the gate.

The shipped baseline is (near-)empty — the PR that introduced the gate
fixed what it found — but the mechanism matters: a NEW rule can land with
its legacy findings baselined instead of blocking, then the baseline
burns down.  Format (JSON, sorted, diff-friendly)::

    {"version": 1,
     "findings": {"<fingerprint>": "<rule> <path>:<line> <message>"}}

The value is a human-readable label only; the KEY (content-addressed
fingerprint, core._fingerprint) is what matching uses, so baselines
survive line-number drift but not edits to the flagged line itself.
"""

from __future__ import annotations

import json
import os


def load_baseline(path: str | None) -> set[str]:
    if not path or not os.path.isfile(path):
        return set()
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable analysis baseline {path!r}: {e}")
    if not isinstance(obj, dict) or not isinstance(obj.get("findings"), dict):
        raise ValueError(
            f"analysis baseline {path!r} must be "
            '{"version": 1, "findings": {...}}'
        )
    return set(obj["findings"])


def write_baseline(path: str, findings) -> int:
    """Write every (non-suppressed) finding as the new baseline; returns
    the count.  An empty finding list writes an empty baseline — the
    healthy steady state."""
    payload = {
        "version": 1,
        "findings": {
            f.fingerprint: f"{f.rule_id} {f.path}:{f.line} {f.message}"
            for f in findings
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(payload["findings"])
