"""R002/R003 — purity and host-sync discipline around traced code.

R002 (traced-purity): functions handed to ``jax.jit`` / ``shard_map`` /
``compat_shard_map`` / ``pallas_call`` (as calls or decorators) run under
tracing: side effects execute ONCE at trace time and then silently never
again — or, for Pallas interpret mode on CPU, can crash the XLA compiler
outright (the bitonic-under-mesh segfault guard, CLAUDE.md).  Flags
``print``, ``time.*``, ``random.*``/``np.random.*``, ``open``/socket
I/O, and global/nonlocal writes inside the traced function's subtree.
``jax.debug.print`` / ``pl.debug_print`` are the sanctioned forms and
stay silent.

R003 (host-sync-in-hot-loop): ``block_until_ready``/``jax.device_get``
inside a ``for``/``while`` loop in library code serializes the device
pipeline per iteration — the exact anti-pattern the fused ``lax.scan``
engine exists to avoid.  Deliberate syncs (stage-timing boundaries,
bounded-inflight backpressure) carry a noqa with their argument.
"""

from __future__ import annotations

import ast
import re

from locust_tpu.analysis.core import Finding, Rule, call_name

_TRACER_RE = re.compile(
    r"(^|\.)(jit|shard_map|compat_shard_map|pallas_call)$"
)
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "socket.", "os.environ")
_SANCTIONED = ("debug.print", "debug_print")


def _traced_fn_exprs(tree: ast.Module):
    """Expressions positioned as the to-be-traced function: first arg of
    tracer calls (unwrapping nested tracer calls, e.g.
    ``jax.jit(compat_shard_map(body, ...))``), plus decorated defs."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _TRACER_RE.search(call_name(node)):
            if node.args:
                arg = node.args[0]
                while (
                    isinstance(arg, ast.Call)
                    and _TRACER_RE.search(call_name(arg))
                    and arg.args
                ):
                    arg = arg.args[0]
                yield arg
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                # Unparse the WHOLE decorator: for the dominant
                # @functools.partial(jax.jit, static_argnames=...) idiom
                # the tracer name lives in the call's ARGUMENTS, which
                # call_name() would drop.
                src = ast.unparse(dec)
                if _TRACER_RE.search(src) or re.search(
                    r"\b(jit|shard_map|pallas_call)\b", src
                ):
                    yield node
                    break


def _impurities(fn: ast.AST):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = call_name(node)
            if callee == "print":
                yield node, "print() call"
            elif callee == "open":
                yield node, "file I/O (open)"
            elif any(callee.startswith(p) for p in _IMPURE_PREFIXES):
                if not callee.endswith(_SANCTIONED):
                    yield node, f"host side effect ({callee})"
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            yield node, f"{kind} write ({', '.join(node.names)})"


class TracedPurityRule(Rule):
    rule_id = "R002"
    title = "impure statement inside jit/shard_map/pallas-traced code"

    def check_file(self, f, root):
        by_name: dict[str, list] = {}
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        seen: set[int] = set()
        for expr in _traced_fn_exprs(f.tree):
            if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                fns = [expr]
            elif isinstance(expr, ast.Name):
                fns = by_name.get(expr.id, [])
            elif isinstance(expr, ast.Attribute):
                fns = by_name.get(expr.attr, [])
            else:
                fns = []
            for fn in fns:
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                name = getattr(fn, "name", "<lambda>")
                for node, what in _impurities(fn):
                    yield Finding(
                        self.rule_id,
                        f.rel,
                        node.lineno,
                        node.col_offset,
                        f"{what} inside traced function '{name}': runs "
                        "once at trace time, then never again (or crashes "
                        "the compiler in Pallas interpret mode) — hoist it "
                        "out of the traced body",
                    )


_SYNC_ATTRS = {"block_until_ready"}
_SYNC_CALLS = {"jax.block_until_ready", "jax.device_get"}


class HostSyncInLoopRule(Rule):
    rule_id = "R003"
    title = "host sync inside a hot loop"

    def check_file(self, f, root):
        # Library code only: tests and scripts sync at will.
        top = f.rel.split("/", 1)[0]
        if top != "locust_tpu":
            return
        if "import jax" not in f.text:
            return
        seen: set[int] = set()  # nested loops: report each sync once
        for loop in ast.walk(f.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                callee = call_name(node)
                is_sync = callee in _SYNC_CALLS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                )
                if is_sync:
                    yield Finding(
                        self.rule_id,
                        f.rel,
                        node.lineno,
                        node.col_offset,
                        f"host sync ({callee}) inside a loop serializes "
                        "the device pipeline per iteration — batch the "
                        "loop into one dispatch (lax.scan) or noqa with "
                        "the backpressure/timing argument",
                    )
