"""R002/R003/R010 — purity, sync and donation discipline around traced code.

R002 (traced-purity, interprocedural): functions handed to ``jax.jit`` /
``shard_map`` / ``compat_shard_map`` / ``pallas_call`` (as calls or
decorators) run under tracing: side effects execute ONCE at trace time
and then silently never again — or, for Pallas interpret mode on CPU,
can crash the XLA compiler outright (the bitonic-under-mesh segfault
guard, CLAUDE.md).  Flags ``print``, ``time.*``, ``random.*``/
``np.random.*``, ``open``/socket I/O, and global/nonlocal writes in the
traced function AND in every callee the summaries call graph can
attribute, across modules — a traced body outsourcing its side effect to
an imported helper is the same bug one hop away.  ``jax.debug.print`` /
``pl.debug_print`` are the sanctioned forms and stay silent.

R003 (host-sync-in-hot-loop): ``block_until_ready``/``jax.device_get``
inside a ``for``/``while`` loop in library code serializes the device
pipeline per iteration — the exact anti-pattern the fused ``lax.scan``
engine exists to avoid.  Deliberate syncs (stage-timing boundaries,
bounded-inflight backpressure) carry a noqa with their argument.

R010 (donated-buffer hygiene): ``donate_argnums`` lets XLA alias a
buffer input->output — which means XLA eventually FREES it.  Donating a
jax array that zero-copy aliases host numpy memory (``jnp.asarray`` of
an npz/numpy value, on CPU) corrupts the heap: XLA frees memory it never
allocated — the PR 5 resume incident (engine._load_state), observed as
nondeterministic segfaults under pytest.  Reading a name after passing
it to a donating call in the same scope is the softer cousin: the
buffer's contents are undefined.  Both are flagged; ``jnp.array(...,
copy=True)`` (owned memory) and rebinding the result are the sanctioned
shapes.  Aliased values are tracked through same-scope assignments and
one call-graph hop (a helper that RETURNS an aliased table taints its
callers' bindings — the exact _load_state -> run_stream shape).
"""

from __future__ import annotations

import ast

from locust_tpu.analysis.core import Finding, Rule, call_name, unparse


class TracedPurityRule(Rule):
    rule_id = "R002"
    title = "impure statement inside jit/shard_map/pallas-traced code"

    _MAX_DEPTH = 6

    def check_program(self, program):
        emitted: set[tuple] = set()
        for mod in program.modules.values():
            visited: set[int] = set()
            for expr in mod.traced_exprs:
                for fn in self._resolve_traced(program, mod, expr):
                    yield from self._visit(
                        program, fn, root=fn.name, chain=(fn.name,),
                        depth=0, visited=visited, emitted=emitted,
                    )

    def _resolve_traced(self, program, mod, expr):
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return mod.by_name.get(expr.name, [])
        if isinstance(expr, ast.Lambda):
            return [mod.lambda_summary(expr)]
        if isinstance(expr, ast.Name):
            return program.graph.resolve(mod, expr.id, include_nested=True)
        if isinstance(expr, ast.Attribute):
            return program.graph.resolve(
                mod, unparse(expr), include_nested=True
            )
        return []

    def _visit(self, program, fn, root, chain, depth, visited, emitted):
        if id(fn.node) in visited:
            return
        # A depth-truncated visit is not recorded — it never explored
        # its callees, and marking it would blind a later shallower path
        # (emitted dedups re-reported impurities; depth bounds recursion).
        if depth < self._MAX_DEPTH:
            visited.add(id(fn.node))
        for line, col, what in fn.impurities:
            key = (fn.rel, line, what)
            if key in emitted:
                continue
            emitted.add(key)
            if len(chain) == 1:
                where = f"inside traced function '{fn.name}'"
            else:
                where = (
                    f"inside '{fn.name}', reached from traced function "
                    f"'{root}' via {' -> '.join(chain)}"
                )
            yield Finding(
                self.rule_id, fn.rel, line, col,
                f"{what} {where}: runs once at trace time, then never "
                "again (or crashes the compiler in Pallas interpret "
                "mode) — hoist it out of the traced body",
            )
        if depth >= self._MAX_DEPTH:
            return
        for c in fn.calls:
            for callee in program.graph.resolve(fn.module, c.callee):
                if callee.node is fn.node:
                    continue
                yield from self._visit(
                    program, callee, root, chain + (callee.name,),
                    depth + 1, visited, emitted,
                )


_SYNC_ATTRS = {"block_until_ready"}
_SYNC_CALLS = {"jax.block_until_ready", "jax.device_get"}


class HostSyncInLoopRule(Rule):
    rule_id = "R003"
    title = "host sync inside a hot loop"

    def check_file(self, f, root):
        # Library code only: tests and scripts sync at will.
        top = f.rel.split("/", 1)[0]
        if top != "locust_tpu":
            return
        if "import jax" not in f.text:
            return
        seen: set[int] = set()  # nested loops: report each sync once
        for loop in ast.walk(f.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                seen.add(id(node))
                callee = call_name(node)
                is_sync = callee in _SYNC_CALLS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                )
                if is_sync:
                    yield Finding(
                        self.rule_id,
                        f.rel,
                        node.lineno,
                        node.col_offset,
                        f"host sync ({callee}) inside a loop serializes "
                        "the device pipeline per iteration — batch the "
                        "loop into one dispatch (lax.scan) or noqa with "
                        "the backpressure/timing argument",
                    )


def _is_jnp_asarray(callee: str) -> bool:
    return callee in ("jnp.asarray", "jax.numpy.asarray") or (
        callee.endswith(".asarray") and callee.startswith(("jnp.", "jax."))
    )


def _is_uncopied_jnp_array(call: ast.Call, callee: str) -> bool:
    """``jnp.array(x, copy=False)`` — explicit no-copy is asarray in a
    trenchcoat.  Bare ``jnp.array`` copies by default and is safe."""
    if callee not in ("jnp.array", "jax.numpy.array"):
        return False
    for kw in call.keywords:
        if (
            kw.arg == "copy"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
        ):
            return True
    return False


def _body_stmts(node: ast.AST):
    """Child statements of a compound statement, in source order, not
    descending into nested function/class scopes."""
    for field in ("body", "orelse", "finalbody"):
        for stmt in getattr(node, field, []) or []:
            yield stmt
    for handler in getattr(node, "handlers", []) or []:
        yield from handler.body


def _own_exprs(stmt: ast.stmt):
    """Nodes belonging to THIS statement only: headers of compound
    statements (the ``with`` items, the ``if`` test, the ``for`` iter)
    but never child statements — those are walked in their own turn —
    and never nested function scopes."""
    stack = [
        child for child in ast.iter_child_nodes(stmt)
        if not isinstance(child, ast.stmt)
    ]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(
            child for child in ast.iter_child_nodes(node)
            if not isinstance(child, ast.stmt)
        )


def _calls_in_stmt(stmt: ast.stmt):
    """Every Call in the statement's own expressions."""
    for node in _own_exprs(stmt):
        if isinstance(node, ast.Call):
            yield node


def _names_read(stmt: ast.stmt):
    for node in _own_exprs(stmt):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            yield node


class DonationHygieneRule(Rule):
    rule_id = "R010"
    title = "donated buffer aliases host memory or is read after donation"

    _MAX_DEPTH = 3

    def check_program(self, program):
        self._ret_memo: dict[int, set[int]] = {}
        self._in_progress: set[int] = set()
        for mod in program.modules.values():
            if not mod.donating:
                continue
            for fn in mod.functions:
                yield from self._scan_fn(program, mod, fn)

    # ------------------------------------------------------ alias tracking

    def _aliasing(self, program, mod, expr, aliased: set[str],
                  depth: int = 0) -> bool:
        """Does this expression (possibly) alias host numpy memory?"""
        if isinstance(expr, ast.Name):
            return expr.id in aliased
        if isinstance(expr, ast.Tuple):
            return any(
                self._aliasing(program, mod, e, aliased, depth)
                for e in expr.elts
            )
        if not isinstance(expr, ast.Call):
            return False
        callee = call_name(expr)
        if _is_jnp_asarray(callee) or _is_uncopied_jnp_array(expr, callee):
            return True
        args = list(expr.args) + [kw.value for kw in expr.keywords]
        # Constructor convention (KVBatch(...)): a capitalized bare name
        # wrapping an aliasing argument carries the alias.
        leaf = callee.split(".")[-1]
        if leaf[:1].isupper() and any(
            self._aliasing(program, mod, a, aliased, depth) for a in args
        ):
            return True
        if depth < self._MAX_DEPTH:
            for target in program.graph.resolve(mod, callee):
                if -1 in self._returns_aliased(program, target, depth + 1):
                    return True
        return False

    def _returns_aliased(self, program, fn, depth: int) -> set[int]:
        """Tuple indices (or -1 = the whole value) of ``fn``'s returns
        that may alias host numpy memory."""
        key = id(fn.node)
        if key in self._ret_memo:
            return self._ret_memo[key]
        if key in self._in_progress or depth > self._MAX_DEPTH:
            return set()
        self._in_progress.add(key)
        indices: set[int] = set()
        aliased: set[str] = set()

        def walk(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.Assign):
                    self._track_assign(program, fn.module, stmt, aliased,
                                       depth)
                elif isinstance(stmt, ast.Return) and stmt.value is not None:
                    v = stmt.value
                    if isinstance(v, ast.Tuple):
                        for i, elt in enumerate(v.elts):
                            if self._aliasing(program, fn.module, elt,
                                              aliased, depth):
                                indices.add(i)
                    elif self._aliasing(program, fn.module, v, aliased,
                                        depth):
                        indices.add(-1)
                walk(list(_body_stmts(stmt)))

        body = fn.node.body
        walk(body if isinstance(body, list) else [])
        self._in_progress.discard(key)
        self._ret_memo[key] = indices
        return indices

    def _track_assign(self, program, mod, stmt: ast.Assign,
                      aliased: set[str], depth: int = 0) -> None:
        """Propagate aliasing through one assignment (rebinding kills)."""
        value = stmt.value
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                if self._aliasing(program, mod, value, aliased, depth):
                    aliased.add(t.id)
                else:
                    aliased.discard(t.id)
            elif isinstance(t, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in t.elts
            ):
                taint: set[int] = set()
                if isinstance(value, ast.Tuple):
                    taint = {
                        i for i, e in enumerate(value.elts)
                        if self._aliasing(program, mod, e, aliased, depth)
                    }
                elif isinstance(value, ast.Call) and depth < self._MAX_DEPTH:
                    for target in program.graph.resolve(
                        mod, call_name(value)
                    ):
                        taint |= self._returns_aliased(
                            program, target, depth + 1
                        )
                for i, e in enumerate(t.elts):
                    if i in taint or -1 in taint:
                        aliased.add(e.id)
                    else:
                        aliased.discard(e.id)

    # ---------------------------------------------------------- the checks

    def _scan_fn(self, program, mod, fn):
        donating = mod.donating
        aliased: set[str] = set()
        donated: dict[str, tuple[str, int]] = {}  # name -> (callee, line)
        findings: list[Finding] = []

        def donate_positions(call: ast.Call) -> tuple[str, tuple[int, ...]]:
            callee = call_name(call)
            parts = callee.split(".")
            leaf = parts[-1]
            if leaf in donating and (
                len(parts) == 1 or parts[0] in ("self", "cls")
                or len(parts) == 2
            ):
                return callee, donating[leaf]
            return callee, ()

        def process(stmt: ast.stmt) -> None:
            # Reads of previously-donated names come first: the donation
            # mark only ever applies to LATER statements.
            for name in _names_read(stmt):
                hit = donated.get(name.id)
                if hit is not None:
                    callee, dline = hit
                    donated.pop(name.id)  # one finding per donation
                    findings.append(Finding(
                        self.rule_id, fn.rel, name.lineno, name.col_offset,
                        f"{name.id!r} is read after being donated to "
                        f"{callee}(...) on line {dline} — a donated "
                        "buffer's contents are undefined after the call; "
                        "use the call's result or copy before donating",
                    ))
            for call in _calls_in_stmt(stmt):
                callee, positions = donate_positions(call)
                for pos in positions:
                    if pos >= len(call.args):
                        continue
                    arg = call.args[pos]
                    if self._aliasing(program, mod, arg, aliased):
                        findings.append(Finding(
                            self.rule_id, fn.rel, call.lineno,
                            call.col_offset,
                            f"argument {pos} of donating call "
                            f"{callee}(...) may alias host numpy memory "
                            "(jnp.asarray keeps a zero-copy view on CPU) "
                            "— XLA frees donated buffers it then never "
                            "allocated, corrupting the heap (the PR 5 "
                            "resume incident); materialize with "
                            "jnp.array(..., copy=True) first",
                        ))
                    if isinstance(arg, ast.Name):
                        donated[arg.id] = (callee, call.lineno)
            if isinstance(stmt, ast.Assign):
                self._track_assign(program, mod, stmt, aliased)
                for t in stmt.targets:
                    for e in (
                        t.elts if isinstance(t, ast.Tuple) else [t]
                    ):
                        if isinstance(e, ast.Name):
                            donated.pop(e.id, None)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(stmt.target, ast.Name):
                    aliased.discard(stmt.target.id)
                    donated.pop(stmt.target.id, None)

        def walk(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # nested scopes get their own scan
                process(stmt)
                walk(list(_body_stmts(stmt)))

        body = fn.node.body
        walk(body if isinstance(body, list) else [])
        return findings
