"""R006/R007/R008 — environment and repo hygiene rules.

R006 (subprocess env hygiene): the host injects a remote-TPU PJRT plugin
into EVERY python via a PYTHONPATH sitecustomize; jax initializes all
plugins even under ``JAX_PLATFORMS=cpu``, so a child python spawned from
tests/ or scripts/ without an explicit environment can hang on a wedged
tunnel (CLAUDE.md — this class of hang has cost hours).  A spawn of
python must pass ``env=`` built with BOTH ``JAX_PLATFORMS`` and
``PYTHONPATH`` pinned.  Heuristics: the command must visibly be python
(``sys.executable`` or a ``python`` literal in the argv expression, or a
local variable whose enclosing scope mentions ``sys.executable``); an
``env=`` forwarded from an enclosing function's parameter is trusted
(the wrapper's callers own the pinning).

R007 (bench contract): ``bench.py`` must print EXACTLY one JSON line on
stdout no matter what (the driver parses it).  Statically pinned as:
exactly one ``print(json.dumps(...))`` site, and every other ``print``
either goes to ``file=sys.stderr`` or is a flushed relay of an
already-captured JSON line (``flush=True``).

R008 (tracked artifact hygiene): ``__pycache__``/``*.pyc``/pytest caches
must never be tracked, and .gitignore must keep ignoring them.  Uses
``git ls-files`` (plain git, not python — R006 does not apply) and skips
silently when git is unavailable.
"""

from __future__ import annotations

import ast
import os
import re
import subprocess

from locust_tpu.analysis.core import Finding, Rule, call_name, unparse

_SPAWN_ATTRS = {"run", "Popen", "call", "check_call", "check_output"}
_REQUIRED_ENV = ("JAX_PLATFORMS", "PYTHONPATH")


def _enclosing_function(tree: ast.Module, node: ast.AST):
    """Innermost def containing ``node`` (None = module level)."""
    best = None
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (
                fn.lineno <= node.lineno
                and node.lineno <= max(
                    getattr(fn, "end_lineno", fn.lineno), fn.lineno
                )
                and (best is None or fn.lineno > best.lineno)
            ):
                best = fn
    return best


def _mentions_env_keys(scope: ast.AST) -> list[str]:
    """Which required env keys the scope visibly pins: string constants
    ("JAX_PLATFORMS": ...) or keyword names (env.update(PYTHONPATH=...))."""
    found = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for key in _REQUIRED_ENV:
                if node.value == key:
                    found.add(key)
        elif isinstance(node, ast.keyword) and node.arg in _REQUIRED_ENV:
            found.add(node.arg)
    return [k for k in _REQUIRED_ENV if k in found]


def _is_python_spawn(call: ast.Call, scope: ast.AST) -> bool:
    if not call.args:
        return False
    argv = call.args[0]
    src = unparse(argv)
    if "sys.executable" in src or "python" in src.lower():
        return True
    if isinstance(argv, ast.Name) and scope is not None:
        return "sys.executable" in unparse(scope)
    return False


class SubprocessEnvRule(Rule):
    rule_id = "R006"
    title = "python child spawned without a pinned environment"

    def check_file(self, f, root):
        top = f.rel.split("/", 1)[0]
        if top not in ("tests", "scripts"):
            return
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node)
            leaf = callee.split(".")[-1]
            is_spawn = leaf == "Popen" or (
                leaf in _SPAWN_ATTRS and "subprocess" in callee
            )
            if not is_spawn:
                continue
            scope = _enclosing_function(f.tree, node) or f.tree
            if not _is_python_spawn(node, scope):
                continue
            env_kw = next(
                (kw for kw in node.keywords if kw.arg == "env"), None
            )
            if env_kw is None:
                yield Finding(
                    self.rule_id, f.rel, node.lineno, node.col_offset,
                    f"{callee} spawns python with the inherited "
                    "environment — the axon sitecustomize can hang the "
                    "child on a wedged TPU tunnel; pass env= pinning "
                    "JAX_PLATFORMS and PYTHONPATH (CLAUDE.md)",
                )
                continue
            # env forwarded from a wrapper's parameter: callers own it.
            if isinstance(env_kw.value, ast.Name) and isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                params = {
                    a.arg
                    for a in (
                        scope.args.args
                        + scope.args.kwonlyargs
                        + scope.args.posonlyargs
                    )
                }
                if env_kw.value.id in params:
                    continue
            pinned = _mentions_env_keys(scope)
            missing = [k for k in _REQUIRED_ENV if k not in pinned]
            if missing:
                yield Finding(
                    self.rule_id, f.rel, node.lineno, node.col_offset,
                    f"{callee} spawns python with env= that never pins "
                    f"{' or '.join(missing)} in this scope — pin both so "
                    "the axon sitecustomize cannot hang the child "
                    "(CLAUDE.md)",
                )


class BenchContractRule(Rule):
    rule_id = "R007"
    title = "bench.py one-JSON-line contract"

    def check_file(self, f, root):
        if f.rel != "bench.py":
            return
        json_prints = []
        for node in ast.walk(f.tree):
            if not (
                isinstance(node, ast.Call) and call_name(node) == "print"
            ):
                continue
            kwargs = {kw.arg: kw for kw in node.keywords if kw.arg}
            is_json_dump = bool(node.args) and (
                isinstance(node.args[0], ast.Call)
                and call_name(node.args[0]).endswith("json.dumps")
            )
            if is_json_dump:
                json_prints.append(node)
                continue
            to_stderr = "file" in kwargs and unparse(
                kwargs["file"].value
            ).endswith("stderr")
            # A relay must print a CAPTURED value (a name or a subscript
            # like json_lines[-1]) — a flushed literal/f-string is still
            # stdout noise that breaks the one-line parse.
            flushed_relay = (
                "flush" in kwargs
                and isinstance(kwargs["flush"].value, ast.Constant)
                and kwargs["flush"].value.value is True
                and "file" not in kwargs
                and len(node.args) == 1
                and isinstance(node.args[0], (ast.Name, ast.Subscript))
            )
            if not to_stderr and not flushed_relay:
                yield Finding(
                    self.rule_id, f.rel, node.lineno, node.col_offset,
                    "print to stdout outside the one-JSON-line contract — "
                    "route diagnostics to file=sys.stderr (the driver "
                    "parses stdout as a single JSON line)",
                )
        if len(json_prints) != 1:
            where = json_prints[1] if len(json_prints) > 1 else None
            yield Finding(
                self.rule_id, f.rel,
                where.lineno if where is not None else 1,
                where.col_offset if where is not None else 0,
                f"bench.py must have exactly ONE print(json.dumps(...)) "
                f"emission site, found {len(json_prints)} — the driver "
                "contract is one JSON line from one place (emit())",
            )


_TRACKED_JUNK = re.compile(
    r"(^|/)__pycache__(/|$)|\.py[co]$|(^|/)\.pytest_cache(/|$)"
    r"|(^|/)\.hypothesis(/|$)|(^|/)\.DS_Store$"
)
_IGNORE_WANTED = ("__pycache__/", "*.pyc")


class TrackedArtifactRule(Rule):
    rule_id = "R008"
    title = "build/cache artifacts tracked by git"

    def check_project(self, files, root):
        if not os.path.isdir(os.path.join(root, ".git")):
            return  # fixture trees / exported sources: nothing to check
        try:
            out = subprocess.run(
                ["git", "-C", root, "ls-files"],
                capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            return
        if out.returncode != 0:
            return
        for tracked in out.stdout.splitlines():
            if _TRACKED_JUNK.search(tracked):
                yield Finding(
                    self.rule_id, tracked, 1, 0,
                    "build/cache artifact is tracked by git — "
                    "`git rm -r --cached` it (and keep .gitignore "
                    "covering it)",
                )
        gi_path = os.path.join(root, ".gitignore")
        try:
            with open(gi_path, encoding="utf-8") as fh:
                entries = {ln.strip() for ln in fh}
        except OSError:
            entries = set()
        for want in _IGNORE_WANTED:
            if want not in entries:
                yield Finding(
                    self.rule_id, ".gitignore", 1, 0,
                    f".gitignore is missing {want!r} — cache artifacts "
                    "will show up as untracked noise and eventually get "
                    "committed",
                )
