"""Cross-module call resolution over the phase-1 summaries.

The graph is deliberately narrow — a call is followed only when its
callee can be ATTRIBUTED, in the same false-negative-leaning spirit as
every other rule (docs/ANALYSIS.md):

  * a bare name resolves to same-module top-level defs first, then
    through the module's imports (``from locust_tpu.x import fn``);
  * ``self.meth`` / ``cls.meth`` resolves to same-module defs named
    ``meth`` (classes are not modeled — the module is the unit);
  * ``mod.fn`` resolves through ``import``/``from ... import mod`` when
    ``mod`` names an analyzed module; ``Cls.meth`` resolves when ``Cls``
    was imported from an analyzed module;
  * anything else — ``obj.meth`` on an arbitrary receiver, calls through
    parameters or containers — is UNRESOLVED and silently skipped.

Only top-level functions and methods are returned: a def nested inside a
function is either covered by its parent's whole-subtree summary or
unreachable by name from outside.
"""

from __future__ import annotations

import ast


def module_imports(
    tree: ast.Module, self_name: str, is_package: bool = False
) -> dict[str, str]:
    """Local binding -> dotted target for every import in the module:
    ``import a.b as c`` -> {"c": "a.b"}; ``import a.b`` -> {"a": "a"};
    ``from a.b import x as y`` -> {"y": "a.b.x"}.  Relative imports are
    anchored on the module's own package — for a package ``__init__``
    (``is_package``) level 1 is the package ITSELF, not its parent."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    out[root] = root
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = self_name.split(".")
                drop = node.level - (1 if is_package else 0)
                anchor = parts[: max(0, len(parts) - drop)]
                base = ".".join(anchor + ([base] if base else []))
            for a in node.names:
                if a.name == "*":
                    continue
                target = f"{base}.{a.name}" if base else a.name
                out[a.asname or a.name] = target
    return out


class CallGraph:
    def __init__(self, program):
        self.program = program

    def resolve(self, mod, callee: str, include_nested: bool = False):
        """Callee source text -> list of FunctionSummary targets (empty
        when unresolvable).  ``include_nested`` widens same-module bare /
        ``self.``-resolution to nested defs — thread ENTRY points may be
        nested (``Thread(target=attempt)``); followed CALLS never are."""
        parts = callee.split(".")
        table = mod.by_name if include_nested else mod.top_by_name
        if len(parts) == 1:
            hits = table.get(parts[0])
            if hits:
                return hits
            return self._imported(mod, parts[0])
        if parts[0] in ("self", "cls") and len(parts) == 2:
            return table.get(parts[1], [])
        # Dotted path: substitute the head through the imports, then try
        # "<module>.fn" and "<module>.Cls.meth" splits.
        head = mod.imports.get(parts[0], parts[0])
        fparts = head.split(".") + parts[1:]
        for cut in (1, 2):
            if len(fparts) <= cut:
                break
            target_mod = self.program.modules.get(".".join(fparts[:-cut]))
            if target_mod is not None:
                return target_mod.top_by_name.get(fparts[-1], [])
        return []

    def _imported(self, mod, name: str):
        target = mod.imports.get(name)
        if not target:
            return []
        owner, _, attr = target.rpartition(".")
        target_mod = self.program.modules.get(owner)
        if target_mod is None:
            return []
        return target_mod.top_by_name.get(attr, [])
