"""CLI: ``python -m locust_tpu.analysis [--json] [--sarif FILE]
[--changed[=REF]] [--rule R00x] [paths...]``.

Exit codes: 0 = no new findings (baselined findings may remain and are
reported as such), 1 = new findings, 2 = usage/config error.  The gate
test (tests/test_analysis.py) runs the same engine in-process; this CLI
is the dev / CI surface.  ``--changed`` scopes the REPORTED findings to
lines touched vs a git ref (the fast pre-commit loop; analysis itself is
always whole-program — the call graph does not shrink with the diff);
``--sarif`` additionally writes the findings as a SARIF 2.1.0 log for
CI/PR annotation.
"""

from __future__ import annotations

import argparse
import sys

from locust_tpu.analysis import config as cfg
from locust_tpu.analysis import run_analysis
from locust_tpu.analysis.baseline import write_baseline
from locust_tpu.analysis.core import changed_lines, emit_json, scope_to_changed
from locust_tpu.analysis.registry import all_rules
from locust_tpu.analysis.sarif import write_sarif


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m locust_tpu.analysis",
        description="locust_tpu static invariant checker (docs/ANALYSIS.md)",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to check (default: pyproject "
                        "[tool.locust-analysis] paths)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--rule", action="append", default=None, metavar="R00x",
                   help="run only this rule (repeatable)")
    p.add_argument("--root", default=None,
                   help="repo root (default: nearest pyproject.toml)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: from pyproject)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="report only findings on lines touched vs REF "
                        "(default HEAD) — the fast pre-commit loop; "
                        "analysis still runs whole-program")
    p.add_argument("--sarif", default=None, metavar="FILE",
                   help="also write the findings as a SARIF 2.1.0 log")
    args = p.parse_args(argv)

    if args.changed is not None and args.write_baseline:
        print("error: --write-baseline must see the whole tree; drop "
              "--changed", file=sys.stderr)
        return 2

    if args.list_rules:
        for rid, rcls in sorted(all_rules().items()):
            print(f"{rid}  {rcls.title}")
        return 0

    try:
        result = run_analysis(
            paths=args.paths or None,
            root=args.root,
            rules=args.rule,
            baseline_path=args.baseline,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.changed is not None:
        import os

        root = os.path.abspath(args.root or cfg.find_root())
        try:
            result = scope_to_changed(result, changed_lines(root, args.changed))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.sarif:
        # Rule CLASSES, not bare titles: the report derives helpUri and
        # defaultConfiguration.level per rule from them.
        write_sarif(args.sarif, result, dict(all_rules()))
        print(f"sarif: findings written to {args.sarif}", file=sys.stderr)

    if args.write_baseline:
        root = args.root or cfg.find_root()
        conf = cfg.load_config(root)
        import os

        path = args.baseline or os.path.join(root, conf["baseline"])
        # R000 never enters a baseline: fix the parse error / write the
        # noqa reason instead of accepting it.  Likewise findings marked
        # non-baselineable by their rule (R016 phantom cmds: a cmd with
        # no handler is never acceptable debt) — refuse the whole write
        # loudly rather than silently burying a dead RPC.
        refused = [
            f for f in result.findings
            if f.rule_id != "R000" and not f.baselineable
        ]
        if refused:
            print(
                "error: refusing to baseline non-baselineable "
                "finding(s) — fix them instead:", file=sys.stderr,
            )
            for f in refused:
                print(f"  {f.format()}", file=sys.stderr)
            return 2
        n = write_baseline(
            path, [f for f in result.findings if f.rule_id != "R000"]
        )
        print(f"baseline: {n} finding(s) written to {path}", file=sys.stderr)
        return 0

    if args.as_json:
        print(emit_json(result))
    else:
        for f in result.findings:
            print(f.format())
        print(
            f"{result.n_files} file(s), rules {','.join(result.rules)}: "
            f"{len(result.new)} new finding(s), "
            f"{len(result.findings) - len(result.new)} baselined, "
            f"{result.suppressed} suppressed",
            file=sys.stderr,
        )
    return 1 if result.new else 0


if __name__ == "__main__":
    raise SystemExit(main())
