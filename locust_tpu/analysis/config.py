"""Analyzer configuration: repo root discovery + ``[tool.locust-analysis]``.

Python 3.10 has no ``tomllib``, so the pyproject section is read with a
deliberately narrow fallback parser: our own section only, ``key = value``
lines whose values are TOML strings/arrays-of-strings (which are also
valid Python literals).  ``tomllib`` is used when available.
"""

from __future__ import annotations

import ast
import os
import re

DEFAULTS = {
    # What the tier-1 gate sweeps.  bench.py and __graft_entry__.py are
    # top-level driver contracts; everything else is the package + its
    # scripts and tests.
    "paths": [
        "locust_tpu",
        "scripts",
        "tests",
        "bench.py",
        "__graft_entry__.py",
    ],
    "baseline": "analysis_baseline.json",
    # Where scripts/check.py archives the SARIF log of its full pass
    # (repo-relative; gitignored — an artifact, not a source of truth).
    "sarif_artifact": "artifacts/analysis.sarif",
}

_SECTION = "tool.locust-analysis"


def find_root(start: str | None = None) -> str:
    """Nearest ancestor holding pyproject.toml; falls back to the repo
    this package is installed from (two levels above this file)."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isfile(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _parse_section_fallback(text: str) -> dict:
    out: dict = {}
    in_section = False
    key = None
    pending = ""  # accumulates a multi-line array value
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and pending == "":
            in_section = line == f"[{_SECTION}]"
            continue
        if not in_section:
            continue
        if pending:
            pending += " " + line
        else:
            m = re.match(r"([A-Za-z0-9_-]+)\s*=\s*(.+)$", line)
            if not m:
                continue
            key, pending = m.group(1), m.group(2).strip()
        # A value is complete when its brackets balance (handles the
        # standard TOML multi-line array; strings here never contain
        # brackets — ours are paths and filenames).
        if pending.count("[") > pending.count("]"):
            continue
        try:
            out[key] = ast.literal_eval(pending)
        except (ValueError, SyntaxError):
            pass  # a value shape we don't own; keep the default
        pending = ""
    return out


def load_config(root: str) -> dict:
    conf = dict(DEFAULTS)
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.isfile(pyproject):
        return conf
    with open(pyproject, encoding="utf-8") as f:
        text = f.read()
    try:
        import tomllib  # py >= 3.11

        section = tomllib.loads(text).get("tool", {}).get(
            "locust-analysis", {}
        )
    except ImportError:
        section = _parse_section_fallback(text)
    if isinstance(section.get("paths"), list):
        conf["paths"] = [str(p) for p in section["paths"]]
    if isinstance(section.get("baseline"), str):
        conf["baseline"] = section["baseline"]
    if isinstance(section.get("sarif_artifact"), str):
        conf["sarif_artifact"] = section["sarif_artifact"]
    return conf
